"""SecAgg v2 session state machines — the protocol minus the transport.

The cross-silo managers own the message flow; these sessions own the
per-round mask state, the reveal bookkeeping and every privacy guard:

- **key advertisement** rides the existing client→server STATUS
  messages (one X25519 public key per client process, 32 bytes);
- the **round header** rides the existing broadcast (roster + pk
  directory + the shared codec spec) — no extra round-trips on the
  happy path;
- **dropout recovery** rides the quorum-close path: when a round closes
  with missing clients the server asks each survivor for the pair-seeds
  it shared with the evicted peers (ONE extra round-trip per recovery
  wave), never anything that could unmask a received upload.

Client-side guards (the client is the last line of defense against a
lying server):

- reveals cover pair-seeds with EVICTED peers only — a client never
  reveals anything that unmasks its own upload ("its own self-mask"),
  and refuses requests that name itself as evicted;
- the cumulative evicted set per round is bounded by what the quorum
  could legitimately lose (``roster − quorum``): a server claiming more
  dropouts than the round could survive is refused;
- one reveal per (round, peer), ever — recovery waves may extend the
  evicted set but can never re-target a peer under a different story.

Threat model (full write-up in ``docs/privacy.md``): honest-but-curious
server, honest clients. Each received upload stays masked by at least
one pair shared with another survivor, so the recovery floor is two
survivors; a malicious server that fabricates evictions for clients
whose uploads it RECEIVED is outside this model (that is what the
Bonawitz double-mask + Shamir construction in ``cross_silo/secagg``
defends against, at 8 bytes/element and two extra protocol legs).
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from fedml_tpu.cross_silo.message_define import MyMessage
from fedml_tpu.privacy.secagg import masking
from fedml_tpu.privacy.secagg.codec import (
    SecAggInt8Codec,
    masked_encode,
    unmask_finalize,
)

logger = logging.getLogger(__name__)

Pytree = Any

__all__ = [
    "SecAggClientSession",
    "SecAggMessage",
    "SecAggServerSession",
    "secagg_enabled",
]


class SecAggMessage(MyMessage):
    """Protocol extensions riding the standard cross-silo flows."""

    # server → survivors: the round closed on quorum; reveal the pair
    # seeds you shared with the evicted peers
    MSG_TYPE_S2C_SECAGG_RECOVER = "MSG_TYPE_S2C_SECAGG_RECOVER"
    # survivor → server: {evicted_rank: per-round pair seed}
    MSG_TYPE_C2S_SECAGG_REVEAL = "MSG_TYPE_C2S_SECAGG_REVEAL"

    MSG_ARG_KEY_SECAGG = "secagg"            # round header on broadcasts
    MSG_ARG_KEY_SECAGG_PK = "secagg_pk"      # key advert on status msgs
    MSG_ARG_KEY_SECAGG_EVICTED = "secagg_evicted"
    MSG_ARG_KEY_SECAGG_REVEAL = "secagg_reveal"


def secagg_enabled(args: Any) -> bool:
    """``secagg: int8`` (the only masked domain so far) turns it on."""
    mode = str(getattr(args, "secagg", "") or "").lower()
    if mode in ("", "0", "false", "none", "off"):
        return False
    if mode not in ("int8", "1", "true"):
        raise ValueError(
            f"unknown secagg mode {mode!r} (supported: int8)")
    return True


def _counter(name: str, **labels):
    from fedml_tpu.telemetry import get_registry

    return get_registry().counter(name, labels=labels or None)


def _secagg_event(event: str, **fields) -> None:
    """Land one protocol event everywhere the doctor looks."""
    from fedml_tpu.telemetry import flight_recorder
    from fedml_tpu.telemetry.health import log_health_event

    try:
        log_health_event({"kind": "secagg_event", "event": event, **fields})
    except Exception:  # pragma: no cover - observability must not kill
        logger.exception("secagg event logging failed")
    flight_recorder.record("secagg_event", event=event, **fields)


def record_phase(phase: str, round_idx: int, **fields) -> None:
    """Flight-recorder phase marker. ``individual_plaintext`` is the
    acceptance invariant: no phase of a SecAgg round ever materializes
    an individual client's unmasked delta on the server."""
    from fedml_tpu.telemetry import flight_recorder

    flight_recorder.record("secagg_phase", phase=phase, round=int(round_idx),
                           masked=True, individual_plaintext=False, **fields)


def _validate_pk(pk: Any) -> bytes:
    if not isinstance(pk, (bytes, bytearray)) or len(pk) != 32:
        raise ValueError(
            f"secagg public key must be 32 bytes, got "
            f"{type(pk).__name__}[{len(pk) if hasattr(pk, '__len__') else '?'}]")
    return bytes(pk)


def _codec_from_spec(spec: str) -> SecAggInt8Codec:
    from fedml_tpu.compression import get_codec

    codec = get_codec(spec)
    if not isinstance(codec, SecAggInt8Codec):
        raise ValueError(f"not a secagg codec spec: {spec!r}")
    return codec


class SecAggClientSession:
    """One client's masking state across the run (keys persist; mask and
    reveal state is per round)."""

    def __init__(self, rank: int, args: Any):
        from fedml_tpu.privacy.secagg.keys import kx_agree, kx_keygen
        from fedml_tpu.resilience import ResilienceConfig

        self.rank = int(rank)
        self._kx_agree = kx_agree
        self.sk, self.pk = kx_keygen()
        self._secret_cache: Dict[Tuple[int, bytes], int] = {}
        self.quorum_frac = ResilienceConfig(args).round_quorum
        # round state
        self.round_idx: Optional[int] = None
        self.roster: List[int] = []
        self.codec: Optional[SecAggInt8Codec] = None
        self._peer_seeds: Dict[int, int] = {}
        self._residual: Optional[Pytree] = None
        self._revealed: Dict[int, set] = {}  # round -> peers revealed

    @classmethod
    def from_args(cls, rank: int, args: Any) -> Optional["SecAggClientSession"]:
        return cls(rank, args) if secagg_enabled(args) else None

    # -- round setup --------------------------------------------------------
    def begin_round(self, header: Any, round_idx: int) -> None:
        """Apply the broadcast's secagg header. Malformed headers raise
        ``ValueError`` — a client never trains against a roster it could
        not parse."""
        if not isinstance(header, dict):
            raise ValueError("malformed secagg header (not a dict)")
        try:
            roster = [int(c) for c in header["roster"]]
            pks = {int(c): _validate_pk(pk)
                   for c, pk in dict(header["pks"]).items()}
            spec = str(header["spec"])
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed secagg header: {e}") from None
        if self.rank not in roster:
            raise ValueError(
                f"secagg header roster {roster} does not include this "
                f"client (rank {self.rank})")
        if len(set(roster)) != len(roster):
            raise ValueError("secagg header roster has duplicates")
        codec = _codec_from_spec(spec)
        if codec.bound != masking.client_bound(len(roster), codec.mod_bits):
            raise ValueError(
                f"secagg spec bound {codec.bound} does not match a "
                f"{len(roster)}-client roster")
        self.round_idx = int(round_idx)
        self.roster = roster
        self.codec = codec
        self._peer_seeds = {}
        for j in roster:
            if j == self.rank:
                continue
            if j not in pks:
                raise ValueError(f"secagg header missing pk for peer {j}")
            ck = (j, pks[j])
            if ck not in self._secret_cache:
                self._secret_cache[ck] = self._kx_agree(self.sk, pks[j])
            self._peer_seeds[j] = masking.pair_round_seed(
                self._secret_cache[ck], self.round_idx)
        # prune reveal bookkeeping for long runs
        for r in [r for r in self._revealed if r < self.round_idx - 4]:
            del self._revealed[r]

    @property
    def active(self) -> bool:
        return self.codec is not None

    # -- upload path ---------------------------------------------------------
    def encode_update(self, delta: Pytree, key):
        """Mask + encode one round's delta (EF residual lives here)."""
        net_mask = masking.net_mask_leaves(
            self.rank, self._peer_seeds,
            _meta_of(delta), self.codec.mod_bits)
        sa = {"round": int(self.round_idx), "rank": self.rank,
              "roster": list(self.roster)}
        ct, self._residual = masked_encode(
            delta, net_mask, self.codec, key,
            residual=self._residual, sa=sa)
        _counter("secagg/masked_uploads").inc()
        return ct

    def reset_identity(self) -> None:
        """Rejoin / round-gap: drop the EF residual — pre-gap
        quantization error must not leak into the new identity."""
        self._residual = None

    # -- dropout recovery -----------------------------------------------------
    def reveal_for(self, evicted: Sequence[Any],
                   round_idx: Any) -> Optional[Dict[int, int]]:
        """Pair seeds shared with ``evicted``, or None when the request
        fails a privacy guard (refusals are counted and logged — an
        honest server only sees them when it is lying)."""
        refuse = _counter("secagg/reveal_refusals")
        try:
            evicted = sorted({int(e) for e in evicted})
            round_idx = int(round_idx)
        except (TypeError, ValueError):
            refuse.inc()
            logger.error("secagg: refusing malformed reveal request")
            return None
        if round_idx != self.round_idx or not self.roster:
            refuse.inc()
            logger.error(
                "secagg: refusing reveal for round %s (client is at %s)",
                round_idx, self.round_idx)
            return None
        if self.rank in evicted:
            # revealing "for ourselves" would hand over half of our own
            # mask while the server may well hold our upload
            refuse.inc()
            logger.error("secagg: refusing reveal request naming THIS "
                         "client as evicted")
            return None
        if not set(evicted) <= set(self.roster):
            refuse.inc()
            logger.error("secagg: refusing reveal for peers outside the "
                         "round roster")
            return None
        from fedml_tpu.resilience import quorum_size

        already = self._revealed.setdefault(self.round_idx, set())
        # the bound is the TIGHTER of the quorum (a round that lost more
        # could never have closed) and the 2-survivor privacy floor (at
        # one survivor, revealing every pair seed would unmask this
        # client's own upload — even a legally-low quorum never excuses
        # that)
        max_evictable = len(self.roster) - max(2, quorum_size(
            len(self.roster), self.quorum_frac))
        if len(already | set(evicted)) > max_evictable:
            refuse.inc()
            logger.error(
                "secagg: refusing reveal — %d claimed dropouts exceed the "
                "quorum/privacy-compatible maximum %d",
                len(already | set(evicted)), max_evictable)
            return None
        out = {j: self._peer_seeds[j] for j in evicted
               if j in self._peer_seeds}
        already.update(out)
        _counter("secagg/seeds_revealed").inc(len(out))
        return out


def _meta_of(tree: Pytree):
    from fedml_tpu.compression.codecs import _tree_meta
    import jax

    return _tree_meta(jax.tree.leaves(tree))


class SecAggServerSession:
    """Server-side roster/reveal bookkeeping + the unmask aggregation.

    The server never holds mask seeds of its own: it learns exactly the
    revealed (survivor, evicted) pair seeds, applies them to the masked
    SUM, and materializes only the (optionally DP-noised) aggregate.
    """

    def __init__(self, args: Any, client_num: int):
        self.client_num = int(client_num)
        self.clip = float(getattr(args, "secagg_clip", 0.1))
        self.mod_bits = int(getattr(args, "secagg_mod_bits", 8))
        self.recovery_rounds = int(getattr(
            args, "secagg_recovery_rounds",
            getattr(args, "round_deadline_extensions", 3)))
        self.pks: Dict[int, bytes] = {}
        self._lock = threading.Lock()
        # round state
        self.round_idx: Optional[int] = None
        self.roster: List[int] = []
        self.codec: Optional[SecAggInt8Codec] = None
        # recovery state
        self.recovering = False
        self.survivors: List[int] = []
        self.evicted: List[int] = []
        self.reveals: Dict[int, Dict[int, int]] = {}
        self.recovery_waves = 0

    @classmethod
    def from_args(cls, args: Any,
                  client_num: int) -> Optional["SecAggServerSession"]:
        return cls(args, client_num) if secagg_enabled(args) else None

    # -- key advertisement ----------------------------------------------------
    def note_pk(self, client_id: int, pk: Any) -> None:
        """Store a client's advertised key. A changed key is a restarted
        client — replace it (its next roster uses the new key)."""
        self.pks[int(client_id)] = _validate_pk(pk)

    # -- round lifecycle --------------------------------------------------------
    def begin_round(self, round_idx: int, cohort: Sequence[int]) -> dict:
        """Open a masked round; returns the broadcast header."""
        from fedml_tpu.compression import get_codec

        cohort = [int(c) for c in cohort]
        missing = [c for c in cohort if c not in self.pks]
        if missing:
            raise RuntimeError(
                f"secagg round {round_idx} cannot open: no key "
                f"advertisement from clients {missing}")
        bound = masking.client_bound(len(cohort), self.mod_bits)
        spec = (f"{SecAggInt8Codec.name}@{self.clip:g}/{bound}/"
                f"{self.mod_bits}")
        with self._lock:
            self.round_idx = int(round_idx)
            self.roster = cohort
            self.codec = get_codec(spec)
            self.recovering = False
            self.survivors = []
            self.evicted = []
            self.reveals = {}
            self.recovery_waves = 0
        _counter("secagg/rounds").inc()
        record_phase("collect", round_idx, roster=cohort)
        return {"v": 1, "spec": spec, "roster": cohort,
                "pks": {int(c): self.pks[c] for c in cohort},
                "round": int(round_idx)}

    def validate_upload(self, sender: int, ct: Any) -> None:
        """Reject masked uploads whose metadata lies — wrong codec,
        foreign round, spoofed rank, roster mismatch. ``ValueError``
        only (the caller drops + counts, never aggregates)."""
        from fedml_tpu.compression import CompressedTree

        if not isinstance(ct, CompressedTree) or (
                ct.codec != SecAggInt8Codec.name):
            raise ValueError(
                f"secagg round expected a masked upload, got "
                f"{type(ct).__name__}")
        sa = ct.sa
        if not isinstance(sa, dict):
            raise ValueError("masked upload missing its sa header")
        try:
            rank = int(sa["rank"])
            rnd = int(sa["round"])
            roster = [int(c) for c in sa["roster"]]
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"malformed masked upload header: {e}") from None
        if rank != int(sender):
            raise ValueError(
                f"masked upload claims rank {rank} but came from {sender}")
        if rnd != self.round_idx or roster != self.roster:
            raise ValueError(
                f"masked upload for round {rnd}/roster {roster} does not "
                f"match the open round {self.round_idx}/{self.roster}")

    # -- dropout recovery -------------------------------------------------------
    def begin_recovery(self, survivors: Sequence[int],
                       evicted: Sequence[int]) -> List[int]:
        """Start (or extend) recovery; returns the survivors to ask."""
        with self._lock:
            if not self.recovering:
                self.recovering = True
                self.survivors = [int(s) for s in survivors]
                self.evicted = sorted(int(e) for e in evicted)
                self.reveals = {}
            else:
                newly = sorted(set(int(e) for e in evicted)
                               - set(self.evicted))
                self.evicted = sorted(set(self.evicted)
                                      | set(int(e) for e in evicted))
                self.survivors = [s for s in self.survivors
                                  if s not in self.evicted]
                for s in list(self.reveals):
                    if s in self.evicted:
                        del self.reveals[s]
                logger.warning("secagg recovery extended to evicted=%s "
                               "(+%s)", self.evicted, newly)
            self.recovery_waves += 1
            _counter("secagg/recoveries").inc()
            record_phase("recover", self.round_idx or -1,
                         wave=self.recovery_waves, evicted=self.evicted,
                         survivors=list(self.survivors))
            _secagg_event("recovery_started", round=self.round_idx,
                          wave=self.recovery_waves,
                          evicted=list(self.evicted))
            return list(self.survivors)

    def note_reveal(self, sender: int, payload: Any,
                    round_idx: Any) -> bool:
        """Record one survivor's reveal; True once recovery is complete.
        Malformed payloads raise ``ValueError`` (counted by the caller,
        the sender is then treated as not having revealed)."""
        sender = int(sender)
        with self._lock:
            if not self.recovering or int(round_idx) != self.round_idx:
                raise ValueError(
                    f"unexpected secagg reveal for round {round_idx} "
                    f"(recovering={self.recovering} at {self.round_idx})")
            if sender not in self.survivors:
                raise ValueError(
                    f"secagg reveal from non-survivor {sender}")
            if not isinstance(payload, dict):
                raise ValueError("secagg reveal payload must be a dict")
            try:
                seeds = {int(j): int(s) for j, s in payload.items()}
            except (TypeError, ValueError):
                raise ValueError(
                    "secagg reveal payload must map int→int") from None
            if not set(seeds) <= set(self.evicted):
                raise ValueError(
                    f"secagg reveal covers non-evicted peers "
                    f"{sorted(set(seeds) - set(self.evicted))}")
            self.reveals.setdefault(sender, {}).update(seeds)
            return self._complete_locked()

    def _complete_locked(self) -> bool:
        need = set(self.evicted)
        return all(need <= set(self.reveals.get(s, {}))
                   for s in self.survivors)

    def recovery_complete(self) -> bool:
        with self._lock:
            return self.recovering and self._complete_locked()

    def pending_reveals(self) -> List[int]:
        with self._lock:
            need = set(self.evicted)
            return [s for s in self.survivors
                    if not need <= set(self.reveals.get(s, {}))]

    def recovery_adjustment(self, meta) -> Optional[List[np.ndarray]]:
        with self._lock:
            if not self.evicted:
                return None
            pairs = [(s, j, self.reveals[s][j])
                     for s in self.survivors for j in self.evicted]
        return masking.recovery_adjustment(pairs, meta, self.mod_bits)

    # -- the unmask aggregation ---------------------------------------------------
    def aggregate(self, cts: Sequence[Any], base: Pytree) -> Pytree:
        """Unmask the survivors' sum into the new global model (+ DP).

        ``cts`` are the received masked trees (any order — ``sa.rank``
        orders them canonically). The per-client trees stay masked; the
        only decoded value is the aggregate, noised in-program when
        central DP is enabled.
        """
        ordered = sorted(cts, key=lambda ct: int(ct.sa["rank"]))
        ranks = [int(ct.sa["rank"]) for ct in ordered]
        with self._lock:
            survivors = (list(self.survivors) if self.recovering
                         else list(self.roster))
        if ranks != sorted(survivors):
            raise ValueError(
                f"masked uploads {ranks} do not match the survivor set "
                f"{sorted(survivors)}")
        recovery = self.recovery_adjustment(ordered[0].meta)
        dp_sigma, dp_key = self._dp_noise_params()
        out = unmask_finalize(ordered, base, self.codec,
                              recovery=recovery, dp_sigma=dp_sigma,
                              dp_key_data=dp_key)
        record_phase("unmask", self.round_idx or -1,
                     survivors=ranks, recovered=len(self.evicted),
                     dp_noised=dp_sigma > 0)
        if self.evicted:
            _secagg_event("recovery_closed", round=self.round_idx,
                          evicted=list(self.evicted),
                          seeds=sum(len(v) for v in self.reveals.values()))
        return out

    def _dp_noise_params(self) -> Tuple[float, Optional[np.ndarray]]:
        """Central-DP noise drawn INSIDE the unmask program: σ from the
        configured gaussian mechanism, key from the accounted counter
        chain (one release per round, like ``add_global_noise``)."""
        from fedml_tpu.core.dp.fedml_differential_privacy import (
            FedMLDifferentialPrivacy,
        )

        dp = FedMLDifferentialPrivacy.get_instance()
        if not (dp.is_dp_enabled() and dp.is_global_dp_enabled()):
            return 0.0, None
        sigma = getattr(getattr(dp.frame, "mechanism", None), "sigma", None)
        if sigma is None:
            raise ValueError(
                "secagg in-program central DP supports the gaussian "
                "mechanism only (laplace has no in-program path)")
        _counter("secagg/dp_noise_rounds").inc()
        return float(sigma), dp.take_key_data(1)[0]
