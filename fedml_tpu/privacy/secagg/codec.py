"""The maskable int8-block codec + the fused unmask/finalize program.

Client side (:func:`masked_encode`): ONE jitted program runs error
feedback, clipping, shared-scale stochastic quantization and the mask
add — what leaves the device is already masked, so no unmasked
quantized update ever exists on the host, and the wire carries one
mask-domain word per element (uint8 at ``mod_bits=8`` — same bytes as
plain int8 blocks; the f32 per-leaf scales of plain int8 are replaced
by one shared scalar in the codec spec, which is how SecAgg stays
within the 1.2× wire gate). At ``mod_bits=4`` the masked words pack
two nibbles per byte inside the same program — half the masked wire,
riding the int4 transport floor — and the unmask side unpacks them as
XLA temporaries before the mod-16 sum (a packed-byte sum would carry
between nibbles and corrupt the cancellation).

Server side (:func:`unmask_finalize`): ONE jitted program sums the
masked words mod ``2^k`` (masks cancel inside the sum — this is the
dequant-fused aggregation of PR 3 transplanted to the masked domain),
subtracts the dropout-recovery adjustment, re-centers, scales to the
cohort mean, applies it to the broadcast base, and — when central DP is
live — adds the seeded Gaussian noise BEFORE anything is materialized:
the plain (pre-noise) aggregate exists only as an XLA intermediate.
``last_finalize_trace()`` exposes trace-time evidence of that for the
acceptance tests.

Shared-scale quantization: every cohort member quantizes with
``scale = clip / bound`` where ``bound = client_bound(n)`` — per-client
adaptive scales (plain int8) would multiply each mask by a different
factor and break exact cancellation. The clip doubles as the norm bound
defenses and DP accounting want; clip error is re-sent by error
feedback like any other quantization error.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.compression.codecs import (
    WIRE_VERSION_MASKED,
    Codec,
    CompressedTree,
    _is_float_meta,
    _tree_meta,
)
from fedml_tpu.privacy.secagg.masking import MOD_BITS_CHOICES

Pytree = Any

__all__ = [
    "SecAggInt8Codec",
    "WIRE_VERSION_MASKED",
    "last_finalize_trace",
    "masked_encode",
    "unmask_finalize",
]

_UINT = {4: jnp.uint8, 8: jnp.uint8, 16: jnp.uint16}


def _pack_nibbles(y, size: int):
    """[*leaf] mod-16 words → flat packed uint8 [(size+1)//2].

    Element ``2i`` rides the low nibble of byte ``i``, ``2i+1`` the
    high nibble — the same layout as the int4/nf4 wire codec."""
    flat = y.reshape(-1)
    if size % 2:
        flat = jnp.concatenate([flat, jnp.zeros((1,), flat.dtype)])
    pairs = flat.reshape(-1, 2)
    return (pairs[:, 0] | (pairs[:, 1] << 4)).astype(jnp.uint8)


def _unpack_nibbles(packed, size: int):
    """flat packed uint8 → [size] int32 words in [0, 16)."""
    lo = (packed & 0xF).astype(jnp.int32)
    hi = (packed >> 4).astype(jnp.int32)
    return jnp.stack([lo, hi], axis=-1).reshape(
        packed.shape[:-1] + (2 * packed.shape[-1],))[..., :size]

# trace-time evidence for the "plain aggregate never hits the host
# pre-noise" acceptance check: during tracing of the finalize program
# we record whether the pre-noise mean was an abstract tracer (an XLA
# intermediate) rather than a concrete host value
_FINALIZE_TRACE = {"pre_noise_traced": None, "noised_in_program": None}


def last_finalize_trace() -> dict:
    return dict(_FINALIZE_TRACE)


class SecAggInt8Codec(Codec):
    """Masked int8-block codec — registered so the wire recognizes the
    tag, but deliberately NOT a general-purpose codec:

    - :meth:`encode`/:meth:`decode` of an individual tree raise
      ``ValueError``: a masked update is meaningless (and decoding one
      is exactly the privacy violation SecAgg exists to prevent) —
      masked trees only ever resolve in aggregate via
      :func:`unmask_finalize`;
    - the generic ``fused_weighted_sum`` refuses maskable codecs for
      the same reason (float weights would scale each client's masks
      differently and silently corrupt the cancellation).
    """

    name = "secagg_int8"
    lossless = False
    broadcast_safe = False  # upload-only, like topk
    maskable = True

    def __init__(self, clip: float = 0.1, bound: int = 42,
                 mod_bits: int = 8):
        self.clip = float(clip)
        self.bound = int(bound)
        self.mod_bits = int(mod_bits)
        if not self.clip > 0:
            raise ValueError(f"secagg clip must be > 0, got {clip}")
        if self.mod_bits not in MOD_BITS_CHOICES:
            raise ValueError(
                f"secagg mod_bits must be one of {MOD_BITS_CHOICES}, "
                f"got {mod_bits}")
        if not 1 <= self.bound <= (1 << (self.mod_bits - 1)) - 1:
            raise ValueError(
                f"secagg bound {bound} not representable mod "
                f"2^{self.mod_bits}")

    @property
    def spec(self) -> str:
        return (f"{self.name}@{self.clip:g}/{self.bound}/"
                f"{self.mod_bits}")

    @property
    def scale(self) -> float:
        return self.clip / float(self.bound)

    @classmethod
    def parse_param(cls, param: str) -> Tuple[float, int, int]:
        """``clip/bound/mod_bits`` — the ``@``-suffix of the spec."""
        parts = str(param).split("/")
        if len(parts) != 3:
            raise ValueError(
                f"malformed secagg_int8 spec param {param!r} "
                "(want clip/bound/mod_bits)")
        try:
            return float(parts[0]), int(parts[1]), int(parts[2])
        except ValueError:
            raise ValueError(
                f"malformed secagg_int8 spec param {param!r}") from None

    # -- privacy guards: individual masked trees never decode -------------
    def encode(self, tree, key=None, is_delta: bool = False,
               residual=None):
        raise ValueError(
            "secagg_int8 updates are masked: use "
            "privacy.secagg.masked_encode (plain Codec.encode has no "
            "mask input)")

    def decode(self, ct: CompressedTree):
        raise ValueError(
            "refusing to decode an individual masked update — masked "
            "trees only resolve in aggregate (privacy.secagg."
            "unmask_finalize)")

    def encode_leaf(self, x, key):  # pragma: no cover - guarded above
        raise ValueError("secagg_int8 has no per-leaf encode")

    def decode_leaf(self, parts, dt, shape):
        raise ValueError(
            "refusing to decode an individual masked leaf")

    def weighted_sum_leaf(self, stacked, w, dt, shape):
        raise ValueError(
            "masked updates cannot ride the generic weighted sum — "
            "per-client weights would break mask cancellation")


def _check_float_meta(meta) -> None:
    bad = [dt for dt, _ in meta if not _is_float_meta(dt)]
    if bad:
        raise ValueError(
            "secure aggregation supports float-leaf trees only; "
            f"non-float leaves ({', '.join(sorted(set(bad)))}) would ride "
            "the wire unmasked")


from fedml_tpu.telemetry.profiling import wrap_jit as _wrap_jit


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _masked_encode_program(clip: float, bound: int, mod_bits: int, meta,
                           leaves, res_leaves, mask_leaves, key):
    """EF-compensate → clip → shared-scale stochastic quant → +mask,
    one program. Returns (masked uint words per leaf, new residual)."""
    scale = jnp.float32(clip / float(bound))
    masked, new_res = [], []
    for i, (x, r, m, (dt, sh)) in enumerate(
            zip(leaves, res_leaves, mask_leaves, meta)):
        comp = x.astype(jnp.float32) + r.astype(jnp.float32)
        xc = jnp.clip(comp, -clip, clip)
        u = jax.random.uniform(jax.random.fold_in(key, i), xc.shape)
        q = jnp.clip(jnp.floor(xc / scale + u), -bound, bound)
        q = q.astype(jnp.int32)
        # uint cast of the int32 low bits IS the mod-2^k wrap
        y = (q + m.astype(jnp.int32)) & ((1 << mod_bits) - 1)
        if mod_bits == 4:
            # the wire carries packed nibbles (two masked words per
            # byte) — the unpacked word tree exists only inside this
            # program
            size = int(np.prod(sh, dtype=np.int64)) if sh else 1
            masked.append(_pack_nibbles(y, size))
        else:
            masked.append(y.astype(_UINT[mod_bits]))
        # residual: everything the server will not see for this client
        # (clip error + quantization error), re-sent next round
        new_res.append(comp - q.astype(jnp.float32) * scale)
    return tuple(masked), tuple(new_res)


_masked_encode_program = _wrap_jit(
    "secagg/masked_encode", _masked_encode_program,
    static_argnums=(0, 1, 2, 3), multi_shape=True)


def masked_encode(delta: Pytree, net_mask: Sequence[np.ndarray],
                  codec: SecAggInt8Codec, key,
                  residual: Optional[Pytree] = None,
                  sa: Optional[dict] = None
                  ) -> Tuple[CompressedTree, Pytree]:
    """Encode one client's delta into a masked wire tree.

    ``net_mask`` is the client's folded pairwise mask
    (:func:`masking.net_mask_leaves`) over the SAME meta as ``delta``.
    Returns ``(CompressedTree, new_residual)`` — the residual is the
    caller's per-identity EF state (reset on rejoin, like every codec).
    """
    from fedml_tpu import telemetry

    leaves, treedef = jax.tree.flatten(delta)
    meta = _tree_meta(leaves)
    _check_float_meta(meta)
    if len(net_mask) != len(leaves):
        raise ValueError(
            f"net mask has {len(net_mask)} leaves for a {len(leaves)}-leaf "
            "tree")
    if residual is None:
        res_leaves = tuple(jnp.zeros_like(x, jnp.float32) for x in leaves)
    else:
        res_leaves = tuple(jax.tree.leaves(residual))
    import itertools

    counter = itertools.count()
    structure = jax.tree.unflatten(treedef, [next(counter) for _ in leaves])
    raw_nbytes = sum(
        int(np.prod(sh, dtype=np.int64)) * np.dtype("float32").itemsize
        for _, sh in meta)
    with telemetry.get_tracer().span("compress/encode", codec=codec.name,
                                     n_leaves=len(leaves)):
        masked, new_res = _masked_encode_program(
            codec.clip, codec.bound, codec.mod_bits, meta,
            tuple(leaves), res_leaves,
            tuple(jnp.asarray(m) for m in net_mask), key)
    ct = CompressedTree(codec.name, WIRE_VERSION_MASKED, True, raw_nbytes,
                        meta, structure, [[y] for y in masked], sa=sa)
    return ct, jax.tree.unflatten(treedef, new_res)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _unmask_program(clip: float, bound: int, mod_bits: int, meta,
                    with_noise: bool, stacked, recovery, base_leaves,
                    n_div, sigma, key_data):
    """masked Σ → −recovery → re-center → mean → undelta → (+DP noise),
    one program: the plain aggregate is an XLA temporary only."""
    scale = jnp.float32(clip / float(bound))
    half = 1 << (mod_bits - 1)
    key = jax.random.wrap_key_data(key_data)
    out = []
    pre_noise_traced = True
    for i, (ys, rec, base, (dt, sh)) in enumerate(
            zip(stacked, recovery, base_leaves, meta)):
        if mod_bits == 4:
            # packed wire: unpack each client's nibbles as XLA
            # temporaries, then exact mod-16 arithmetic in int32 (a
            # packed-byte sum would carry between nibbles)
            size = int(np.prod(sh, dtype=np.int64)) if sh else 1
            words = _unpack_nibbles(ys, size)  # [C, size]
            s = (jnp.sum(words, axis=0)
                 - rec.astype(jnp.int32).reshape(-1)) & 0xF
            c = s - ((s >= half).astype(jnp.int32) << mod_bits)
            c = c.reshape(sh)
        else:
            udt = _UINT[mod_bits]
            s = jnp.sum(ys, axis=0, dtype=udt) - rec.astype(udt)
            c = s.astype(jnp.int32)
            c = c - ((c >= half).astype(jnp.int32) << mod_bits)
        mean = c.astype(jnp.float32) * scale / n_div
        agg = base.astype(jnp.float32) + mean
        pre_noise_traced = pre_noise_traced and isinstance(
            agg, jax.core.Tracer)
        if with_noise:
            agg = agg + sigma * jax.random.normal(
                jax.random.fold_in(key, i), agg.shape, jnp.float32)
        out.append(agg.astype(base.dtype))
    _FINALIZE_TRACE["pre_noise_traced"] = bool(pre_noise_traced)
    _FINALIZE_TRACE["noised_in_program"] = bool(with_noise)
    return tuple(out)


_unmask_program = _wrap_jit(
    "secagg/unmask_finalize", _unmask_program,
    static_argnums=(0, 1, 2, 3, 4), multi_shape=True)


def unmask_finalize(cts: Sequence[CompressedTree], base: Pytree,
                    codec: SecAggInt8Codec,
                    recovery: Optional[Sequence[np.ndarray]] = None,
                    dp_sigma: float = 0.0,
                    dp_key_data: Optional[np.ndarray] = None,
                    mesh=None) -> Pytree:
    """Fuse the survivors' masked trees into the new global model.

    ``recovery`` is the dropout adjustment
    (:func:`masking.recovery_adjustment`), ``dp_sigma`` > 0 adds seeded
    Gaussian noise to the aggregate inside the same program. Raises
    ``ValueError`` on heterogeneous or non-masked inputs.

    ``mesh`` (optional, >1-device) runs the unmask per-shard: masked
    blocks, recovery and base split on their largest coordinate axis
    while the client axis stays whole, so the mod-2^k mask cancellation
    — exact integer arithmetic per coordinate — happens locally on each
    shard and the unmasked aggregate stays bit-identical to the
    1-device program (see :mod:`fedml_tpu.parallel.multichip`).
    """
    from fedml_tpu import telemetry

    if not cts:
        raise ValueError("empty masked update list")
    first = cts[0]
    for ct in cts:
        if (ct.codec != SecAggInt8Codec.name
                or ct.version != WIRE_VERSION_MASKED
                or ct.meta != first.meta or not ct.is_delta):
            raise ValueError(
                "unmask_finalize needs homogeneous masked delta trees "
                f"(got {ct.codec}/v{ct.version})")
    base_leaves = jax.tree.leaves(base)
    if len(base_leaves) != len(first.meta):
        raise ValueError("broadcast base does not match the masked trees")
    try:
        stacked = tuple(
            jnp.stack([np.asarray(ct.arrays[j][0]) for ct in cts])
            for j in range(len(first.meta)))
    except (ValueError, TypeError) as e:
        raise ValueError(
            f"masked block shapes differ across clients: {e}") from None
    if recovery is None:
        rec = tuple(jnp.zeros(sh, _UINT[codec.mod_bits])
                    for _, sh in first.meta)
    else:
        if len(recovery) != len(first.meta):
            raise ValueError("recovery adjustment leaf count mismatch")
        rec = tuple(jnp.asarray(r) for r in recovery)
    with_noise = float(dp_sigma) > 0.0
    if dp_key_data is None:
        dp_key_data = np.asarray(jax.random.key_data(jax.random.key(0)))
    base_leaves = tuple(base_leaves)
    if mesh is not None and getattr(mesh, "size", 1) > 1:
        from fedml_tpu.parallel.multichip import shard_stacked

        stacked = shard_stacked(stacked, mesh)
        # recovery and base carry leaf shapes (no client axis): split on
        # the same coordinate axis the stacked blocks chose
        rec = shard_stacked(rec, mesh, leading_client_axis=False)
        base_leaves = shard_stacked(base_leaves, mesh,
                                    leading_client_axis=False)
    with telemetry.get_tracer().span("compress/decode", codec=codec.name,
                                     n_leaves=len(first.meta)):
        flat = _unmask_program(
            codec.clip, codec.bound, codec.mod_bits, first.meta,
            with_noise, stacked, rec, base_leaves,
            jnp.float32(len(cts)), jnp.float32(dp_sigma),
            jnp.asarray(dp_key_data))
    return jax.tree.unflatten(jax.tree.structure(base), list(flat))
