"""Pairwise-mask arithmetic over the quantized block domain.

The PR 3 int8 codecs ship each update as small integer blocks; masked
secure aggregation (Bonawitz et al., CCS'17 — the semi-honest pairwise
variant) adds, to every client's quantized blocks, a zero-sum family of
pairwise masks **in the same integer domain**, wrapping mod ``2^k``:

    y_i = q_i + Σ_{j≠i} sign(i,j) · PRG(s_ijr)      (mod 2^k)

with ``sign(i,j) = +1`` when ``i < j`` else ``-1`` and ``s_ijr`` a
per-(round, i, j) seed both endpoints derive from their X25519 shared
secret. Summing the survivors' ``y_i`` cancels every mask whose both
endpoints survived; masks paired with an evicted client are removed via
the dropout-recovery reveal (:func:`recovery_adjustment`). Because the
cancellation is exact integer arithmetic, the unmasked sum is
bit-identical to the never-masked sum — masking can never perturb the
aggregate, only hide the contributions.

Wire cost: the masked word is the SAME width as the quantized word
(uint8 for ``mod_bits=8``), so SecAgg rides the int8 wire at ~1× — the
whole point of masking in the block domain instead of a 64-bit finite
field (``core/mpc/finite`` pays 8 bytes/element; this pays 1).

Headroom: with ``mod_bits=8`` every client quantizes to
``B = 127 // cohort_n`` levels so the TRUE cohort sum fits in
``[-127, 127]`` and the mod-256 residue decodes exactly. The per-client
resolution loss (8 → 8−log2(n) bits) is re-sent by error feedback; the
``mod_bits=16`` knob trades 2× wire for full int8-grade resolution at
cohorts up to 255, and ``mod_bits=4`` rides the int4 wire — the masked
nibbles pack two per byte inside the encode program, halving masked
bytes again (``bound = 7 // n``, cohorts up to 7).

Everything here is transport-free math — the protocol dance lives in
:mod:`fedml_tpu.privacy.secagg.protocol`.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Sequence, Tuple

import numpy as np

__all__ = [
    "MOD_BITS_CHOICES",
    "client_bound",
    "mask_leaves",
    "net_mask_leaves",
    "pair_round_seed",
    "recovery_adjustment",
]

MOD_BITS_CHOICES = (4, 8, 16)

# host-side mask words are UNPACKED (one word per element) even at
# mod_bits=4 — packing to two nibbles per byte happens only inside the
# jitted encode program, where the wire is assembled
_WORD_DTYPE = {4: np.uint8, 8: np.uint8, 16: np.uint16}


def _check_mod_bits(mod_bits: int) -> int:
    mod_bits = int(mod_bits)
    if mod_bits not in MOD_BITS_CHOICES:
        raise ValueError(
            f"secagg mod_bits must be one of {MOD_BITS_CHOICES}, "
            f"got {mod_bits}")
    return mod_bits


def client_bound(cohort_n: int, mod_bits: int = 8) -> int:
    """Per-client quantization bound B so the cohort sum never wraps.

    Each client's quantized words live in ``[-B, B]``; ``n`` of them sum
    inside ``[-(2^(k-1)-1), 2^(k-1)-1]``, so the wrapped mod-``2^k``
    residue of the unmasked sum is exact. Cohorts larger than
    ``2^(k-1)-1`` have no representable bound — a loud error, not a
    silent wrap."""
    mod_bits = _check_mod_bits(mod_bits)
    n = int(cohort_n)
    if n < 1:
        raise ValueError(f"cohort must have at least 1 client, got {n}")
    bound = ((1 << (mod_bits - 1)) - 1) // n
    if bound < 1:
        raise ValueError(
            f"cohort of {n} clients cannot share a mod-2^{mod_bits} masked "
            f"domain (max {(1 << (mod_bits - 1)) - 1}); raise "
            f"secagg_mod_bits or shrink the cohort")
    return bound


def pair_round_seed(shared_secret: int, round_idx: int) -> int:
    """The per-(round, i, j) PRF key: fold the X25519-agreed pair secret
    with the round index. Revealing one round's seed (dropout recovery)
    exposes nothing about any other round's masks."""
    h = hashlib.sha256(
        int(shared_secret).to_bytes(16, "little", signed=False)
        + int(round_idx).to_bytes(8, "little", signed=True)
        + b"fedml_tpu/secagg/v2")
    return int.from_bytes(h.digest()[:16], "little")


def _leaf_sizes(meta) -> List[int]:
    return [int(np.prod(sh, dtype=np.int64)) if sh else 1 for _, sh in meta]


def mask_leaves(seed: int, meta, mod_bits: int = 8) -> List[np.ndarray]:
    """One pair's PRG mask, per leaf of a tree described by ``meta``.

    A single Philox stream keyed by ``seed`` covers the whole tree in
    meta order — both endpoints (and the recovery path) slice the same
    stream, so a mask is a pure function of (seed, meta, mod_bits)."""
    mod_bits = _check_mod_bits(mod_bits)
    sizes = _leaf_sizes(meta)
    gen = np.random.Generator(
        np.random.Philox(key=int(seed) & ((1 << 128) - 1)))
    words = gen.integers(0, 1 << mod_bits, size=int(sum(sizes)),
                         dtype=np.uint32)
    out, off = [], 0
    for (dt, sh), n in zip(meta, sizes):
        out.append(words[off:off + n].astype(
            _WORD_DTYPE[mod_bits]).reshape(sh))
        off += n
    return out


def _accumulate(meta, signed_seeds: Sequence[Tuple[int, int]],
                mod_bits: int) -> List[np.ndarray]:
    """Σ sign·PRG(seed) per leaf, wrapping mod 2^k (uint words)."""
    mod_bits = _check_mod_bits(mod_bits)
    dtype = _WORD_DTYPE[mod_bits]
    acc = [np.zeros(sh, dtype) for _, sh in meta]
    for sign, seed in signed_seeds:
        for a, m in zip(acc, mask_leaves(seed, meta, mod_bits)):
            if sign >= 0:
                a += m  # uint wraparound IS the mod-2^k arithmetic
            else:
                a -= m
    if mod_bits < 8:
        # sub-byte domain rides uint8 words: the byte wraparound above
        # is mod-256, which reduces exactly to mod-2^k because 2^k
        # divides 256 — mask down so words stay in [0, 2^k)
        for a in acc:
            a &= (1 << mod_bits) - 1
    return acc


def net_mask_leaves(rank: int, peer_seeds: Dict[int, int], meta,
                    mod_bits: int = 8) -> List[np.ndarray]:
    """A client's NET mask: Σ_{j≠i} sign(i,j)·PRG(s_ijr), per leaf.

    ``peer_seeds`` maps peer rank → per-round pair seed for every OTHER
    member of the round roster. Folding all pairs into one tree means
    the device-side encode adds a single mask tensor per leaf."""
    rank = int(rank)
    signed = [(+1 if rank < int(j) else -1, s)
              for j, s in sorted(peer_seeds.items())]
    return _accumulate(meta, signed, mod_bits)


def recovery_adjustment(pairs: Sequence[Tuple[int, int, int]], meta,
                        mod_bits: int = 8) -> List[np.ndarray]:
    """The sum the server must SUBTRACT after dropout recovery.

    ``pairs`` is ``[(survivor_rank, evicted_rank, revealed_seed), ...]``
    — each survivor applied ``sign(survivor, evicted)·PRG(seed)`` inside
    its upload and the evicted peer's cancelling half never arrived, so
    the same signed mask is reproduced here and removed from the masked
    sum. Exact by construction: recovery restores the bit-identical
    unmasked sum over the survivors."""
    signed = [(+1 if int(i) < int(j) else -1, s) for i, j, s in pairs]
    return _accumulate(meta, signed, mod_bits)
