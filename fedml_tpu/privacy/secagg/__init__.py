"""Dropout-robust secure aggregation over the int8 block domain.

Quick tour::

    # client
    session = SecAggClientSession.from_args(rank, args)   # None when off
    pk = session.pk                                       # rides STATUS msgs
    session.begin_round(header, round_idx)                # from the broadcast
    ct = session.encode_update(delta_tree, key)           # masked, one program
    seeds = session.reveal_for(evicted, round_idx)        # dropout recovery

    # server
    session = SecAggServerSession.from_args(args, client_num)
    header = session.begin_round(round_idx, cohort)       # rides the broadcast
    session.validate_upload(sender, ct)
    new_global = session.aggregate(cts, base)             # unmask + DP, fused

Masks cancel exactly in integer arithmetic (mod ``2^k``), so SecAgg
aggregates are bit-identical to the never-masked sum; the wire carries
one mask-domain word per element (≈ plain int8 bytes). Protocol,
guards and the threat model: ``docs/privacy.md``.
"""
from fedml_tpu.privacy.secagg.codec import (
    SecAggInt8Codec,
    WIRE_VERSION_MASKED,
    last_finalize_trace,
    masked_encode,
    unmask_finalize,
)
from fedml_tpu.privacy.secagg.masking import (
    client_bound,
    mask_leaves,
    net_mask_leaves,
    pair_round_seed,
    recovery_adjustment,
)
from fedml_tpu.privacy.secagg.protocol import (
    SecAggClientSession,
    SecAggMessage,
    SecAggServerSession,
    secagg_enabled,
)

__all__ = [
    "SecAggClientSession",
    "SecAggInt8Codec",
    "SecAggMessage",
    "SecAggServerSession",
    "WIRE_VERSION_MASKED",
    "client_bound",
    "last_finalize_trace",
    "mask_leaves",
    "masked_encode",
    "net_mask_leaves",
    "pair_round_seed",
    "recovery_adjustment",
    "secagg_enabled",
    "unmask_finalize",
]
