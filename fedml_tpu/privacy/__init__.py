"""Privacy subsystems that ride the compressed wire.

- :mod:`fedml_tpu.privacy.secagg` — dropout-robust masked secure
  aggregation over the int8 block domain plus in-program central-DP
  noise. See ``docs/privacy.md`` for the threat model and protocol.
"""
