"""On-device update codecs — the compressed-transport numeric core.

Cross-silo FL is bandwidth-bound: every model payload used to cross the
transport boundary as full-precision f32 ``.npy`` blobs, and the
device→host ``device_get`` moved the same uncompressed bytes off the
accelerator before they even hit the wire. Each codec here encodes a whole
pytree in ONE jitted program on device, so what ``device_get`` (and then
the wire) carries is the compressed representation — int8 blocks + f32
scales, bf16 halves, or top-k (value, index) pairs — never the full f32
tree.

Codecs (QSGD, Alistarh et al. 2017; Deep Gradient Compression, Lin et al.
2018):

  identity   tagged passthrough — bit-exact, the wire-format control
  bf16       f32→bf16 cast — 2×, deterministic, ~2^-8 relative error
  int8       per-leaf stochastic uniform quantization — ~4×, unbiased
             (E[decode(encode(x))] = x), |err| ≤ max|leaf|/127 per element
  topk       per-leaf top-k-by-magnitude sparsification — size ~2k·4B;
             kept entries are exact, dropped entries are the error (pair
             with the client-side error-feedback residual,
             :mod:`fedml_tpu.compression.error_feedback`)
  int4       blockwise stochastic uniform 4-bit quantization — ~7.5×;
             two codes packed per uint8 + one f32 absmax scale per block
             (spec ``int4@128`` sets the block size)
  nf4        blockwise normal-float 4-bit (QLoRA's NF4 codebook,
             Dettmers et al. 2023) — same packing/ratio as int4, lower
             error on normally-distributed deltas

Integer/bool leaves always pass through raw — quantizing a step counter
would corrupt it silently.

A :class:`CompressedTree` is a registered pytree (children = the encoded
arrays) so ``tree_nbytes``, ``device_get``/``device_put`` and the
transport offload threshold all see the *compressed* size. The wire
format is a versioned, codec-tagged extension of ``safe_dumps`` — see
``utils/serialization.py``; unknown codec tags are rejected with
``ValueError``.
"""
from __future__ import annotations

import functools
import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

WIRE_VERSION = 1
# masked wire nodes (secure aggregation) are version-2 codec nodes: same
# array framing plus a validated "sa" metadata field. The codec lives in
# fedml_tpu/privacy/secagg (loaded lazily — see get_codec).
WIRE_VERSION_MASKED = 2

# meta entry per original leaf: (dtype string, shape tuple)
LeafMeta = Tuple[str, Tuple[int, ...]]


def _dtype_from_str(s: str):
    if s == "bfloat16":
        return jnp.bfloat16
    return np.dtype(s)


def _is_float_meta(dt: str) -> bool:
    if dt == "bfloat16":
        return True
    return np.dtype(dt).kind == "f"


class CompressedTree:
    """A pytree encoded by a named codec, ready for the wire.

    ``arrays`` is a flat list over the original leaves; each entry is the
    codec-positional list of arrays for that leaf (e.g. ``[q, scale]`` for
    int8). ``structure`` is the original container tree with each leaf
    replaced by its flat index, so decode can rebuild the exact shape.
    ``sa`` is the masked-wire (v2) metadata dict — None on plain (v1)
    trees.
    """

    __slots__ = ("codec", "version", "is_delta", "raw_nbytes", "meta",
                 "structure", "arrays", "sa")

    def __init__(self, codec: str, version: int, is_delta: bool,
                 raw_nbytes: int, meta: Tuple[LeafMeta, ...],
                 structure: Pytree, arrays: List[List[Any]],
                 sa: Optional[dict] = None):
        self.codec = str(codec)
        self.version = int(version)
        self.is_delta = bool(is_delta)
        self.raw_nbytes = int(raw_nbytes)
        self.meta = tuple((str(dt), tuple(int(d) for d in sh))
                          for dt, sh in meta)
        self.structure = structure
        self.arrays = arrays
        self.sa = dict(sa) if sa is not None else None

    def tree_flatten(self):
        aux = (self.codec, self.version, self.is_delta, self.raw_nbytes,
               self.meta, self.structure, self.sa)
        return (self.arrays,), aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        codec, version, is_delta, raw_nbytes, meta, structure, sa = aux
        return cls(codec, version, is_delta, raw_nbytes, meta, structure,
                   children[0], sa=sa)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"CompressedTree(codec={self.codec}, v{self.version}, "
                f"delta={self.is_delta}, leaves={len(self.arrays)})")


jax.tree_util.register_pytree_node(
    CompressedTree,
    lambda ct: ct.tree_flatten(),
    CompressedTree.tree_unflatten,
)


def _leaf_key(key, i: int):
    return jax.random.fold_in(key, i)


class Codec:
    """Base codec: per-leaf traceable kernels + whole-tree jitted wrappers."""

    name: str = "base"
    lossless: bool = False
    # safe for FULL-model broadcast (not just deltas): sparsifying a whole
    # model would zero most of its weights, so top-k is delta/upload-only
    broadcast_safe: bool = True
    # maskable codecs (secure aggregation) carry pairwise-masked blocks:
    # individual trees never decode and the generic weighted sum refuses
    # them — they resolve only through privacy.secagg.unmask_finalize
    maskable: bool = False

    @property
    def spec(self) -> str:
        """The negotiation-header form: name plus any parameters a peer
        must match for fused aggregation (``topk@0.05``)."""
        return self.name

    # -- per-leaf kernels (pure jnp; must trace under jit/vmap) -----------
    def encode_leaf(self, x: jax.Array, key) -> List[jax.Array]:
        raise NotImplementedError

    def decode_leaf(self, parts: Sequence[jax.Array], dt: str,
                    shape: Tuple[int, ...]) -> jax.Array:
        raise NotImplementedError

    # -- traceable tree-level helpers -------------------------------------
    def _encode_leaves(self, leaves: Sequence[jax.Array], meta, key):
        out = []
        for i, (leaf, (dt, _)) in enumerate(zip(leaves, meta)):
            if _is_float_meta(dt):
                out.append(self.encode_leaf(leaf, _leaf_key(key, i)))
            else:
                out.append([leaf])  # raw passthrough for int/bool leaves
        return out

    def _decode_leaves(self, arrays, meta):
        out = []
        for parts, (dt, sh) in zip(arrays, meta):
            if _is_float_meta(dt):
                out.append(self.decode_leaf(parts, dt, sh))
            else:
                out.append(parts[0])
        return out

    def qdq(self, tree: Pytree, key) -> Pytree:
        """decode(encode(tree)) as one traceable function — the simulated
        wire for in-program paths (mesh simulator) and error feedback."""
        leaves, treedef = jax.tree.flatten(tree)
        meta = _tree_meta(leaves)
        enc = self._encode_leaves(leaves, meta, key)
        return jax.tree.unflatten(treedef, self._decode_leaves(enc, meta))

    # -- wire validation ---------------------------------------------------
    def check_wire(self, ct: "CompressedTree") -> None:
        """Reject wire payloads whose metadata-like parts are non-finite.

        A NaN/Inf *scale* poisons every element of its block on decode
        and is absorbing under every weighted sum — silently propagating
        it turns one corrupt upload into a corrupt global model. Codecs
        with scale-like parts override this (int8 scales, top-k values);
        the check runs on HOST arrays only (the wire form — what a
        hostile peer controls): device-resident arrays from an
        in-process encode are covered by the integrity screen's jitted
        pass instead, so the hot path never pays a forced sync here.
        Raises ``ValueError`` and counts
        ``integrity/nonfinite_wire`` on a hit.
        """

    def _resolve_wire(self, ct: "CompressedTree") -> "Codec":
        """The codec INSTANCE that matches a wire tree.

        Tag-only resolution sites (fused sums, robust agg, screening,
        serving staging) call :func:`get_codec` with ``ct.codec`` — the
        bare NAME. Codecs whose decode geometry depends on a parameter
        (the 4-bit block size) override this to recover the parameter
        from the wire arrays themselves, so no out-of-band spec is
        needed to frame the blocks.
        """
        return self

    def _reject_nonfinite_wire(self, what: str) -> None:
        from fedml_tpu import telemetry

        telemetry.get_registry().counter("integrity/nonfinite_wire").inc()
        raise ValueError(
            f"non-finite {what} in a {self.name} wire payload — refusing "
            "to decode/aggregate a poisoned tree (see docs/integrity.md)")

    # -- whole-tree entry points ------------------------------------------
    def encode(self, tree: Pytree, key=None, is_delta: bool = False,
               residual: Optional[Pytree] = None):
        """Encode a pytree → :class:`CompressedTree` (one jitted program).

        With ``residual`` (error feedback) the program also returns the
        new residual: ``(CompressedTree, new_residual)``.
        """
        from fedml_tpu import telemetry

        leaves, treedef = jax.tree.flatten(tree)
        meta = _tree_meta(leaves)
        counter = itertools.count()
        structure = jax.tree.unflatten(
            treedef, [next(counter) for _ in leaves])
        raw_nbytes = sum(
            int(np.prod(sh, dtype=np.int64))
            * np.dtype(_dtype_from_str(dt)).itemsize
            for dt, sh in meta
        )
        if key is None:
            key = jax.random.key(0)
        with telemetry.get_tracer().span("compress/encode", codec=self.name,
                                         n_leaves=len(leaves)):
            if residual is None:
                arrays = _encode_program(self, meta, tuple(leaves), key)
                new_residual = None
            else:
                res_leaves = tuple(jax.tree.leaves(residual))
                arrays, new_res_leaves = _ef_encode_program(
                    self, meta, tuple(leaves), res_leaves, key)
                new_residual = jax.tree.unflatten(treedef, new_res_leaves)
        ct = CompressedTree(self.name, WIRE_VERSION, is_delta, raw_nbytes,
                            meta, structure, [list(p) for p in arrays])
        return ct if residual is None else (ct, new_residual)

    def decode(self, ct: CompressedTree) -> Pytree:
        """Decode a :class:`CompressedTree` back to a full pytree."""
        from fedml_tpu import telemetry

        if ct.codec != self.name:
            raise ValueError(
                f"codec mismatch: {self.name} cannot decode {ct.codec!r}")
        if ct.version != WIRE_VERSION:
            raise ValueError(
                f"unsupported compression wire version {ct.version}")
        eff = self._resolve_wire(ct)
        if eff is not self:
            # tag-only callers hold the default-parameter instance; the
            # wire itself says which block geometry framed it
            return eff.decode(ct)
        self.check_wire(ct)
        with telemetry.get_tracer().span("compress/decode", codec=self.name,
                                         n_leaves=len(ct.arrays)):
            flat = _decode_program(
                self, ct.meta, tuple(tuple(p) for p in ct.arrays))
        return jax.tree.map(lambda i: flat[i], ct.structure)

    # -- dequant-fused weighted reduction ---------------------------------
    def weighted_sum_leaf(self, stacked: Sequence[jax.Array], w: jax.Array,
                          dt: str, shape: Tuple[int, ...]) -> jax.Array:
        """Σ_i w_i · decode(leaf_i) with the client axis stacked — the
        default decodes per client; subclasses fuse the dequant into the
        reduction so no per-client f32 tree is ever materialized."""
        dec = jax.vmap(lambda *p: self.decode_leaf(p, dt, shape))(*stacked)
        return jnp.einsum("c,c...->...", w, dec.astype(jnp.float32)).astype(
            _dtype_from_str(dt))


def _tree_meta(leaves) -> Tuple[LeafMeta, ...]:
    out = []
    for leaf in leaves:
        dt = getattr(leaf, "dtype", None)
        sh = getattr(leaf, "shape", None)
        if dt is None:  # python scalar leaf
            a = np.asarray(leaf)
            dt, sh = a.dtype, a.shape
        out.append((str(dt), tuple(int(d) for d in sh)))
    return tuple(out)


# Whole-tree programs, jitted once per (codec instance, meta, structure)
# and registered in the program catalog (telemetry.profiling) so their
# XLA flops/bytes/HBM feed the attribution layer. Codec instances are
# cached by get_codec, so jit's weakref cache holds; distinct trees are
# legitimate variants (multi_shape), not treedef churn.
from fedml_tpu.telemetry.profiling import wrap_jit as _wrap_jit


@functools.partial(jax.jit, static_argnums=(0, 1))
def _encode_program(codec: Codec, meta, leaves, key):
    return tuple(tuple(p) for p in codec._encode_leaves(leaves, meta, key))


_encode_program = _wrap_jit("compress/encode", _encode_program,
                            static_argnums=(0, 1), multi_shape=True)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _ef_encode_program(codec: Codec, meta, leaves, res_leaves, key):
    """Error-feedback encode as ONE program: compensate, encode, decode,
    and compute the new residual without leaving the device."""
    comp = tuple(x + r for x, r in zip(leaves, res_leaves))
    enc = codec._encode_leaves(comp, meta, key)
    dec = codec._decode_leaves(enc, meta)
    new_res = tuple(
        (c - d.astype(c.dtype)) if _is_float_meta(dt) else jnp.zeros_like(c)
        for c, d, (dt, _) in zip(comp, dec, meta)
    )
    return tuple(tuple(p) for p in enc), new_res


_ef_encode_program = _wrap_jit("compress/ef_encode", _ef_encode_program,
                               static_argnums=(0, 1), multi_shape=True)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _decode_program(codec: Codec, meta, arrays):
    return tuple(codec._decode_leaves(arrays, meta))


_decode_program = _wrap_jit("compress/decode", _decode_program,
                            static_argnums=(0, 1), multi_shape=True)


def _raw_weighted_sum(leaf_stacked, w):
    # raw-passthrough (int/bool) leaves: same semantics as
    # utils.tree.weighted_tree_sum (weights cast to the leaf dtype)
    wb = w.reshape((-1,) + (1,) * (leaf_stacked.ndim - 1)).astype(
        leaf_stacked.dtype)
    return jnp.sum(leaf_stacked * wb, axis=0)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _fused_weighted_sum_program(codec: Codec, meta, stacked, w):
    return tuple(
        codec.weighted_sum_leaf(parts, w, dt, sh)
        if _is_float_meta(dt) else _raw_weighted_sum(parts[0], w)
        for parts, (dt, sh) in zip(stacked, meta)
    )


_fused_weighted_sum_program = _wrap_jit(
    "compress/fused_weighted_sum", _fused_weighted_sum_program,
    static_argnums=(0, 1), multi_shape=True)


def tree_delta(new: Pytree, ref: Pytree) -> Pytree:
    """Delta of ``new`` against ``ref`` — float leaves only.

    Int/bool leaves ride as ABSOLUTE values: codecs pass them through
    raw, and a weighted sum of int *deltas* would not match what the
    uncompressed path computes for those leaves. :func:`tree_undelta`
    is the inverse.
    """
    return jax.tree.map(
        lambda n, r: n - r if jnp.issubdtype(
            jnp.asarray(n).dtype, jnp.floating) else n,
        new, ref)


def tree_undelta(ref: Pytree, delta: Pytree) -> Pytree:
    """Apply a :func:`tree_delta` result back onto ``ref``."""
    return jax.tree.map(
        lambda r, d: (r + d.astype(r.dtype)) if jnp.issubdtype(
            jnp.asarray(r).dtype, jnp.floating) else d,
        ref, delta)


def fused_weighted_sum(cts: Sequence[CompressedTree], weights,
                       mesh=None) -> Pytree:
    """Σ_i w_i · decode(ct_i) over clients as ONE dequant-fused program.

    The per-client compressed blocks (int8 q + scales, top-k pairs, …)
    are stacked on a leading client axis and reduced inside the same
    jitted weighted sum — the server never materializes the N decoded
    f32 client trees. ``weights`` should already be normalized.

    ``mesh`` (optional, a >1-device jax Mesh) runs the SAME program
    per-shard: each leaf's largest coordinate axis is split across the
    mesh while the client axis stays whole, so the per-coordinate
    weighted reduction is local to a shard and the result is
    bit-identical to the unsharded call — per-device stacked-wire and
    f32-temporary bytes drop by the mesh size (see
    :mod:`fedml_tpu.parallel.multichip`).
    """
    if not cts:
        raise ValueError("empty compressed update list")
    first = cts[0]
    for ct in cts[1:]:
        if (ct.codec != first.codec or ct.version != first.version
                or ct.meta != first.meta
                or ct.is_delta != first.is_delta):
            raise ValueError(
                "cannot fuse heterogeneous compressed updates "
                f"({ct.codec}/v{ct.version} vs {first.codec}/v{first.version})")
    codec = get_codec(first.codec)._resolve_wire(first)
    if codec.maskable:
        raise ValueError(
            "masked (secure-aggregation) updates cannot ride the generic "
            "weighted sum — per-client float weights would break exact "
            "mask cancellation; use privacy.secagg.unmask_finalize")
    n_leaves = len(first.meta)
    if any(len(ct.arrays) != n_leaves for ct in cts):
        raise ValueError("compressed update leaf count mismatch")
    for ct in cts:
        # a NaN/Inf scale is absorbing under the fused einsum — one
        # corrupt wire payload must fail loudly, not poison the sum
        codec.check_wire(ct)
    try:
        stacked = tuple(
            tuple(jnp.stack([ct.arrays[j][p] for ct in cts])
                  for p in range(len(first.arrays[j])))
            for j in range(n_leaves)
        )
    except (ValueError, TypeError) as e:
        raise ValueError(
            f"compressed update block shapes differ across clients "
            f"({first.codec}); check that every peer uses the same codec "
            f"parameters (e.g. compression_topk_ratio): {e}") from None
    if (mesh is not None and getattr(mesh, "size", 1) > 1
            and codec.name != "topk"):
        # dense codecs only: top-k blocks are (indices, values) pairs
        # whose coordinate ownership is data-dependent — splitting the k
        # axis would turn the scatter into an all-to-all, not a shard
        from fedml_tpu.parallel.multichip import shard_stacked

        stacked = shard_stacked(stacked, mesh)
    w = jnp.asarray(weights, jnp.float32)
    flat = _fused_weighted_sum_program(codec, first.meta, stacked, w)
    return jax.tree.map(lambda i: flat[i], first.structure)


class IdentityCodec(Codec):
    name = "identity"
    lossless = True

    def encode_leaf(self, x, key):
        return [x]

    def decode_leaf(self, parts, dt, shape):
        return parts[0]


class Bf16Codec(Codec):
    name = "bf16"

    def encode_leaf(self, x, key):
        return [x.astype(jnp.bfloat16)]

    def decode_leaf(self, parts, dt, shape):
        return parts[0].astype(_dtype_from_str(dt))


class Int8Codec(Codec):
    """Per-leaf stochastic uniform int8 quantization (QSGD-style).

    scale = max|leaf| / 127; q = ⌊x/scale + u⌋, u ~ U[0,1) — unbiased,
    per-element error bounded by one quantization step (= scale).
    """

    name = "int8"

    def encode_leaf(self, x, key):
        xf = x.astype(jnp.float32)
        amax = jnp.max(jnp.abs(xf))
        scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
        v = xf / scale
        q = jnp.floor(v + jax.random.uniform(key, xf.shape))
        q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
        return [q, scale]

    def decode_leaf(self, parts, dt, shape):
        q, scale = parts
        return (q.astype(jnp.float32) * scale).astype(_dtype_from_str(dt))

    def check_wire(self, ct: "CompressedTree") -> None:
        # int8 blocks are finite by dtype; the scale scalar is the whole
        # attack surface — and tiny, so the host check is free
        for parts, (dt, _) in zip(ct.arrays, ct.meta):
            if not _is_float_meta(dt) or len(parts) < 2:
                continue
            scale = parts[1]
            if isinstance(scale, (np.ndarray, np.generic, float)) and not (
                    np.all(np.isfinite(scale))):
                self._reject_nonfinite_wire("scale")

    def weighted_sum_leaf(self, stacked, w, dt, shape):
        # the dequant is fused INTO the reduction: the (w_i · s_i) scalar
        # product folds both the per-client scale and the FedAvg weight,
        # so the int8 blocks reduce in one einsum — no stacked f32 copy
        # of the client trees ever exists in HBM
        q, scale = stacked  # q: [c, ...] int8, scale: [c]
        return jnp.einsum(
            "c,c...->...", w * scale, q.astype(jnp.float32)
        ).astype(_dtype_from_str(dt))


class TopKCodec(Codec):
    """Per-leaf top-k-by-magnitude sparsification (DGC-style).

    Keeps ``ceil(ratio · size)`` entries per leaf as exact (value, index)
    pairs; everything else decodes to zero. Pair with the client-side
    error-feedback residual so dropped mass is re-sent in later rounds.
    """

    name = "topk"
    broadcast_safe = False  # dropping 1-ratio of a full model is not lossy
    # compression, it is a different model — uploads (deltas + error
    # feedback) only; the broadcast ships plain

    def __init__(self, ratio: float = 0.05):
        self.ratio = float(ratio)
        if not 0.0 < self.ratio <= 1.0:
            raise ValueError(f"topk ratio must be in (0, 1], got {ratio}")

    @property
    def spec(self) -> str:
        return f"{self.name}@{self.ratio:g}"

    def _k(self, size: int) -> int:
        return max(1, int(np.ceil(self.ratio * size)))

    def encode_leaf(self, x, key):
        flat = x.astype(jnp.float32).ravel()
        k = self._k(flat.size)
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        return [flat[idx], idx.astype(jnp.int32)]

    def decode_leaf(self, parts, dt, shape):
        v, idx = parts
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        out = jnp.zeros((size,), jnp.float32).at[idx].set(v)
        return out.reshape(shape).astype(_dtype_from_str(dt))

    def weighted_sum_leaf(self, stacked, w, dt, shape):
        # scatter-add of every client's sparse contribution into one dense
        # accumulator — dense per-client trees are never built
        v, idx = stacked  # [c, k] each
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        contrib = (w[:, None] * v).ravel()
        out = jnp.zeros((size,), jnp.float32).at[idx.ravel()].add(contrib)
        return out.reshape(shape).astype(_dtype_from_str(dt))

    def check_wire(self, ct: "CompressedTree") -> None:
        # the kept values ARE the payload — scale-like, worth the check
        for parts, (dt, _) in zip(ct.arrays, ct.meta):
            if not _is_float_meta(dt):
                continue
            v = parts[0]
            if isinstance(v, (np.ndarray, np.generic)) and not np.all(
                    np.isfinite(v)):
                self._reject_nonfinite_wire("top-k values")


# NF4: the 16-entry normal-float codebook of Dettmers et al. 2023 —
# quantiles of N(0,1) rescaled so the range is exactly [-1, 1] and zero
# is representable. Codes are indices into this table.
NF4_CODEBOOK = np.asarray([
    -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
    -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
    0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
    0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
    0.7229568362236023, 1.0,
], np.float32)
# nearest-codeword binning: code = #{midpoints below v}
_NF4_MIDPOINTS = (NF4_CODEBOOK[1:] + NF4_CODEBOOK[:-1]) / 2.0


class _Blockwise4BitCodec(Codec):
    """Shared 4-bit machinery: flatten → pad to a block multiple →
    per-block absmax scale → 4-bit codes packed two per uint8.

    The wire per float leaf is ``[packed uint8 [n_blocks, block//2],
    scale f32 [n_blocks]]``; element ``2i`` of a block rides in the low
    nibble of byte ``i``, element ``2i+1`` in the high nibble. Unpacking
    happens only inside jitted consumers (decode, fused sums, secagg,
    robust agg) — packed bytes are what HBM and the wire hold.
    """

    DEFAULT_BLOCK = 128
    MAX_BLOCK = 1 << 20  # cap: a hostile wire must not dictate a huge
    # padded decode temporary via an absurd claimed block size

    def __init__(self, block: int = DEFAULT_BLOCK):
        block = int(block)
        # powers of two only: besides matching lane tiling, it makes a
        # truncated pack UNFRAMEABLE — chopping a column off a packed
        # leaf cannot re-present as a smaller self-consistent block
        if block < 2 or block & (block - 1) or block > self.MAX_BLOCK:
            raise ValueError(
                f"{self.name} block size must be a power of two in "
                f"[2, {self.MAX_BLOCK}], got {block}")
        self.block = block

    def _resolve_wire(self, ct):
        # the packed part's last dim IS block/2 — recover the instance
        # from the first float leaf (check_wire then validates every
        # leaf against this geometry); a non-power-of-two claimed block
        # falls through so check_wire rejects it as truncation
        for parts, (dt, _) in zip(ct.arrays, ct.meta):
            if _is_float_meta(dt) and len(parts) == 2:
                pshape = tuple(getattr(parts[0], "shape", ()) or ())
                if len(pshape) == 2 and 0 < pshape[1] <= self.MAX_BLOCK // 2:
                    cand = 2 * int(pshape[1])
                    if not cand & (cand - 1):
                        return get_codec(f"{self.name}@{cand}")
                break
        return self

    @property
    def spec(self) -> str:
        return f"{self.name}@{self.block}"

    def _geometry(self, shape) -> Tuple[int, int]:
        """(element count, block count) for a leaf shape."""
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        return size, -(-size // self.block)

    # -- subclass hooks ----------------------------------------------------
    def _scale_from_amax(self, amax):
        raise NotImplementedError

    def _quantize(self, v, key):
        """Per-block-normalized values → int32 codes in [0, 15]."""
        raise NotImplementedError

    def _lookup(self, codes):
        """int32 codes in [0, 15] → pre-scale f32 values."""
        raise NotImplementedError

    # -- codec kernels -----------------------------------------------------
    def encode_leaf(self, x, key):
        size, n_blocks = self._geometry(x.shape)
        xf = x.astype(jnp.float32).ravel()
        xf = jnp.pad(xf, (0, n_blocks * self.block - size))
        xf = xf.reshape(n_blocks, self.block)
        amax = jnp.max(jnp.abs(xf), axis=1)
        scale = jnp.where(amax > 0, self._scale_from_amax(amax),
                          1.0).astype(jnp.float32)
        codes = self._quantize(xf / scale[:, None], key)
        packed = (codes[:, 0::2] | (codes[:, 1::2] << 4)).astype(jnp.uint8)
        return [packed, scale]

    def _unpack(self, packed):
        lo = (packed & 0xF).astype(jnp.int32)
        hi = (packed >> 4).astype(jnp.int32)
        return jnp.stack([lo, hi], axis=-1).reshape(
            packed.shape[:-1] + (2 * packed.shape[-1],))

    def decode_leaf(self, parts, dt, shape):
        packed, scale = parts
        size, _ = self._geometry(shape)
        vals = self._lookup(self._unpack(packed)) * scale[:, None]
        return vals.reshape(-1)[:size].reshape(shape).astype(
            _dtype_from_str(dt))

    def weighted_sum_leaf(self, stacked, w, dt, shape):
        # the nibble unpack + codebook lookup are XLA temporaries inside
        # the fused program; the (w_i · s_ib) product folds the FedAvg
        # weight and every per-client per-block scale into one einsum —
        # no stacked f32 client trees in HBM
        packed, scale = stacked  # [c, nb, block/2] uint8, [c, nb] f32
        vals = self._lookup(self._unpack(packed))  # [c, nb, block]
        out = jnp.einsum("cb,cbk->bk", w[:, None] * scale, vals)
        size, _ = self._geometry(shape)
        return out.reshape(-1)[:size].reshape(shape).astype(
            _dtype_from_str(dt))

    def check_wire(self, ct: "CompressedTree") -> None:
        # structural first: a truncated or odd-length pack mis-frames
        # every block after the cut; then per-block scales, which are the
        # whole numeric attack surface (packed nibbles are finite by
        # construction). Same contract as int8 — host arrays only.
        for parts, (dt, sh) in zip(ct.arrays, ct.meta):
            if not _is_float_meta(dt):
                continue
            if len(parts) != 2:
                raise ValueError(
                    f"{self.name} wire leaf must carry [packed, scale] "
                    f"(got {len(parts)} parts)")
            packed, scale = parts
            size, n_blocks = self._geometry(sh)
            want = (n_blocks, self.block // 2)
            pshape = tuple(getattr(packed, "shape", ()))
            if pshape != want:
                raise ValueError(
                    f"{self.name} packed nibble shape {pshape} does not "
                    f"cover leaf {sh} at block={self.block} (expected "
                    f"{want}) — truncated or odd-length pack")
            pdt = getattr(packed, "dtype", None)
            if pdt is not None and np.dtype(str(pdt)) != np.uint8:
                raise ValueError(
                    f"{self.name} packed nibbles must be uint8, got {pdt}")
            if tuple(getattr(scale, "shape", ())) != (n_blocks,):
                raise ValueError(
                    f"{self.name} scale shape "
                    f"{tuple(getattr(scale, 'shape', ()))} does not match "
                    f"{n_blocks} blocks for leaf {sh}")
            if isinstance(scale, (np.ndarray, np.generic, float)) and not (
                    np.all(np.isfinite(scale))):
                self._reject_nonfinite_wire("block scale")


class Int4Codec(_Blockwise4BitCodec):
    """Blockwise stochastic uniform int4 (QSGD at 4 bits).

    scale = blockmax|x| / 7; q = ⌊x/scale + u⌋ clipped to [-7, 7],
    stored offset-binary as q+8 ∈ [1, 15] — unbiased, per-element error
    bounded by one step (= block scale).
    """

    name = "int4"

    def _scale_from_amax(self, amax):
        return amax / 7.0

    def _quantize(self, v, key):
        q = jnp.floor(v + jax.random.uniform(key, v.shape))
        return (jnp.clip(q, -7.0, 7.0) + 8.0).astype(jnp.int32)

    def _lookup(self, codes):
        return codes.astype(jnp.float32) - 8.0


class Nf4Codec(_Blockwise4BitCodec):
    """Blockwise NF4 (normal-float 4-bit, Dettmers et al. 2023).

    scale = blockmax|x|; codes index the 16-entry N(0,1)-quantile
    codebook by nearest codeword. Deterministic (round-to-nearest in
    codebook space); pair with error feedback to re-send the bias.
    """

    name = "nf4"

    def _scale_from_amax(self, amax):
        return amax

    def _quantize(self, v, key):
        del key  # nearest-codeword: deterministic by design
        mids = jnp.asarray(_NF4_MIDPOINTS)
        return jnp.sum(
            v[..., None] > mids, axis=-1).astype(jnp.int32)

    def _lookup(self, codes):
        return jnp.asarray(NF4_CODEBOOK)[codes]


_CODEC_CLASSES: Dict[str, type] = {
    IdentityCodec.name: IdentityCodec,
    Bf16Codec.name: Bf16Codec,
    Int8Codec.name: Int8Codec,
    TopKCodec.name: TopKCodec,
    Int4Codec.name: Int4Codec,
    Nf4Codec.name: Nf4Codec,
}

_INSTANCES: Dict[Tuple, Codec] = {}

_SECAGG_NAME = "secagg_int8"

# the FA sketch codec family (fa/sketch/codec.py) registers itself on
# import; resolving one of its tags before anything imported the fa
# package triggers the import instead of failing the lookup
_SKETCH_NAMES = ("cms", "csk", "votevec", "bloom", "hist")


def _load_secagg_codec() -> type:
    """Lazy registration of the maskable codec — privacy.secagg imports
    this module, so the import runs on first use, not at import time."""
    if _SECAGG_NAME not in _CODEC_CLASSES:
        from fedml_tpu.privacy.secagg.codec import SecAggInt8Codec

        _CODEC_CLASSES[SecAggInt8Codec.name] = SecAggInt8Codec
    return _CODEC_CLASSES[_SECAGG_NAME]


def _load_sketch_codecs() -> None:
    """Lazy registration of the FA sketch codecs (same pattern as the
    masked codec: fa.sketch imports this module)."""
    if _SKETCH_NAMES[0] not in _CODEC_CLASSES:
        import fedml_tpu.fa.sketch.codec  # noqa: F401  (register_codec)


def available_codecs() -> Tuple[str, ...]:
    # the masked codec and the sketch family are always legal wire tags,
    # loaded or not — a receiver must not reject a payload just because
    # nothing in its process imported the owning package yet
    return tuple(sorted(
        set(_CODEC_CLASSES) | {_SECAGG_NAME} | set(_SKETCH_NAMES)))


def register_codec(cls: type) -> type:
    """Register a third-party codec class (``cls.name`` becomes its tag)."""
    _CODEC_CLASSES[str(cls.name)] = cls
    return cls


def get_codec(name: str, args: Any = None) -> Optional[Codec]:
    """Resolve a codec by tag or spec. '' / 'none' / 'off' → None.

    Accepts the negotiation-header spec form ``topk@0.05`` — parameters
    in a spec override ``args`` so every peer in a federation encodes
    with the server-advertised parameters, not its local config.
    Instances are cached per (name, params) so jit caches keyed on the
    codec instance stay warm across messages and rounds.
    """
    name = str(name or "").lower()
    if name in ("", "none", "off"):
        return None
    base, _, param = name.partition("@")
    if base == _SECAGG_NAME:
        cls = _load_secagg_codec()
        if param:
            clip, bound, mod_bits = cls.parse_param(param)
        else:
            # bare tag (wire validation, maskable checks): a default
            # instance — every real round negotiates explicit params
            clip, bound, mod_bits = 0.1, 42, 8
        cache_key = (base, clip, bound, mod_bits)
        if cache_key not in _INSTANCES:
            _INSTANCES[cache_key] = cls(clip, bound, mod_bits)
        return _INSTANCES[cache_key]
    if base in _SKETCH_NAMES and base not in _CODEC_CLASSES:
        _load_sketch_codecs()
    if base not in _CODEC_CLASSES:
        raise ValueError(
            f"unknown compression codec {base!r}; "
            f"available: {', '.join(available_codecs())}")
    cls = _CODEC_CLASSES[base]
    if hasattr(cls, "parse_param"):
        # self-describing registered codec (the sketch family): the
        # class owns its spec grammar and its args-derived defaults
        params = tuple(cls.parse_param(param) if param
                       else cls.default_param(args))
        cache_key = (base,) + params
        if cache_key not in _INSTANCES:
            _INSTANCES[cache_key] = cls(*params)
        return _INSTANCES[cache_key]
    if param and base not in (TopKCodec.name, Int4Codec.name,
                              Nf4Codec.name):
        raise ValueError(f"codec {base!r} takes no parameter ({name!r})")
    if base in (Int4Codec.name, Nf4Codec.name):
        if param:
            try:
                block = int(param)
            except ValueError:
                raise ValueError(
                    f"malformed {base} block size in codec spec {name!r}"
                ) from None
        else:
            block = int(getattr(
                args, "compression_block_size",
                _Blockwise4BitCodec.DEFAULT_BLOCK,
            ) if args is not None else _Blockwise4BitCodec.DEFAULT_BLOCK)
        cache_key: Tuple = (base, block)
        if cache_key not in _INSTANCES:
            _INSTANCES[cache_key] = _CODEC_CLASSES[base](block)
        return _INSTANCES[cache_key]
    if base == TopKCodec.name:
        if param:
            try:
                ratio = float(param)
            except ValueError:
                raise ValueError(
                    f"malformed topk ratio in codec spec {name!r}"
                ) from None
        else:
            ratio = float(getattr(args, "compression_topk_ratio", 0.05)
                          if args is not None else 0.05)
        cache_key: Tuple = (base, ratio)
        if cache_key not in _INSTANCES:
            _INSTANCES[cache_key] = TopKCodec(ratio)
        return _INSTANCES[cache_key]
    if (base,) not in _INSTANCES:
        _INSTANCES[(base,)] = _CODEC_CLASSES[base]()
    return _INSTANCES[(base,)]


def derive_key(seed: int, round_idx: int, client_id: int):
    """Deterministic stochastic-rounding key for (run, round, client).

    A pure function of its inputs — no global counter is consumed, so
    prefetched and inline staging (and checkpoint replay) draw identical
    keys.
    """
    key = jax.random.key(int(seed) & 0x7FFFFFFF)
    key = jax.random.fold_in(key, int(round_idx))
    return jax.random.fold_in(key, int(client_id) & 0x7FFFFFFF)


def derive_key_data(seed: int, round_idx: int, client_id: int) -> np.ndarray:
    """Raw uint32 key data for staging paths that ship keys into programs."""
    return np.asarray(jax.random.key_data(
        derive_key(seed, round_idx, client_id)))


def derive_key_data_batch(seed: int, round_idx: int,
                          client_ids: np.ndarray) -> np.ndarray:
    """:func:`derive_key_data` for a whole id array in ONE dispatch.

    Bit-identical per element to the scalar form (same fold_in chain) —
    staging paths must not re-introduce an O(slots) host-dispatch loop.
    """
    base = jax.random.fold_in(
        jax.random.key(int(seed) & 0x7FFFFFFF), int(round_idx))
    cids = jnp.asarray(
        np.asarray(client_ids, np.int64) & 0x7FFFFFFF, jnp.uint32)
    keys = jax.vmap(
        lambda c: jax.random.key_data(jax.random.fold_in(base, c)))(cids)
    return np.asarray(keys)
