"""Client-side error feedback for lossy update codecs.

EF-SGD (Seide et al. 2014; Karimireddy et al. 2019): a client keeps the
quantization/sparsification error it made this round and adds it back
into next round's update before encoding, so compression error is
*re-sent*, not lost — the accumulated decoded updates track the
accumulated true updates, which is what keeps top-k at 1–5% density and
int8 quantization convergent.

The residual lives on the CLIENT (one tree per client), persists across
rounds, and is updated inside the same jitted program as the encode
(see ``codecs._ef_encode_program``) — no extra device round-trip.
Residual state is in-memory only: a restarted client begins with a zero
residual, which costs at most one round of re-sent error.
"""
from __future__ import annotations

from typing import Any, Optional

import jax

from fedml_tpu.compression.codecs import Codec, CompressedTree

Pytree = Any


class ErrorFeedback:
    """Per-client residual accumulator wrapping a lossy codec.

    Lossless codecs (identity) short-circuit: their residual is
    identically zero, so no state is kept.
    """

    def __init__(self, codec: Codec):
        self.codec = codec
        self._residual: Optional[Pytree] = None

    @property
    def residual(self) -> Optional[Pytree]:
        return self._residual

    def reset(self) -> None:
        self._residual = None

    def encode(self, delta: Pytree, key=None,
               is_delta: bool = True) -> CompressedTree:
        """Encode ``delta + residual``; keep the new residual for next round."""
        if self.codec.lossless:
            return self.codec.encode(delta, key=key, is_delta=is_delta)
        if self._residual is None:
            self._residual = jax.tree.map(
                lambda x: jax.numpy.zeros_like(x), delta)
        ct, self._residual = self.codec.encode(
            delta, key=key, is_delta=is_delta, residual=self._residual)
        return ct
