"""Compressed update transport — on-device codecs for federation payloads.

Quick tour::

    from fedml_tpu import compression

    codec = compression.get_codec("int8")          # None for ''/'none'
    ct = codec.encode(delta_tree, key=compression.derive_key(0, r, cid),
                      is_delta=True)               # ONE jitted program
    tree = codec.decode(ct)                        # full pytree back

    ef = compression.ErrorFeedback(codec)          # per-client residual
    ct = ef.encode(delta_tree, key=...)

The wire format is a versioned, codec-tagged extension of
``utils/serialization.safe_dumps`` — a :class:`CompressedTree` survives
every transport backend and unknown codec tags raise ``ValueError``.
See ``docs/compression.md`` for the codec matrix and semantics.
"""
from fedml_tpu.compression.codecs import (
    WIRE_VERSION,
    Codec,
    CompressedTree,
    available_codecs,
    derive_key,
    derive_key_data,
    derive_key_data_batch,
    fused_weighted_sum,
    get_codec,
    register_codec,
    tree_delta,
    tree_undelta,
)
from fedml_tpu.compression.error_feedback import ErrorFeedback


def requires_full_trees(codec=None) -> bool:
    """True when the server-side trust stack needs full per-client models.

    The dequant-fused aggregation path never materializes per-client f32
    trees — but model-poisoning attack injection, list-based defenses,
    central-DP clipping and FHE all operate on full client models, so
    when any of them is live the server decodes each update instead.

    Norm-ONLY defenses (norm-difference clipping) are exempt: their
    per-client norms read straight off the compressed blocks × scales
    (the same path the health tracker uses) and the clip factor folds
    into the fused aggregation weight — see
    ``FedMLDefender.fused_clip_factors``.

    FUSED robust defenses (coordinate-wise trimmed mean / median) are
    exempt too — but only for a caller that passes the ``codec`` its
    updates actually ride: those statistics are shift-equivariant, so on
    a DENSE codec they compute on the stacked compressed *deltas* inside
    one jitted reduction (``fedml_tpu.integrity.fused_robust_sum``) and
    resolve against the broadcast base — the same aggregation the decode
    fallback would produce on full models, without ever materializing N
    f32 client trees (``FedMLDefender.is_fused_defense``). Sparse
    codecs (top-k) cannot sort per coordinate, and a ``codec=None``
    caller has no fused path at all — both keep the decode fallback.
    """
    from fedml_tpu.core.dp.fedml_differential_privacy import (
        FedMLDifferentialPrivacy,
    )
    from fedml_tpu.core.fhe.fhe_agg import FedMLFHE
    from fedml_tpu.core.security.attacker import FedMLAttacker
    from fedml_tpu.core.security.defender import FedMLDefender

    dp = FedMLDifferentialPrivacy.get_instance()
    defender = FedMLDefender.get_instance()
    fused_capable = (codec is not None
                     and getattr(codec, "broadcast_safe", False)
                     and not getattr(codec, "maskable", False))
    return (
        FedMLFHE.get_instance().is_fhe_enabled()
        or FedMLAttacker.get_instance().is_model_attack()
        or (defender.is_defense_enabled()
            and not defender.is_norm_only_defense()
            and not (defender.is_fused_defense() and fused_capable))
        or (dp.is_dp_enabled() and dp.is_global_dp_enabled())
    )


__all__ = [
    "WIRE_VERSION",
    "Codec",
    "CompressedTree",
    "ErrorFeedback",
    "available_codecs",
    "derive_key",
    "derive_key_data",
    "derive_key_data_batch",
    "fused_weighted_sum",
    "get_codec",
    "register_codec",
    "requires_full_trees",
    "tree_delta",
    "tree_undelta",
]
