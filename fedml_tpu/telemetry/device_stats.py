"""Device/HBM introspection — phase-attributed memory gauges.

``device.memory_stats()`` is the accelerator's own allocator telemetry
(bytes in use, peak, limit on TPU/GPU); CPU backends return None, so the
sampler falls back to the two host-side signals that still move when HBM
would — the sum of live JAX buffer bytes (``jax.live_arrays``) and the
process RSS. Every sample is attributed to a *phase* (``train`` /
``aggregate`` / ``stage`` / ``train_agg`` / ``prefetch`` / ``eval``) so
staging-induced growth on the PR 2 prefetch worker is distinguishable
from model growth on the round path.

Each sample lands three ways:

- ``mem/*`` gauges in the metrics registry, labelled ``{phase, ...}``;
- one ``mem_sample`` event (with the round index) in
  ``<run_dir>/health.jsonl`` — the time series ``telemetry doctor`` fits
  its memory-growth slope over;
- the flight-recorder ring, so a crash dump shows where memory stood.

XLA compile-cache behaviour rides the same module: ``jax.monitoring``
listeners count compilation-cache hit/miss/request events
(``jax/compile_cache_*``; actual compiles are already the
``jax/compile_ms`` histogram's count), so
"round N recompiled" shows up as a counter step, not a mystery stall.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from fedml_tpu.telemetry import flight_recorder
from fedml_tpu.telemetry.registry import get_registry

__all__ = [
    "DeviceStatsSampler",
    "install_compile_cache_counters",
    "memory_snapshot",
    "sample_now",
]

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

_cache_counters_installed = False
_cache_counters_lock = threading.Lock()


def install_compile_cache_counters() -> None:
    """Count XLA compiles and compilation-cache traffic as typed counters.

    Installed once per process. The jax compilation-cache events — which
    differ across jax versions — are matched by substring so
    hits/misses/requests each land in their own counter on any 0.4.x.
    (The number of actual backend compiles is already the ``count`` of
    the ``jax/compile_ms`` histogram the span layer maintains — no
    second duration listener needed.)
    """
    global _cache_counters_installed
    with _cache_counters_lock:
        if _cache_counters_installed:
            return
        try:
            import jax.monitoring
        except ImportError:  # pragma: no cover - jax is a hard dep in-tree
            return

        def _on_event(event: str, **kw) -> None:
            if "cache_hit" in event:
                get_registry().counter("jax/compile_cache_hits").inc()
            elif "cache_miss" in event:
                get_registry().counter("jax/compile_cache_misses").inc()
            elif "compilation_cache" in event:
                get_registry().counter("jax/compile_cache_requests").inc()

        jax.monitoring.register_event_listener(_on_event)
        _cache_counters_installed = True


def _host_rss_bytes() -> float:
    """Current resident set size (Linux /proc; 0 where unreadable)."""
    try:
        with open("/proc/self/statm") as f:
            return float(int(f.read().split()[1]) * _PAGE_SIZE)
    except (OSError, ValueError, IndexError):
        return 0.0


def memory_snapshot() -> Dict[str, float]:
    """One cross-device memory reading, no gauges touched.

    ``bytes_in_use`` / ``peak_bytes`` / ``bytes_limit`` sum the per-device
    allocator stats where the backend exposes them (TPU/GPU) and stay 0
    on CPU; ``live_buffer_bytes`` (all live jax Arrays) and
    ``host_rss_bytes`` are always populated.
    """
    import jax

    in_use = peak = limit = 0.0
    have_device_stats = False
    for dev in jax.devices():
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        have_device_stats = True
        in_use += float(stats.get("bytes_in_use", 0) or 0)
        peak += float(stats.get("peak_bytes_in_use", 0) or 0)
        limit += float(stats.get("bytes_limit", 0) or 0)
    try:
        live = float(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:  # pragma: no cover - live_arrays is stable API
        live = 0.0
    snap = {
        "bytes_in_use": in_use,
        "peak_bytes": peak,
        "bytes_limit": limit,
        "live_buffer_bytes": live,
        "host_rss_bytes": _host_rss_bytes(),
        "device_stats_available": have_device_stats,
    }
    if limit > 0:
        snap["utilization"] = in_use / limit
    return snap


class DeviceStatsSampler:
    """Phase-attributed memory sampling for a round-based engine.

    ``min_interval_s`` rate-limits per phase so a tight loop (e.g. the
    async server's per-update path) cannot turn introspection into a
    hot-path cost; round loops sample every call by default.
    """

    def __init__(self, registry=None, min_interval_s: float = 0.0):
        # a pinned registry is honored; otherwise resolve per sample, so
        # the long-lived process-global sampler (the prefetch worker's)
        # follows registry resets instead of writing into a dead one
        self._pinned_reg = registry
        self.min_interval_s = float(min_interval_s)
        self._last_sample: Dict[str, float] = {}
        self._lock = threading.Lock()
        install_compile_cache_counters()

    @property
    def _reg(self):
        return self._pinned_reg or get_registry()

    def sample(self, phase: str, round_idx: Optional[int] = None,
               **extra: Any) -> Optional[Dict[str, float]]:
        now = time.time()
        with self._lock:
            last = self._last_sample.get(phase, 0.0)
            if self.min_interval_s and now - last < self.min_interval_s:
                return None
            self._last_sample[phase] = now
        snap = memory_snapshot()
        labels = {"phase": str(phase)}
        self._reg.gauge("mem/device_bytes_in_use", labels=labels).set(
            snap["bytes_in_use"])
        self._reg.gauge("mem/device_peak_bytes", labels=labels).set(
            snap["peak_bytes"])
        self._reg.gauge("mem/bytes_limit", labels=labels).set(
            snap["bytes_limit"])
        self._reg.gauge("mem/live_buffer_bytes", labels=labels).set(
            snap["live_buffer_bytes"])
        self._reg.gauge("mem/host_rss_bytes", labels=labels).set(
            snap["host_rss_bytes"])
        if "utilization" in snap:
            self._reg.gauge("mem/hbm_utilization", labels=labels).set(
                snap["utilization"])
        event = {"kind": "mem_sample", "phase": str(phase), **snap, **extra}
        if round_idx is not None:
            event["round"] = int(round_idx)
        from fedml_tpu.telemetry.health import log_health_event

        log_health_event(event)
        flight_recorder.record(**event)
        # phase samples double as the profile/* refresh tick: the program
        # catalog's live MFU/roofline gauges update on the same cadence
        # the mem/* gauges do, so the live plane streams both together
        from fedml_tpu.telemetry.profiling import pump_profile_gauges

        pump_profile_gauges()
        return snap


_default_sampler: Optional[DeviceStatsSampler] = None
_default_lock = threading.Lock()


def sample_now(phase: str, round_idx: Optional[int] = None,
               **extra: Any) -> Optional[Dict[str, float]]:
    """Sample through a shared process-global sampler — the entry point
    for call sites that don't own an engine (the prefetch worker)."""
    global _default_sampler
    with _default_lock:
        if _default_sampler is None:
            _default_sampler = DeviceStatsSampler()
        sampler = _default_sampler
    return sampler.sample(phase, round_idx, **extra)
