"""Causal round tracing: assembly, clock alignment, critical path.

The federation writes spans per process; this package joins them into one
happens-before-ordered timeline (:mod:`.assemble`), places every node's
clock on the reference timeline with an explicit uncertainty bound
(:mod:`.clock`), extracts and attributes each round's critical path
(:mod:`.critical_path`), streams span batches over the live plane for
distributed runs (:mod:`.stream`), and exports Perfetto/Chrome
trace-event JSON (:mod:`.perfetto`).
"""
from fedml_tpu.telemetry.tracing.assemble import (
    REMOTE_SPANS_FILENAME,
    AssembledTrace,
    TraceSpan,
    assemble_records,
    assemble_trace,
    load_trace_records,
)
from fedml_tpu.telemetry.tracing.clock import NodeClock, align_clocks
from fedml_tpu.telemetry.tracing.critical_path import (
    RoundCriticalPath,
    Segment,
    compute_critical_path,
    compute_critical_paths,
    phase_of,
    summarize_critical_paths,
)
from fedml_tpu.telemetry.tracing.perfetto import (
    export_perfetto,
    write_perfetto,
)
from fedml_tpu.telemetry.tracing.stream import (
    PHASE_CODES,
    SpanStreamer,
    TraceCollector,
    phase_code,
    phase_label,
)

__all__ = [
    "REMOTE_SPANS_FILENAME",
    "AssembledTrace",
    "TraceSpan",
    "assemble_records",
    "assemble_trace",
    "load_trace_records",
    "NodeClock",
    "align_clocks",
    "RoundCriticalPath",
    "Segment",
    "compute_critical_path",
    "compute_critical_paths",
    "phase_of",
    "summarize_critical_paths",
    "export_perfetto",
    "write_perfetto",
    "PHASE_CODES",
    "SpanStreamer",
    "TraceCollector",
    "phase_code",
    "phase_label",
]
