"""Per-round critical-path extraction and slack attribution.

Given an assembled trace, walk *backward* from the round's last-finishing
span (normally the server eval) along the chain of causes: into the
latest-finishing same-node child, across ``remote_parent`` stitch points
via the matching ``comm/send`` event (a **wire** edge), up to local
parents, until the chain leaves the round (a parent belonging to an
earlier round) or runs out of causes. Every step emits a segment, and the
segments exactly tile the interval from chain start to round end — so
their durations sum to the measured round wall by construction.

Each segment is attributed: node, phase, span, kind (``compute`` for real
work, ``queue`` for dispatch/handler framing and causal gaps, ``wire``
for cross-process message latency), and — when a program catalog is
available — the dominant XLA program of its phase.

Slack analysis answers the "so what": per-round client upload arrival
spread gives the what-if saving of removing the straggler (the round can
only close when its last *required* upload lands), and wire share says
whether compression beats rescheduling. A straggler that the quorum or
deadline path already excluded shows up here as "straggler with slack":
slow, but not what bounded the round.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from fedml_tpu.telemetry.tracing.assemble import AssembledTrace, TraceSpan

_EPS = 1e-6  # seconds; below this a segment is noise, not attribution
_MAX_STEPS = 100_000

# wire segments and causal-gap bridges synthesized by the walk
KIND_COMPUTE = "compute"
KIND_QUEUE = "queue"
KIND_WIRE = "wire"


class Segment:
    """One contiguous slice of the round's critical path."""

    __slots__ = ("node", "span_name", "phase", "kind", "t0", "t1",
                 "client", "program", "flags")

    def __init__(self, node: str, span_name: str, phase: str, kind: str,
                 t0: float, t1: float, client: Optional[str] = None,
                 program: Optional[str] = None,
                 flags: Optional[List[str]] = None):
        self.node = node
        self.span_name = span_name
        self.phase = phase
        self.kind = kind
        self.t0 = t0
        self.t1 = t1
        self.client = client
        self.program = program
        self.flags = flags or []

    @property
    def duration_ms(self) -> float:
        return (self.t1 - self.t0) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "node": self.node, "span": self.span_name, "phase": self.phase,
            "kind": self.kind, "t0": self.t0, "t1": self.t1,
            "duration_ms": round(self.duration_ms, 3),
        }
        if self.client is not None:
            d["client"] = self.client
        if self.program is not None:
            d["program"] = self.program
        if self.flags:
            d["flags"] = list(self.flags)
        return d


def phase_of(name: str) -> str:
    """Collapse a span name to its phase label: the trailing component of
    ``round/<n>[/client/<id>]/<phase>``; ``comm/*`` spans are dispatch
    framing; anything else keeps its own name."""
    if name.startswith("round/"):
        return name.rsplit("/", 1)[-1]
    if name.startswith("comm/"):
        return "dispatch"
    return name


def _kind_of(span: TraceSpan) -> str:
    return KIND_QUEUE if span.name.startswith("comm/") else KIND_COMPUTE


class RoundCriticalPath:
    """The walk result for one round."""

    def __init__(self, round_idx: int, segments: List[Segment],
                 anchor: TraceSpan, wall_ms: float,
                 flags: List[str], straggler: Optional[Dict[str, Any]]):
        self.round = round_idx
        self.segments = segments
        self.anchor = anchor
        self.wall_ms = wall_ms
        self.flags = flags
        self.straggler = straggler

    @property
    def total_ms(self) -> float:
        return sum(s.duration_ms for s in self.segments)

    def by_kind(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for s in self.segments:
            out[s.kind] = out.get(s.kind, 0.0) + s.duration_ms
        return out

    def by_phase(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for s in self.segments:
            out[s.phase] = out.get(s.phase, 0.0) + s.duration_ms
        return out

    def by_node(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for s in self.segments:
            out[s.node] = out.get(s.node, 0.0) + s.duration_ms
        return out

    def top_phase(self) -> Optional[str]:
        phases = self.by_phase()
        if not phases:
            return None
        return max(sorted(phases), key=lambda p: phases[p])

    def top_share(self) -> float:
        phases = self.by_phase()
        total = self.total_ms
        if not phases or total <= 0:
            return 0.0
        return max(phases.values()) / total

    def clients_on_path(self) -> List[str]:
        return sorted({s.client for s in self.segments
                       if s.client is not None})

    def to_dict(self) -> Dict[str, Any]:
        return {
            "round": self.round,
            "wall_ms": round(self.wall_ms, 3),
            "path_ms": round(self.total_ms, 3),
            "coverage": (round(self.total_ms / self.wall_ms, 4)
                         if self.wall_ms > 0 else None),
            "anchor": self.anchor.name,
            "by_kind": {k: round(v, 3) for k, v in self.by_kind().items()},
            "by_phase": {k: round(v, 3)
                         for k, v in sorted(self.by_phase().items())},
            "by_node": {k: round(v, 3) for k, v in self.by_node().items()},
            "top_phase": self.top_phase(),
            "top_share": round(self.top_share(), 4),
            "clients_on_path": self.clients_on_path(),
            "straggler": self.straggler,
            "segments": [s.to_dict() for s in self.segments],
            "flags": list(self.flags),
        }


def _emit(segments: List[Segment], span: TraceSpan, lo: float, hi: float,
          flags: Optional[List[str]] = None) -> None:
    if hi - lo <= _EPS:
        return
    segments.append(Segment(span.node, span.name, phase_of(span.name),
                            _kind_of(span), lo, hi, client=span.client,
                            flags=flags))


def _walk(trace: AssembledTrace, round_idx: int,
          anchor: TraceSpan, round_spans: List[TraceSpan]):
    """Backward walk from ``anchor.t1``; returns (segments, flags) with
    segments in chronological order, exactly tiling the covered interval.
    """
    segments: List[Segment] = []
    flags: List[str] = []
    descended = {anchor.span_id}
    current, t = anchor, anchor.t1
    for _ in range(_MAX_STEPS):
        # 1. descend into the latest same-node child finishing before t —
        # its completion is what unblocked the remainder of `current`
        kids = [k for k in trace.children.get(current.span_id, ())
                if k.node == current.node and k.span_id not in descended
                and k.t1 <= t + _EPS and k.t1 >= current.t0 - _EPS]
        if kids:
            k = max(kids, key=lambda s: s.t1)
            _emit(segments, current, k.t1, t)
            descended.add(k.span_id)
            current, t = k, min(k.t1, t)
            continue
        # 2. nothing left inside: attribute down to the span start
        _emit(segments, current, current.t0, t)
        t = min(t, current.t0)
        # 3. cross the start edge
        if current.remote_parent:
            msg_id = (current.attrs or {}).get("msg_id")
            send_ev = (trace.send_event_for(str(msg_id))
                       if msg_id else None)
            parent = (trace.by_id.get(current.parent_id)
                      if current.parent_id else None)
            if send_ev is not None:
                t_send = min(float(send_ev["t"]), t)
                if t - t_send > _EPS:
                    segments.append(Segment(
                        f"{send_ev['node']}->{current.node}",
                        current.name, "wire", KIND_WIRE, t_send, t))
                t = t_send
                if parent is None:
                    # the send event recorded the sender's open span id
                    sid = send_ev.get("span_id")
                    parent = trace.by_id.get(str(sid)) if sid else None
            else:
                flags.append("unmatched_send:" + current.name)
            if parent is None:
                flags.append("truncated:" + current.name)
                break
            if parent.round is not None and parent.round < round_idx:
                break  # the chain left the round: previous round's work
            current, t = parent, min(t, parent.t1)
            continue
        if current.parent_id:
            parent = trace.by_id.get(current.parent_id)
            if parent is None:
                flags.append("truncated:" + current.name)
                break
            if parent.round is not None and parent.round < round_idx:
                break
            current = parent
            continue
        # 4. root with no parent (loop-style engines emit sibling round
        # spans with no shared ancestor): bridge the causal gap to the
        # latest earlier same-node round span
        cands = [s for s in round_spans
                 if s.node == current.node and s.span_id not in descended
                 and s.t1 <= t + _EPS]
        if not cands:
            break
        k = max(cands, key=lambda s: s.t1)
        if t - k.t1 > _EPS:
            segments.append(Segment(current.node, current.name, "gap",
                                    KIND_QUEUE, k.t1, t))
        descended.add(k.span_id)
        current, t = k, min(k.t1, t)
    segments.reverse()
    return segments, flags


def _round_arrivals(trace: AssembledTrace, round_idx: int
                    ) -> Dict[str, float]:
    """Latest aligned receive time per peer for this round's messages at
    the reference (server) node — the upload-arrival spread."""
    arrivals: Dict[str, float] = {}
    for evs in trace.recvs.values():
        for ev in evs:
            if ev["node"] != trace.ref_node:
                continue
            attrs = ev.get("attrs") or {}
            try:
                ev_round = int(attrs.get("round"))
            except (TypeError, ValueError):
                continue
            if ev_round != round_idx or attrs.get("peer") is None:
                continue
            peer = str(attrs["peer"])
            arrivals[peer] = max(arrivals.get(peer, float("-inf")),
                                 float(ev["t"]))
    return arrivals


def _straggler_analysis(trace: AssembledTrace, round_idx: int,
                        segments: List[Segment]) -> Optional[Dict[str, Any]]:
    arrivals = _round_arrivals(trace, round_idx)
    if len(arrivals) < 2:
        return None
    ordered = sorted(arrivals.items(), key=lambda kv: kv[1])
    worst, worst_t = ordered[-1]
    second_t = ordered[-2][1]
    on_path = {s.client for s in segments if s.client is not None}
    wire_ms = sum(s.duration_ms for s in segments if s.kind == KIND_WIRE)
    return {
        "client": worst,
        "on_critical_path": worst in on_path,
        # the round closes on its last required upload: removing the
        # straggler can save at most the arrival gap to the runner-up
        "savings_ms": round((worst_t - second_t) * 1e3, 3),
        "wire_ms": round(wire_ms, 3),
        "arrivals": len(arrivals),
    }


def compute_critical_path(trace: AssembledTrace, round_idx: int,
                          programs: Optional[List[Dict[str, Any]]] = None
                          ) -> Optional[RoundCriticalPath]:
    """The critical path of one round, or None when the round has no
    spans. ``programs`` (loaded ``programs.jsonl`` records) attaches the
    dominant XLA program to each compute segment's phase."""
    round_spans = [s for s in trace.rounds.get(round_idx, ())
                   if "/prefetch" not in s.name]
    if not round_spans:
        return None
    anchor = max(round_spans, key=lambda s: s.t1)
    segments, flags = _walk(trace, round_idx, anchor, round_spans)
    wall_ms = (anchor.t1 - min(s.t0 for s in round_spans)) * 1e3
    if programs:
        _attach_programs(segments, programs)
    unaligned = [c.node for c in trace.clocks.values()
                 if c.method == "unaligned"]
    if unaligned and len(trace.clocks) > 1:
        flags.append("unaligned_nodes:" + ",".join(sorted(unaligned)))
    straggler = _straggler_analysis(trace, round_idx, segments)
    return RoundCriticalPath(round_idx, segments, anchor, wall_ms, flags,
                             straggler)


def compute_critical_paths(trace: AssembledTrace,
                           rounds: Optional[List[int]] = None,
                           programs: Optional[List[Dict[str, Any]]] = None
                           ) -> List[RoundCriticalPath]:
    out = []
    for r in (rounds if rounds is not None else trace.round_indexes()):
        cp = compute_critical_path(trace, r, programs=programs)
        if cp is not None:
            out.append(cp)
    return out


def _attach_programs(segments: List[Segment],
                     programs: List[Dict[str, Any]]) -> None:
    """Join the PR 10 catalog: each phase's dominant program (most calls
    attributed there) labels that phase's compute segments."""
    from fedml_tpu.telemetry.report import normalize_name

    best: Dict[str, tuple] = {}
    for rec in programs:
        name = rec.get("name")
        for phase, calls in (rec.get("phase_calls") or {}).items():
            calls = int(calls or 0)
            if name and calls > best.get(phase, (0, ""))[0]:
                best[phase] = (calls, str(name))
    for seg in segments:
        if seg.kind != KIND_COMPUTE:
            continue
        hit = best.get(normalize_name(seg.span_name))
        if hit:
            seg.program = hit[1]


def summarize_critical_paths(cps: List[RoundCriticalPath]
                             ) -> Dict[str, Any]:
    """The report/doctor-facing rollup: per-round rows plus whole-run
    kind/phase decomposition."""
    rounds = []
    kind_totals: Dict[str, float] = {}
    phase_totals: Dict[str, float] = {}
    for cp in cps:
        d = cp.to_dict()
        d.pop("segments")  # rows stay table-sized; full detail via trace CLI
        rounds.append(d)
        for k, v in cp.by_kind().items():
            kind_totals[k] = kind_totals.get(k, 0.0) + v
        for k, v in cp.by_phase().items():
            phase_totals[k] = phase_totals.get(k, 0.0) + v
    total = sum(kind_totals.values())
    return {
        "rounds": rounds,
        "by_kind_ms": {k: round(v, 3)
                       for k, v in sorted(kind_totals.items())},
        "by_phase_ms": {k: round(v, 3)
                        for k, v in sorted(phase_totals.items())},
        "total_ms": round(total, 3),
    }
