"""Live span-batch streaming: bounded frames over the PR 8 live plane.

``SpanStreamer`` taps the process-global span listeners
(:func:`fedml_tpu.telemetry.spans.add_span_listener`) and buffers every
completed span / point event in a bounded ring, each entry carrying an
absolute, per-node, monotonically increasing index. Frames ship either
piggybacked on outgoing comm messages (the cross-silo client path — same
split as ``MetricStreamer``) or via a dedicated ``send_cb`` carrier (the
serving endpoint path):

- a *delta* frame carries the unsent contiguous index range (capped per
  frame);
- every ``resync_every``-th frame — and the final flush — is a *FULL*
  frame carrying the whole ring, so dropped frames heal without acks.

``TraceCollector`` merges frames idempotently **by absolute index**:
duplicates overwrite themselves, reordering is irrelevant, and drops are
healed by the next full frame — chaos-grade delivery converges to the
identical record set (and therefore the identical critical path) as
loss-free delivery. Only records evicted from the ring before ever being
shipped are truly lost, and those are counted (``tracepath/
records_dropped``).
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

FRAME_KIND = "trace"
FRAME_VERSION = 1

# phase -> integer code for the registry (gauges are numeric-only); the
# watch column and doctor decode through phase_label().
PHASE_CODES: Dict[str, int] = {
    "sync": 0, "train": 1, "aggregate": 2, "eval": 3, "wire": 4,
    "dispatch": 5, "gap": 6, "sample": 7, "stage": 8, "other": 9,
}
_PHASE_LABELS = {v: k for k, v in PHASE_CODES.items()}


def phase_code(phase: Optional[str]) -> int:
    return PHASE_CODES.get(phase or "other", PHASE_CODES["other"])


def phase_label(code: float) -> str:
    return _PHASE_LABELS.get(int(code), "other")


def frame_nbytes(frame: Dict[str, Any]) -> int:
    try:
        return len(json.dumps(frame, default=str))
    except (TypeError, ValueError):
        return 0


class SpanStreamer:
    """Span-record ring with seq-numbered, drop-tolerant frame emission."""

    def __init__(self, node: str, job: str = "", interval_s: float = 1.0,
                 ring: int = 4096, max_batch: int = 256,
                 resync_every: int = 8,
                 send_cb: Optional[Callable[[Dict[str, Any]], None]] = None,
                 registry: Any = None):
        self.node = node
        self.job = job
        self.interval_s = max(float(interval_s), 0.05)
        self._ring_cap = max(int(ring), 8)
        self._max_batch = max(int(max_batch), 1)
        self._resync_every = max(int(resync_every), 2)
        self._send_cb = send_cb
        self._lock = threading.Lock()
        self._ring: "deque[tuple]" = deque()  # (abs_idx, record)
        self._next_idx = 0
        self._sent_upto = 0  # first index not yet emitted in any frame
        self._seq = 0
        self._last_emit = 0.0
        self._force_full = False
        self._attached = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from fedml_tpu.telemetry.registry import get_registry

            registry = get_registry()
        self._c_frames = registry.counter("tracepath/frames_emitted")
        self._c_bytes = registry.counter("tracepath/frame_bytes")
        self._c_shipped = registry.counter("tracepath/records_shipped")
        self._c_dropped = registry.counter("tracepath/records_dropped")

    # -- record intake -----------------------------------------------------
    def on_record(self, rec: Dict[str, Any]) -> None:
        """Span-listener callback; must never raise (and the listener
        dispatch swallows anyway)."""
        with self._lock:
            self._ring.append((self._next_idx, dict(rec)))
            self._next_idx += 1
            while len(self._ring) > self._ring_cap:
                idx, _ = self._ring.popleft()
                if idx >= self._sent_upto:
                    # evicted before any frame carried it: unrecoverable
                    self._c_dropped.inc()
                    self._sent_upto = idx + 1

    def attach(self) -> "SpanStreamer":
        """Register on the process span listeners (idempotent)."""
        if not self._attached:
            from fedml_tpu.telemetry import spans as _spans

            _spans.add_span_listener(self.on_record)
            self._attached = True
        return self

    def detach(self) -> None:
        if self._attached:
            from fedml_tpu.telemetry import spans as _spans

            _spans.remove_span_listener(self.on_record)
            self._attached = False

    # -- frame emission ----------------------------------------------------
    def _due_full(self) -> bool:
        return self._force_full or (self._seq + 1) % self._resync_every == 0

    def pop_frame(self, force: bool = False) -> Optional[Dict[str, Any]]:
        """The next frame, or None when rate-limited / nothing new.

        Callers on the piggyback path call this per outgoing message; the
        interval gate keeps one frame per ``interval_s`` regardless of
        message rate.
        """
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_emit < self.interval_s:
                return None
            full = self._due_full()
            if full:
                batch = list(self._ring)
            else:
                batch = [(i, r) for i, r in self._ring
                         if i >= self._sent_upto][: self._max_batch]
            if not batch:
                return None
            base = batch[0][0]
            self._sent_upto = max(self._sent_upto, base + len(batch))
            self._seq += 1
            seq = self._seq
            self._force_full = False
            self._last_emit = now
        frame = {
            "kind": FRAME_KIND, "v": FRAME_VERSION, "node": self.node,
            "job": self.job, "seq": seq, "base": base, "full": full,
            "records": [r for _, r in batch],
        }
        self._c_frames.inc()
        self._c_bytes.inc(frame_nbytes(frame))
        self._c_shipped.inc(len(batch))
        return frame

    def pump(self, collector: "TraceCollector", force: bool = True) -> bool:
        """Synchronous snapshot->frame->ingest (loopback and tests)."""
        frame = self.pop_frame(force=force)
        if frame is None:
            return False
        return collector.ingest(frame)

    def flush_final(self) -> None:
        """Arm a FULL frame so the next pop re-ships the whole ring —
        called right before the last messages of a run go out."""
        with self._lock:
            self._force_full = True
            self._last_emit = 0.0

    # -- dedicated carrier -------------------------------------------------
    def start(self) -> "SpanStreamer":
        self.attach()
        if self._send_cb is not None and self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name=f"span-streamer-{self.node}",
                                            daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            frame = self.pop_frame(force=True)
            if frame is not None:
                try:
                    self._send_cb(frame)
                except Exception:  # noqa: BLE001 - carrier must not die
                    pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.detach()

    def close(self) -> Optional[Dict[str, Any]]:
        """Stop the loop and emit one final FULL frame (delivered through
        ``send_cb`` when set; also returned for loopback ingestion)."""
        self.stop()
        with self._lock:
            self._force_full = True
            self._last_emit = 0.0
        frame = self.pop_frame(force=True)
        if frame is not None and self._send_cb is not None:
            try:
                self._send_cb(frame)
            except Exception:  # noqa: BLE001
                pass
        return frame


class TraceCollector:
    """Merges span-batch frames from every node, idempotently by index."""

    def __init__(self, job: str = "", registry: Any = None):
        self.job = job
        self._lock = threading.Lock()
        # node -> {"records": {abs_idx: rec}, "last_seq": int}
        self._nodes: Dict[str, Dict[str, Any]] = {}
        if registry is None:
            from fedml_tpu.telemetry.registry import get_registry

            registry = get_registry()
        self._c_ingested = registry.counter("tracepath/frames_ingested")
        self._c_dup = registry.counter("tracepath/frames_duplicate")
        self._c_gaps = registry.counter("tracepath/seq_gaps")
        self._c_merged = registry.counter("tracepath/records_merged")

    def ingest(self, frame: Any) -> bool:
        if not isinstance(frame, dict) or frame.get("kind") != FRAME_KIND:
            return False
        if int(frame.get("v", -1)) != FRAME_VERSION:
            return False
        node = frame.get("node")
        records = frame.get("records")
        if not node or not isinstance(records, list):
            return False
        if self.job and frame.get("job") and frame["job"] != self.job:
            return False  # a stale run's frames must not pollute this one
        try:
            seq = int(frame.get("seq", 0))
            base = int(frame.get("base", 0))
        except (TypeError, ValueError):
            return False
        merged = 0
        with self._lock:
            st = self._nodes.setdefault(str(node),
                                        {"records": {}, "last_seq": 0})
            if seq <= st["last_seq"]:
                # duplicate / reordered frame: counted, but still merged —
                # the index keys make re-application a no-op
                self._c_dup.inc()
            elif seq > st["last_seq"] + 1:
                self._c_gaps.inc(seq - st["last_seq"] - 1)
            st["last_seq"] = max(st["last_seq"], seq)
            store = st["records"]
            for i, rec in enumerate(records):
                if not isinstance(rec, dict):
                    continue
                idx = base + i
                if idx not in store:
                    store[idx] = rec
                    merged += 1
        self._c_ingested.inc()
        if merged:
            self._c_merged.inc(merged)
        return True

    def records(self) -> List[Dict[str, Any]]:
        """Every merged record, node-stamped, in per-node index order."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for node in sorted(self._nodes):
                store = self._nodes[node]["records"]
                for idx in sorted(store):
                    rec = dict(store[idx])
                    rec.setdefault("node", node)
                    out.append(rec)
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {node: {"records": len(st["records"]),
                           "last_seq": st["last_seq"]}
                    for node, st in self._nodes.items()}

    def persist(self, run_dir: str,
                filename: Optional[str] = None) -> Optional[str]:
        """Land the merged set as a node-annotated JSONL next to the local
        sink (rewritten whole — the merge is the source of truth)."""
        import os

        from fedml_tpu.telemetry.tracing.assemble import (
            REMOTE_SPANS_FILENAME,
        )

        records = self.records()
        if not records:
            return None
        os.makedirs(run_dir, exist_ok=True)
        path = os.path.join(run_dir, filename or REMOTE_SPANS_FILENAME)
        with open(path, "w") as f:
            for rec in records:
                f.write(json.dumps(rec, default=str) + "\n")
        return path
