"""Perfetto / Chrome trace-event JSON export of an assembled trace.

Emits the stable subset of the trace-event format both ``ui.perfetto.dev``
and ``chrome://tracing`` load:

- one *process* per federation node (``M``/``process_name`` metadata),
  with spans laid out as complete slices (``ph: "X"``, microsecond
  ``ts``/``dur`` relative to the earliest aligned span start);
- overlapping spans on a node spread across greedy *thread* lanes so
  concurrent handler dispatches render side by side instead of garbled;
- cross-process message edges as flow events (``ph: "s"`` at the send
  point, ``ph: "f", bp: "e"`` binding to the receiving dispatch slice),
  keyed per ``msg_id``;
- optionally, the computed critical path as an extra synthetic process so
  the bounding chain reads as one contiguous track above the real spans.
"""
from __future__ import annotations

import json
import zlib
from typing import Any, Dict, List, Optional

from fedml_tpu.telemetry.tracing.assemble import AssembledTrace, TraceSpan
from fedml_tpu.telemetry.tracing.critical_path import RoundCriticalPath

_CP_PID = 0  # synthetic critical-path track renders above real nodes


def _lanes(spans: List[TraceSpan]) -> Dict[str, int]:
    """Greedy interval-graph coloring: span_id -> lane (tid)."""
    lanes_end: List[float] = []
    assignment: Dict[str, int] = {}
    for s in sorted(spans, key=lambda x: (x.t0, -x.t1)):
        for i, end in enumerate(lanes_end):
            if end <= s.t0 + 1e-9:
                lanes_end[i] = s.t1
                assignment[s.span_id] = i + 1
                break
        else:
            lanes_end.append(s.t1)
            assignment[s.span_id] = len(lanes_end)
    return assignment


def _flow_id(msg_id: str) -> int:
    return zlib.crc32(str(msg_id).encode()) & 0x7FFFFFFF


def export_perfetto(trace: AssembledTrace,
                    critical_paths: Optional[List[RoundCriticalPath]] = None,
                    rounds: Optional[List[int]] = None) -> Dict[str, Any]:
    """Build the trace-event dict (callers json.dump it themselves)."""
    spans = trace.spans
    if rounds is not None:
        keep = set(rounds)
        spans = [s for s in spans if s.round in keep]
    events: List[Dict[str, Any]] = []
    if not spans:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    base = min(s.t0 for s in spans)

    def us(t: float) -> float:
        return round((t - base) * 1e6, 3)

    pids = {node: i + 1 for i, node in
            enumerate(sorted({s.node for s in spans}))}
    for node, pid in pids.items():
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": f"node:{node}"}})
    by_node: Dict[str, List[TraceSpan]] = {}
    for s in spans:
        by_node.setdefault(s.node, []).append(s)
    lanes: Dict[str, int] = {}
    for node, node_spans in by_node.items():
        lanes.update(_lanes(node_spans))

    for s in spans:
        args: Dict[str, Any] = {"span_id": s.span_id, "node": s.node}
        if s.round is not None:
            args["round"] = s.round
        if s.attrs:
            args.update({k: v for k, v in s.attrs.items()
                         if isinstance(v, (str, int, float, bool))})
        events.append({
            "ph": "X", "name": s.name, "pid": pids[s.node],
            "tid": lanes.get(s.span_id, 1), "ts": us(s.t0),
            "dur": round(s.duration_ms * 1e3, 3), "cat": "span",
            "args": args,
        })

    # message flows: send point -> receiving dispatch slice start
    for msg_id, recvs in trace.recvs.items():
        send = trace.send_event_for(msg_id)
        if send is None:
            continue
        send_node = send["node"]
        sid = str(send.get("span_id") or "")
        send_tid = lanes.get(sid, 1)
        fid = _flow_id(msg_id)
        events.append({"ph": "s", "name": "msg", "cat": "comm", "id": fid,
                       "pid": pids.get(send_node, 1), "tid": send_tid,
                       "ts": us(float(send["t"]))})
        for span in trace.spans:
            if (span.attrs or {}).get("msg_id") == msg_id \
                    and span.remote_parent:
                events.append({"ph": "f", "bp": "e", "name": "msg",
                               "cat": "comm", "id": fid,
                               "pid": pids.get(span.node, 1),
                               "tid": lanes.get(span.span_id, 1),
                               "ts": us(span.t0)})
                break

    if critical_paths:
        events.append({"ph": "M", "name": "process_name", "pid": _CP_PID,
                       "tid": 0, "args": {"name": "critical path"}})
        for cp in critical_paths:
            if rounds is not None and cp.round not in set(rounds):
                continue
            for seg in cp.segments:
                events.append({
                    "ph": "X",
                    "name": f"{seg.phase} [{seg.kind}]",
                    "pid": _CP_PID, "tid": cp.round + 1,
                    "ts": us(seg.t0),
                    "dur": round(seg.duration_ms * 1e3, 3),
                    "cat": "critical-path",
                    "args": {"round": cp.round, "node": seg.node,
                             "span": seg.span_name, "kind": seg.kind},
                })
            events.append({"ph": "M", "name": "thread_name", "pid": _CP_PID,
                           "tid": cp.round + 1,
                           "args": {"name": f"round {cp.round}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_perfetto(trace: AssembledTrace, path: str,
                   critical_paths: Optional[List[RoundCriticalPath]] = None,
                   rounds: Optional[List[int]] = None) -> str:
    doc = export_perfetto(trace, critical_paths=critical_paths,
                          rounds=rounds)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
