"""NTP-style clock alignment from matched comm send/recv pairs.

Every node stamps its span and event records with its own ``time.time()``
wall clock; nothing guarantees those clocks agree. But every comm message
gives us a one-way delay sample: the publisher records a ``comm/send``
point event and the subscriber a ``comm/recv`` point event for the same
``msg_id``. Taking the *minimum* observed delay in each direction filters
queueing noise (the classic NTP minimum-filter), leaving::

    d_fwd = min(recv_X - send_ref)  ~=  L_min + theta
    d_rev = min(recv_ref - send_X)  ~=  L_min - theta

where ``theta`` is node X's clock offset relative to the reference node
and ``L_min`` the (assumed symmetric) minimum one-way latency. Hence::

    theta       = (d_fwd - d_rev) / 2
    uncertainty = (d_fwd + d_rev) / 2     (= L_min, an upper bound on the
                                           asymmetry error)

A node seen in only one direction degrades to ``one_way`` alignment
(offset = the one-way delay, uncertainty = its magnitude); a node with no
matched pairs at all stays ``unaligned`` (offset 0, uncertainty None) —
consumers must treat its placement as wall-clock faith.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional


class NodeClock:
    """Offset of one node's wall clock relative to the reference node.

    ``aligned_time = wall_time - offset_s`` places this node's records on
    the reference timeline, within ``+/- uncertainty_s``.
    """

    __slots__ = ("node", "offset_s", "uncertainty_s", "method", "pairs")

    def __init__(self, node: str, offset_s: float = 0.0,
                 uncertainty_s: Optional[float] = None,
                 method: str = "unaligned", pairs: int = 0):
        self.node = node
        self.offset_s = offset_s
        self.uncertainty_s = uncertainty_s
        self.method = method
        self.pairs = pairs

    def align(self, wall_ts: float) -> float:
        return wall_ts - self.offset_s

    def to_dict(self) -> Dict[str, Any]:
        return {
            "node": self.node,
            "offset_ms": round(self.offset_s * 1e3, 3),
            "uncertainty_ms": (round(self.uncertainty_s * 1e3, 3)
                               if self.uncertainty_s is not None else None),
            "method": self.method,
            "pairs": self.pairs,
        }


def _min_delay(samples: List[float]) -> Optional[float]:
    return min(samples) if samples else None


def align_clocks(send_events: Dict[str, List[dict]],
                 recv_events: Dict[str, List[dict]],
                 ref_node: str) -> Dict[str, "NodeClock"]:
    """Estimate per-node clock offsets against ``ref_node``.

    ``send_events`` / ``recv_events`` map ``msg_id -> [event dicts]``
    where each event carries ``node`` and ``ts`` (sender wall clock /
    receiver wall clock). Returns a ``NodeClock`` for every node seen in
    either stream; the reference node gets offset 0 / uncertainty 0.
    """
    # direction samples per non-reference node
    fwd: Dict[str, List[float]] = {}  # ref sent -> node received
    rev: Dict[str, List[float]] = {}  # node sent -> ref received
    nodes = set()
    for msg_id, sends in send_events.items():
        recvs = recv_events.get(msg_id) or []
        for s in sends:
            nodes.add(s["node"])
            for r in recvs:
                nodes.add(r["node"])
                delay = float(r["ts"]) - float(s["ts"])
                if s["node"] == ref_node and r["node"] != ref_node:
                    fwd.setdefault(r["node"], []).append(delay)
                elif s["node"] != ref_node and r["node"] == ref_node:
                    rev.setdefault(s["node"], []).append(delay)
    for recvs in recv_events.values():
        for r in recvs:
            nodes.add(r["node"])

    clocks: Dict[str, NodeClock] = {
        ref_node: NodeClock(ref_node, 0.0, 0.0, "reference")
    }
    for node in sorted(nodes):
        if node == ref_node:
            continue
        d_fwd = _min_delay(fwd.get(node, []))
        d_rev = _min_delay(rev.get(node, []))
        n_pairs = len(fwd.get(node, [])) + len(rev.get(node, []))
        if d_fwd is not None and d_rev is not None:
            theta = (d_fwd - d_rev) / 2.0
            unc = max((d_fwd + d_rev) / 2.0, 0.0)
            clocks[node] = NodeClock(node, theta, unc, "paired", n_pairs)
        elif d_fwd is not None:
            clocks[node] = NodeClock(node, d_fwd, abs(d_fwd), "one_way",
                                     n_pairs)
        elif d_rev is not None:
            clocks[node] = NodeClock(node, -d_rev, abs(d_rev), "one_way",
                                     n_pairs)
        else:
            clocks[node] = NodeClock(node)
    return clocks
