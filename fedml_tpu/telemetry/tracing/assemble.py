"""Federation-wide trace assembly: every node's spans on one timeline.

Each process lands its own span stream (``spans.jsonl``, plus the point
events ``Tracer.event`` emits into the same sink); distributed runs
additionally ship span-batch frames over the live plane, which the
collector persists node-annotated as ``spans_remote.jsonl``. Assembly:

1. load + normalize records from both sinks (a record's node identity is
   its ``node`` stamp, else its ``service``, else ``"local"``);
2. align clocks: match ``comm/send``/``comm/recv`` point events by
   ``msg_id`` across nodes and run the NTP-style minimum-RTT estimator
   (:mod:`.clock`), anchored at the reference node (the one that runs
   ``round/<n>/aggregate`` — the server);
3. place every span on the aligned timeline (``t0``/``t1`` in reference
   wall seconds) and index it: by span id, by parent (causal children),
   by round, plus send/recv event indexes by ``msg_id``.

The result is the happens-before-ordered round timeline the critical-path
engine (:mod:`.critical_path`) walks and the Perfetto exporter
(:mod:`.perfetto`) renders.
"""
from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional

from fedml_tpu.telemetry.tracing.clock import NodeClock, align_clocks

_ROUND_RE = re.compile(r"^round/(\d+)(?:/|$)")
_CLIENT_RE = re.compile(r"^round/\d+/client/([^/]+)/")

REMOTE_SPANS_FILENAME = "spans_remote.jsonl"


def _record_node(rec: Dict[str, Any]) -> str:
    return str(rec.get("node") or rec.get("service") or "local")


class TraceSpan:
    """One completed span, normalized and placed on the aligned timeline."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "node",
                 "started", "ended", "duration_ms", "remote_parent",
                 "attrs", "compile_ms", "round", "client", "t0", "t1",
                 "has_mono")

    def __init__(self, rec: Dict[str, Any]):
        self.name = str(rec.get("name", ""))
        self.trace_id = str(rec.get("trace_id", ""))
        self.span_id = str(rec.get("span_id", ""))
        pid = rec.get("parent_id")
        self.parent_id = str(pid) if pid else None
        self.node = _record_node(rec)
        self.started = float(rec.get("started", 0.0))
        self.duration_ms = float(rec.get("duration_ms", 0.0))
        self.ended = float(rec.get("ended",
                                   self.started + self.duration_ms / 1e3))
        self.remote_parent = bool(rec.get("remote_parent"))
        self.attrs = rec.get("attrs") or {}
        self.compile_ms = float(rec.get("compile_ms", 0.0))
        # pre-monotonic records (old sinks) degrade to wall-clock
        # durations — flagged so consumers can widen their uncertainty
        self.has_mono = "mono" in rec
        m = _ROUND_RE.match(self.name)
        if m:
            self.round: Optional[int] = int(m.group(1))
        elif "round" in self.attrs:
            try:
                self.round = int(self.attrs["round"])
            except (TypeError, ValueError):
                self.round = None
        else:
            self.round = None
        cm = _CLIENT_RE.match(self.name)
        self.client = cm.group(1) if cm else None
        self.t0 = self.started  # re-aligned by assemble_records
        self.t1 = self.ended

    def align(self, clock: NodeClock) -> None:
        self.t0 = clock.align(self.started)
        self.t1 = self.t0 + self.duration_ms / 1e3

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "node": self.node, "span_id": self.span_id,
            "parent_id": self.parent_id, "round": self.round,
            "t0": self.t0, "t1": self.t1, "duration_ms": self.duration_ms,
            "remote_parent": self.remote_parent, "attrs": self.attrs,
        }


class AssembledTrace:
    """All nodes' spans and events, aligned and indexed."""

    def __init__(self, spans: List[TraceSpan], events: List[Dict[str, Any]],
                 clocks: Dict[str, NodeClock], ref_node: str):
        self.spans = spans
        self.events = events
        self.clocks = clocks
        self.ref_node = ref_node
        self.by_id: Dict[str, TraceSpan] = {}
        self.children: Dict[str, List[TraceSpan]] = {}
        self.rounds: Dict[int, List[TraceSpan]] = {}
        for s in spans:
            if s.span_id:
                self.by_id[s.span_id] = s
            if s.parent_id:
                self.children.setdefault(s.parent_id, []).append(s)
            if s.round is not None:
                self.rounds.setdefault(s.round, []).append(s)
        # send/recv point events by msg_id, each annotated with the
        # ALIGNED timestamp in ``t`` (raw wall stays in ``ts``)
        self.sends: Dict[str, List[Dict[str, Any]]] = {}
        self.recvs: Dict[str, List[Dict[str, Any]]] = {}
        for ev in events:
            msg_id = (ev.get("attrs") or {}).get("msg_id")
            if not msg_id:
                continue
            clock = clocks.get(ev["node"])
            ev["t"] = (clock.align(ev["ts"]) if clock is not None
                       else ev["ts"])
            if ev["name"] == "comm/send":
                self.sends.setdefault(str(msg_id), []).append(ev)
            elif ev["name"] == "comm/recv":
                self.recvs.setdefault(str(msg_id), []).append(ev)

    @property
    def nodes(self) -> List[str]:
        return sorted({s.node for s in self.spans}
                      | {e["node"] for e in self.events})

    def round_indexes(self) -> List[int]:
        return sorted(self.rounds)

    def send_event_for(self, msg_id: str,
                       node: Optional[str] = None) -> Optional[Dict]:
        """The matching send event for a message (optionally pinned to the
        expected sender node); earliest aligned time wins on duplicates
        (chaos copies share the msg_id on purpose)."""
        cands = self.sends.get(str(msg_id)) or []
        if node is not None:
            pinned = [e for e in cands if e["node"] == node]
            cands = pinned or cands
        return min(cands, key=lambda e: e["t"]) if cands else None


def _pick_reference_node(spans: List[TraceSpan],
                         events: List[Dict[str, Any]]) -> str:
    """The aggregation node is the natural timeline anchor: it opens and
    closes every round. Fallbacks: the node with the most spans, then
    ``"local"``."""
    agg_counts: Dict[str, int] = {}
    span_counts: Dict[str, int] = {}
    for s in spans:
        span_counts[s.node] = span_counts.get(s.node, 0) + 1
        if s.round is not None and s.name.endswith("/aggregate"):
            agg_counts[s.node] = agg_counts.get(s.node, 0) + 1
    for counts in (agg_counts, span_counts):
        if counts:
            return max(sorted(counts), key=lambda n: counts[n])
    if events:
        return _record_node(events[0])
    return "local"


def assemble_records(records: List[Dict[str, Any]]) -> AssembledTrace:
    """Assemble raw span/event record dicts (already node-stamped or
    single-node) into one aligned, indexed trace."""
    spans: List[TraceSpan] = []
    events: List[Dict[str, Any]] = []
    seen_spans = set()
    for rec in records:
        if not isinstance(rec, dict) or "name" not in rec:
            continue
        if rec.get("point"):
            events.append({
                "name": str(rec["name"]),
                "node": _record_node(rec),
                "ts": float(rec.get("ts", 0.0)),
                "attrs": rec.get("attrs") or {},
                "trace_id": rec.get("trace_id"),
                "span_id": rec.get("span_id"),
            })
        elif "duration_ms" in rec:
            span = TraceSpan(rec)
            # the same span can arrive twice (local sink + streamed
            # frame); last writer wins is irrelevant — they're identical
            key = (span.span_id, span.name)
            if span.span_id and key in seen_spans:
                continue
            seen_spans.add(key)
            spans.append(span)
    ref_node = _pick_reference_node(spans, events)

    send_idx: Dict[str, List[dict]] = {}
    recv_idx: Dict[str, List[dict]] = {}
    for ev in events:
        msg_id = (ev.get("attrs") or {}).get("msg_id")
        if not msg_id:
            continue
        if ev["name"] == "comm/send":
            send_idx.setdefault(str(msg_id), []).append(ev)
        elif ev["name"] == "comm/recv":
            recv_idx.setdefault(str(msg_id), []).append(ev)
    clocks = align_clocks(send_idx, recv_idx, ref_node)
    for s in spans:
        clock = clocks.get(s.node)
        if clock is None:
            clock = clocks.setdefault(s.node, NodeClock(s.node))
        s.align(clock)
    spans.sort(key=lambda s: s.t0)
    return AssembledTrace(spans, events, clocks, ref_node)


def load_trace_records(run_dir: str) -> List[Dict[str, Any]]:
    """Raw span + point-event records from a run dir: the local sink plus
    the live-plane-collected remote sink (node-annotated)."""
    from fedml_tpu.telemetry.report import _load_jsonl

    records = _load_jsonl(os.path.join(run_dir, "spans.jsonl"))
    records += _load_jsonl(os.path.join(run_dir, REMOTE_SPANS_FILENAME))
    return records


def assemble_trace(run_dir: str) -> AssembledTrace:
    """Post-hoc assembly from a run dir's sinks."""
    return assemble_records(load_trace_records(run_dir))
