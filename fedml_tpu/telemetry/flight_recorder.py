"""Flight recorder — a bounded black box for post-mortem run triage.

The reference's MLOps plane can answer "why did this run die" because a
hosted backend saw every status transition; this build has no backend, so
the recorder keeps the last few thousand telemetry events — spans, comm
headers, health samples, round/checkpoint markers — in a byte-budgeted
in-memory ring and lands them as ``<run_dir>/flight_recorder.jsonl`` the
moment the process dies abnormally:

- **SIGTERM** (preemption, ``kill``, scheduler stop) and **SIGINT**
  (operator Ctrl-C, scheduler interrupt): dump, then chain — the
  previous handler if one was installed, else re-raise with the default
  disposition so the exit code stays honest (SIGINT's chained default
  raises KeyboardInterrupt as usual);
- **unhandled exception** (main thread via ``sys.excepthook``, any other
  thread via ``threading.excepthook``): dump with the exception type,
  message, and traceback as crash context, then chain to the previous
  hook;
- **atexit**: dump unless a crash path already did, so even a clean run
  leaves its tail of events for ``fedml_tpu telemetry doctor``.

Events are serialized at ``record()`` time (one ``json.dumps``, stored as
the final line string), so the byte budget is exact and the dump path —
which may run inside a signal handler — only writes pre-built lines.
``Tracer.end`` feeds every completed span in via :func:`on_span`; the ring
evicts oldest-first, so a span flood can never grow the recorder past
``max_bytes``.
"""
from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "get_flight_recorder",
    "record",
    "on_span",
    "bind",
    "reset_flight_recorder",
]

DUMP_FILENAME = "flight_recorder.jsonl"

# reasons that mark a *crash* dump; a later atexit dump must not
# overwrite the crash context they captured
_CRASH_REASONS = ("sigterm", "sigint", "exception", "handler_error")


class FlightRecorder:
    """Byte-budgeted ring of pre-serialized telemetry events.

    Uses an ``RLock`` deliberately: the SIGTERM handler runs on the main
    thread and may interrupt a ``record()`` in progress there — with a
    plain lock the dump would self-deadlock. Deque mutations are atomic
    under the GIL, so re-entry at worst mis-counts a few bytes.
    """

    def __init__(self, max_bytes: int = 1 << 20, max_events: int = 4096):
        self.max_bytes = int(max_bytes)
        self.max_events = int(max_events)
        self._lines: "deque[str]" = deque()
        self._sizes: "deque[int]" = deque()
        self._bytes = 0
        self._lock = threading.RLock()
        self._dir: Optional[str] = None
        self.dumped_reason: Optional[str] = None
        self.dropped = 0

    # -- recording --------------------------------------------------------
    def record(self, kind: str, **fields: Any) -> None:
        rec = {"ts": time.time(), "kind": str(kind), **fields}
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError):  # pragma: no cover - default=str
            line = json.dumps({"ts": rec["ts"], "kind": rec["kind"],
                               "unserializable": True})
        size = len(line) + 1
        with self._lock:
            self._lines.append(line)
            self._sizes.append(size)
            self._bytes += size
            while self._lines and (
                    self._bytes > self.max_bytes
                    or len(self._lines) > self.max_events):
                self._lines.popleft()
                self._bytes -= self._sizes.popleft()
                self.dropped += 1

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._lines)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            lines = list(self._lines)
        return [json.loads(l) for l in lines]

    def last_round(self) -> Optional[int]:
        """The highest-recency event carrying a ``round`` field."""
        with self._lock:
            lines = list(self._lines)
        for line in reversed(lines):
            try:
                rec = json.loads(line)
            except ValueError:  # pragma: no cover
                continue
            if "round" in rec:
                try:
                    return int(rec["round"])
                except (TypeError, ValueError):
                    continue
        return None

    # -- binding + dumping ------------------------------------------------
    def bind(self, run_dir: str) -> None:
        self._dir = run_dir

    @property
    def sink_dir(self) -> Optional[str]:
        return self._dir

    def dump(self, run_dir: Optional[str] = None, reason: str = "manual",
             exc: Optional[BaseException] = None) -> Optional[str]:
        """Write the ring (oldest→newest) behind one crash-context header.

        Overwrites any previous dump — the file always reflects the
        latest process state, and the header records why it was written.
        """
        target = run_dir or self._dir
        if target is None:
            return None
        header: Dict[str, Any] = {
            "ts": time.time(),
            "kind": "crash_context",
            "reason": reason,
            "n_events": len(self),
            "dropped": self.dropped,
            "pid": os.getpid(),
        }
        lr = self.last_round()
        if lr is not None:
            header["last_round"] = lr
        if exc is not None:
            header["exc_type"] = type(exc).__name__
            header["exc_message"] = str(exc)
            header["traceback"] = "".join(
                traceback.format_exception(type(exc), exc, exc.__traceback__)
            )[-4096:]
        with self._lock:
            lines = list(self._lines)
        try:
            os.makedirs(target, exist_ok=True)
            path = os.path.join(target, DUMP_FILENAME)
            with open(path, "w") as f:
                f.write(json.dumps(header, default=str) + "\n")
                for line in lines:
                    f.write(line + "\n")
        except OSError:  # pragma: no cover - sink dir gone at crash time
            return None
        self.dumped_reason = reason
        return path


_recorder = FlightRecorder()
_recorder_lock = threading.Lock()
_hooks_installed = False


def get_flight_recorder() -> FlightRecorder:
    return _recorder


def record(kind: str, **fields: Any) -> None:
    """Record one event into the process-global ring (always cheap; the
    ring exists even before a run dir is bound)."""
    _recorder.record(kind, **fields)


def on_span(rec: Dict) -> None:
    """Span feed from ``Tracer.end`` — a condensed copy rides the ring."""
    _recorder.record(
        "span",
        name=rec.get("name"),
        duration_ms=round(float(rec.get("duration_ms", 0.0)), 3),
        started=rec.get("started"),
    )


def reset_flight_recorder() -> None:
    """Fresh unbound ring (test isolation). Crash hooks stay installed —
    they always act on the *current* global recorder."""
    global _recorder
    with _recorder_lock:
        _recorder = FlightRecorder()


# -- crash hooks -----------------------------------------------------------
def _dump_current(reason: str, exc: Optional[BaseException] = None) -> None:
    rec = _recorder
    if rec.sink_dir is None:
        return
    rec.dump(reason=reason, exc=exc)


def _install_hooks() -> None:
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    prev_excepthook = sys.excepthook

    def _excepthook(exc_type, exc, tb):
        _dump_current("exception", exc)
        prev_excepthook(exc_type, exc, tb)

    sys.excepthook = _excepthook

    prev_thread_hook = threading.excepthook

    def _thread_hook(args):
        _dump_current("exception", args.exc_value)
        prev_thread_hook(args)

    threading.excepthook = _thread_hook

    def _chain_signal(sig: int, reason: str) -> None:
        """Dump-then-chain a termination signal. SIGTERM and SIGINT get
        the SAME treatment: an operator Ctrl-C or a scheduler interrupt
        must leave crash context just like a preemption — the journal
        replay that follows should never be the only explanation. For
        SIGINT the chained previous handler is normally
        ``default_int_handler``, so KeyboardInterrupt still propagates
        (and the exit status stays honest either way)."""
        prev_sig = signal.getsignal(sig)

        def _on_signal(signum, frame):
            _dump_current(reason)
            if callable(prev_sig) and prev_sig not in (
                    signal.SIG_DFL, signal.SIG_IGN):
                prev_sig(signum, frame)
                return
            # restore the default disposition and re-raise so the exit
            # status is a real signal death, not a masked clean exit
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        signal.signal(sig, _on_signal)

    try:
        _chain_signal(signal.SIGTERM, "sigterm")
        _chain_signal(signal.SIGINT, "sigint")
    except ValueError:
        # signal.signal only works on the main thread; a worker-thread
        # configure() still gets excepthook + atexit coverage
        pass

    def _atexit_dump():
        if _recorder.dumped_reason not in _CRASH_REASONS:
            _dump_current("atexit")

    atexit.register(_atexit_dump)


def bind(run_dir: str) -> FlightRecorder:
    """Point the global recorder at a run dir and arm the crash hooks.

    Called by ``telemetry.configure`` so every engine that lands spans in
    a run dir gets the black box for free.
    """
    _recorder.bind(run_dir)
    _install_hooks()
    return _recorder
