"""``telemetry doctor`` — triage a run dir into an actionable summary.

The report CLI answers "where did the time go"; the doctor answers "what
is wrong with this run": which clients straggled or diverged, whether
memory is creeping toward OOM, whether compression is paying off, and —
for a dead run — what the flight recorder saw last. Every section
degrades to an explicit "no data" note when its sink is missing or
truncated, so a partial run triages instead of tracebacking.

Data sources (all under ``<run_dir>/``):

- ``health.jsonl``          — ``client_health`` + ``mem_sample`` events
- ``flight_recorder.jsonl`` — crash context + last events before death
- ``spans.jsonl``           — codec/encode outliers, span-based straggler
  fallback when no health events exist
- ``telemetry.jsonl``       — comm/wire counters, service health metrics
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from fedml_tpu.telemetry.health import _median
from fedml_tpu.telemetry.report import (
    RunData,
    build_report,
    normalize_name,
)

__all__ = ["build_doctor", "format_doctor"]


def _fit_slope(xs: List[float], ys: List[float]) -> float:
    """Least-squares slope of y over x (0 when degenerate)."""
    n = len(xs)
    if n < 2:
        return 0.0
    mx = sum(xs) / n
    my = sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    if denom <= 0:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom


def _fmt_bytes(b: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(b) < 1024 or unit == "GiB":
            return f"{b:.1f} {unit}"
        b /= 1024
    return f"{b:.1f} GiB"  # pragma: no cover


def build_doctor(run_dir, straggler_threshold: float = 2.0,
                 anomaly_threshold: float = 4.0,
                 mem_growth_threshold: float = 1.5,
                 min_rounds: int = 3,
                 recompile_threshold: int = 3) -> Dict:
    notes: Dict[str, str] = {}
    verdict: List[str] = []

    # Share one RunData with build_report so every sink file is read at
    # most once per doctor invocation.
    data = run_dir if isinstance(run_dir, RunData) else RunData(run_dir)
    run_dir = data.run_dir

    health_path = os.path.join(run_dir, "health.jsonl")
    health_events = data.health
    if not os.path.exists(health_path):
        notes["health"] = "no data: health.jsonl missing (run predates the " \
                          "health layer, or no health events fired)"
    elif not health_events:
        notes["health"] = "no data: health.jsonl is empty or unparseable"

    fr_path = os.path.join(run_dir, "flight_recorder.jsonl")
    fr_events = data.flight
    if not os.path.exists(fr_path):
        notes["crash"] = "no data: flight_recorder.jsonl missing (process " \
                         "still alive, or recorder not bound)"
    elif not fr_events:
        notes["crash"] = "no data: flight_recorder.jsonl is empty"

    report = build_report(data)
    for key, val in (report.get("notes") or {}).items():
        notes.setdefault(key, val)

    # -- crash context ----------------------------------------------------
    crash: Optional[Dict] = None
    if fr_events:
        header = next((e for e in fr_events
                       if e.get("kind") == "crash_context"), None)
        tail = [e for e in fr_events if e.get("kind") != "crash_context"]
        last_round = None
        last_checkpoint = None
        for e in reversed(tail):
            if last_round is None and "round" in e:
                try:
                    last_round = int(e["round"])
                except (TypeError, ValueError):
                    pass
            if last_checkpoint is None and e.get("kind") == "checkpoint":
                try:
                    last_checkpoint = int(e["round"])
                except (TypeError, ValueError, KeyError):
                    pass
            if last_round is not None and last_checkpoint is not None:
                break
        crash = {
            "reason": (header or {}).get("reason"),
            "exc_type": (header or {}).get("exc_type"),
            "exc_message": (header or {}).get("exc_message"),
            "n_events": (header or {}).get("n_events", len(tail)),
            "dropped": (header or {}).get("dropped", 0),
            "last_round": last_round,
            "last_checkpoint_round": last_checkpoint,
            "last_events": tail[-8:],
        }
        if crash["reason"] in ("sigterm", "exception", "handler_error"):
            what = crash["exc_type"] or crash["reason"]
            where = (f" at round {last_round}" if last_round is not None
                     else "")
            resume = (f"; last checkpoint: round {last_checkpoint} (resume "
                      "with resume: true)" if last_checkpoint is not None
                      else "")
            verdict.append(f"run died ({what}{where}){resume}")

    # -- per-client health ------------------------------------------------
    ch = [e for e in health_events if e.get("kind") == "client_health"]
    clients: Dict[str, Dict] = {}
    for e in ch:
        c = clients.setdefault(str(e.get("client")), {
            "rounds": 0, "round_scores": [], "round_zs": [],
            "latency_ms": [], "max_abs_z": 0.0, "flag_rounds": 0})
        c["rounds"] += 1
        # prefer the raw per-round score; fall back to the tracker's own
        # running median for events from older writers
        score = e.get("round_straggler_score", e.get("straggler_score"))
        if score is not None:
            c["round_scores"].append(float(score))
        z = e.get("round_max_abs_z", e.get("anomaly_score"))
        if z is not None:
            c["round_zs"].append(float(z))
        if e.get("latency_ms") is not None:
            c["latency_ms"].append(float(e["latency_ms"]))
        c["max_abs_z"] = max(c["max_abs_z"],
                             abs(float(e.get("z_norm") or 0.0)),
                             abs(float(e.get("z_loss") or 0.0)))
        if e.get("flagged_straggler") or e.get("flagged_anomaly"):
            c["flag_rounds"] += 1

    stragglers: List[Dict] = []
    anomalies: List[Dict] = []
    for cid, c in sorted(clients.items()):
        s_scores = c["round_scores"]
        zs = c["round_zs"]
        row = {
            "client": cid,
            "rounds": c["rounds"],
            # medians across rounds: robust to one compile-heavy or
            # MAD-unstable round; flags need min_rounds of evidence
            "straggler_score": _median(s_scores) if s_scores else 0.0,
            "anomaly_score": _median(zs) if zs else 0.0,
            "max_abs_z": c["max_abs_z"],
            "mean_latency_ms": (sum(c["latency_ms"]) / len(c["latency_ms"])
                                if c["latency_ms"] else None),
        }
        if (len(s_scores) >= min_rounds
                and row["straggler_score"] >= straggler_threshold):
            stragglers.append(row)
            lat = (f" (mean {row['mean_latency_ms']:.0f} ms/round)"
                   if row["mean_latency_ms"] is not None else "")
            verdict.append(
                f"client {cid} is a straggler: latency "
                f"{row['straggler_score']:.1f}x the cohort median"
                + lat)
        if (len(zs) >= min_rounds
                and row["anomaly_score"] >= anomaly_threshold):
            anomalies.append(row)
            verdict.append(
                f"client {cid} sends anomalous updates: median |z| "
                f"{row['anomaly_score']:.1f} (max {row['max_abs_z']:.1f}) on "
                "update-norm/loss — inspect its data or drop it from "
                "sampling")
    span_stragglers: List[Dict] = []
    if not ch and report.get("stragglers"):
        # span-based fallback: aggregate the report's slowest-client-per-
        # round attribution so a pre-health run still names its slow
        # client (no anomaly scoring possible without update norms)
        by_client: Dict[str, List[Dict]] = {}
        for s in report["stragglers"]:
            by_client.setdefault(str(s["client"]), []).append(s)
        total_rounds = max(len(report["stragglers"]), 1)
        for cid, rows in sorted(by_client.items()):
            span_stragglers.append({
                "client": cid,
                "rounds_slowest": len(rows),
                "mean_share": sum(r["share"] for r in rows) / len(rows),
                "mean_duration_ms": (sum(r["duration_ms"] for r in rows)
                                     / len(rows)),
            })
        span_stragglers.sort(key=lambda r: -r["rounds_slowest"])
        worst = span_stragglers[0]
        if (worst["rounds_slowest"] >= max(min_rounds, total_rounds // 2)
                and worst["mean_share"] >= 0.5):
            verdict.append(
                f"client {worst['client']} was the slowest client in "
                f"{worst['rounds_slowest']}/{total_rounds} rounds "
                f"({100 * worst['mean_share']:.0f}% of client time; "
                "span-based fallback, no health events)")
        notes.setdefault(
            "stragglers",
            "no client_health events; falling back to span-based slowest-"
            "client-per-round (no anomaly scoring possible)")

    # -- memory growth ----------------------------------------------------
    mem = [e for e in health_events if e.get("kind") == "mem_sample"]
    memory: Dict[str, Dict] = {}
    by_phase: Dict[str, List] = {}
    for e in mem:
        if "round" not in e:
            continue
        by_phase.setdefault(str(e.get("phase")), []).append(e)
    for phase, events in sorted(by_phase.items()):
        events.sort(key=lambda e: (int(e["round"]), e.get("ts", 0)))
        # prefer the accelerator's own allocator stats; fall back to live
        # buffer bytes on backends without memory_stats (CPU)
        key = ("bytes_in_use"
               if any(e.get("bytes_in_use") for e in events)
               else "live_buffer_bytes")
        xs = [float(e["round"]) for e in events]
        ys = [float(e.get(key) or 0.0) for e in events]
        if not any(ys):
            continue
        slope = _fit_slope(xs, ys)
        first, last = ys[0], ys[-1]
        row = {
            "phase": phase,
            "metric": key,
            "samples": len(ys),
            "first_bytes": first,
            "last_bytes": last,
            "slope_bytes_per_round": slope,
            "growth_ratio": (last / first) if first > 0 else 0.0,
        }
        limit = max((float(e.get("bytes_limit") or 0.0) for e in events),
                    default=0.0)
        if limit > 0 and slope > 0:
            row["rounds_to_limit"] = max(0.0, (limit - last) / slope)
        memory[phase] = row
        if (row["growth_ratio"] >= mem_growth_threshold and slope > 0
                and len(ys) >= 3):
            msg = (f"memory grows in phase {phase!r}: "
                   f"{_fmt_bytes(first)} -> {_fmt_bytes(last)} "
                   f"({_fmt_bytes(slope)}/round)")
            if "rounds_to_limit" in row:
                msg += f", ~{row['rounds_to_limit']:.0f} rounds to OOM"
            msg += " — check staging cache budget / prefetch double-buffer"
            verdict.append(msg)
    if not mem:
        notes.setdefault("memory",
                         "no data: no mem_sample events in health.jsonl")

    # -- compression + wire bytes ----------------------------------------
    comp = report.get("compression") or {}
    compression: Dict[str, Any] = {
        "ratio": comp.get("ratio", 0.0),
        "raw_bytes": comp.get("raw_bytes", 0.0),
        "wire_bytes": comp.get("wire_bytes", 0.0),
        "outlier_spans": [],
    }
    codec_active = bool(comp.get("encode") or comp.get("decode"))
    if codec_active and comp.get("raw_bytes") and comp.get("ratio", 0) < 1.5:
        verdict.append(
            f"compression is not paying off: raw->wire ratio "
            f"{comp['ratio']:.2f}x — check codec choice vs payload dtypes")
    # encode/decode duration outliers: individual spans way past the p50
    spans = data.spans
    codec_spans = [s for s in spans
                   if normalize_name(s["name"]).startswith("compress/")]
    by_name: Dict[str, List[Dict]] = {}
    for s in codec_spans:
        by_name.setdefault(normalize_name(s["name"]), []).append(s)
    for name, group in sorted(by_name.items()):
        durs = sorted(s["duration_ms"] for s in group)
        p50 = durs[len(durs) // 2]
        for s in group:
            if p50 > 0 and s["duration_ms"] > 5 * p50 and len(group) >= 4:
                compression["outlier_spans"].append({
                    "name": name, "duration_ms": s["duration_ms"],
                    "p50_ms": p50})
    wire = {k: v for k, v in (report.get("comm_bytes") or {}).items()
            if k.split("{")[0].startswith("comm/")}
    compression["wire_counters"] = wire
    if not codec_active and not wire:
        notes.setdefault("compression",
                         "no data: no codec spans or comm byte counters")

    # -- service health (serving/scheduler via the registry) --------------
    services = dict(report.get("services") or {})
    if not services:
        notes.setdefault("services",
                         "no data: no serving/* or scheduler/* metrics")

    # telemetry.jsonl is read once and shared by the serving /
    # connectivity / tier sections below — it holds append-mode
    # CUMULATIVE registry snapshots, so each section keeps the latest
    # record per key rather than summing the stream.
    metric_records = data.metrics

    # -- live serving plane (hot-swap freshness + latency SLO) ------------
    serving: Dict[str, Any] = {}
    latest_serve: Dict[str, Dict] = {}
    for rec in metric_records:
        name = rec.get("name", "")
        if name.startswith("serving/"):
            # several label sets may exist (labelled endpoint monitor +
            # unlabelled slots); the file is append-order, so the LAST
            # record per name is the live reading. Not max: slo_ms and
            # round_current are not monotone (a no-SLO redeploy clears
            # the gauge to 0, a restarted endpoint re-serves its boot
            # round) and a stale larger record must not shadow them.
            latest_serve[name.split("/", 1)[1]] = rec
    if latest_serve:
        def _sval(key, default=None):
            rec = latest_serve.get(key)
            if rec is None:
                return default
            return float(rec.get("value", rec.get("count", 0)) or 0)

        cur = _sval("round_current")
        pub = _sval("round_published")
        swaps = _sval("swaps", 0.0)
        rejected = _sval("rejected", 0.0)
        stall = latest_serve.get("swap_stall_ms") or {}
        req = latest_serve.get("request_ms") or {}
        ttft = latest_serve.get("ttft_ms") or {}
        tpot = latest_serve.get("tpot_ms") or {}
        queue_wait = latest_serve.get("queue_wait_ms") or {}
        slo_ms = _sval("slo_ms")
        serving = {
            "round_current": None if cur is None else int(cur),
            "round_published": None if pub is None else int(pub),
            "swaps": int(swaps),
            "rejected": int(rejected),
            "swap_stall_p99_ms": stall.get("p99"),
            "swap_stall_max_ms": stall.get("max"),
            "request_p99_ms": req.get("p99"),
            "slo_ms": slo_ms,
        }
        # token-latency attribution + saturation gauges (absent on runs
        # that predate request observability — keys only appear with data)
        if ttft.get("count"):
            serving["ttft_p95_ms"] = ttft.get("p95")
            serving["tpot_p95_ms"] = tpot.get("p95")
            serving["tokens_per_s"] = _sval("tokens_per_s")
        if queue_wait.get("count"):
            serving["queue_wait_p95_ms"] = queue_wait.get("p95")
        for gauge in ("batch_occupancy", "queue_depth", "tokens_in_flight",
                      "kv_bytes_in_use", "kv_bytes_allocated"):
            v = _sval(gauge)
            if v is not None:
                serving[gauge] = v
        # SLO scorecard: latest cumulative total/breaches per objective
        # (these counters are labeled by objective kind, so they need a
        # label-aware pass — latest_serve collapses label sets)
        slo_score: Dict[str, Dict[str, float]] = {}
        for rec in metric_records:
            name = rec.get("name", "")
            if name not in ("serving/slo_total", "serving/slo_breaches",
                            "serving/slo_target_ms"):
                continue
            kind = (rec.get("labels") or {}).get("objective", "?")
            row = slo_score.setdefault(kind, {})
            row[name.split("/", 1)[1]] = float(
                rec.get("value", rec.get("count", 0)) or 0)
        objective = _sval("slo_objective")
        if slo_score:
            serving["slo_objective"] = objective
            serving["slo"] = slo_score
            budget = 1.0 - (objective or 0.99)
            for kind, row in sorted(slo_score.items()):
                total = row.get("slo_total", 0.0)
                bad = row.get("slo_breaches", 0.0)
                if total > 0 and budget > 0 and bad / total > budget:
                    verdict.append(
                        f"endpoint burned its {kind} error budget: "
                        f"{bad:.0f}/{total:.0f} observations over the "
                        f"{row.get('slo_target_ms', 0.0):.1f} ms target "
                        f"({100 * bad / total:.1f}% bad vs "
                        f"{100 * budget:.1f}% budget)")
        # shed bursts recorded as first-class serving_events at trip time
        sheds = [e for e in metric_records
                 if e.get("kind") == "serving_event"
                 and e.get("event") == "shed_burst"]
        if sheds:
            serving["shed_bursts"] = len(sheds)
            serving["shed_queue_depth"] = sheds[-1].get("queue_depth")
        if cur is not None and pub is not None and pub - cur >= 2:
            verdict.append(
                f"endpoint is serving a STALE round: round {cur:.0f} while "
                f"training published round {pub:.0f} "
                f"({pub - cur:.0f} behind) — check the serving bridge / "
                "swap transport")
        if (slo_ms and req.get("p99") is not None
                and float(req["p99"]) > slo_ms):
            verdict.append(
                f"endpoint p99 latency {float(req['p99']):.1f} ms exceeds "
                f"its SLO of {slo_ms:.1f} ms — engine saturated or swap "
                "stalls too long (see serving/swap_stall_ms)")
        if rejected:
            depth = (f" (queue depth {sheds[-1].get('queue_depth')} at "
                     "burst trip)" if sheds else "")
            verdict.append(
                f"endpoint shed {rejected:.0f} request(s) with 429 — "
                "offered load exceeded the bounded request queue "
                f"(raise max_inflight or add replicas){depth}")
    else:
        notes.setdefault("serving",
                         "no data: no serving/* metrics (no endpoint in "
                         "this run)")

    # -- connectivity (resilience/* counters + resilience_event records) --
    # keep the LATEST record per (name, labels) — like report.py does —
    # then sum across label sets (e.g. chaos_injections per action)
    latest: Dict[Any, float] = {}
    for rec in metric_records:
        name = rec.get("name", "")
        if name.startswith("resilience/"):
            labels = tuple(sorted((rec.get("labels") or {}).items()))
            latest[(name, labels)] = float(
                rec.get("value", rec.get("count", 0)) or 0)
    res_counters: Dict[str, float] = {}
    for (name, _), val in latest.items():
        key = name.split("/", 1)[1]
        res_counters[key] = res_counters.get(key, 0.0) + val
    res_events = [e for e in health_events
                  if e.get("kind") == "resilience_event"]
    # episode pairing IN EVENT ORDER: each eviction opens a new episode
    # and clears any earlier rejoin — a client that dropped out AGAIN
    # after rejoining must surface as unresolved, not as recovered
    evict_round: Dict[str, Any] = {}
    rejoin_round: Dict[str, Any] = {}
    for e in res_events:
        # tier-tagged events (hierarchical runs) carry node/clients
        # fields, not a per-client identity — they belong to the tiers
        # section below, not the per-client evict/rejoin pairing
        if e.get("tier") is not None or e.get("client") is None:
            continue
        cid = str(e.get("client"))
        if e.get("event") == "evicted":
            evict_round[cid] = e.get("round")
            rejoin_round.pop(cid, None)
        elif e.get("event") == "rejoined" and cid in evict_round:
            rejoin_round[cid] = e.get("round")
    connectivity: Dict[str, Any] = {
        "counters": res_counters,
        "events": res_events[-16:],
        "evicted_clients": evict_round,
        "rejoined_clients": rejoin_round,
    }
    if res_counters.get("quorum_rounds"):
        verdict.append(
            f"{res_counters['quorum_rounds']:.0f} round(s) closed on "
            "quorum after the deadline — the missing clients' uploads "
            "were reweighted out (see evicted/rejoined below)")
    for cid, r in sorted(evict_round.items()):
        if cid in rejoin_round:
            verdict.append(
                f"client {cid} dropped out at round {r} and rejoined at "
                f"round {rejoin_round[cid]}")
        else:
            verdict.append(
                f"client {cid} dropped out at round {r} and NEVER "
                "rejoined — check its process/network")
    disc = res_counters.get("broker_disconnects", 0.0)
    reco = res_counters.get("broker_reconnects", 0.0)
    if disc > reco:
        verdict.append(
            f"{disc - reco:.0f} broker connection(s) lost and never "
            "restored — transport died before the run finished")
    if res_counters.get("send_failures"):
        verdict.append(
            f"{res_counters['send_failures']:.0f} send(s) exhausted their "
            "retry budget — messages were LOST (raise send_max_retries "
            "or fix the transport)")
    if not res_counters and not res_events:
        notes.setdefault(
            "connectivity",
            "no data: no resilience/* metrics or resilience_event records "
            "(run predates the resilience layer, or nothing went wrong)")

    # -- recovery (restarts / write-ahead journal replay) -----------------
    # the durability layer's autopsy: was the process killed and
    # restarted, what did the journal salvage, did a torn tail truncate,
    # and did a secagg round have to abort to its round boundary
    recovery_keys = ("restarts", "journal_replays", "journal_salvaged",
                     "journal_records", "journal_bytes",
                     "journal_truncations", "checkpoints_pruned")
    recovery_counters = {k: res_counters[k] for k in recovery_keys
                         if k in res_counters}
    replay_events = [e for e in res_events
                     if e.get("event") in ("journal_replayed",
                                           "edge_restarted")]
    sa_aborts = [e for e in health_events
                 if e.get("kind") == "secagg_event"
                 and e.get("event") == "resume_aborted"]
    recovery: Dict[str, Any] = {"counters": recovery_counters,
                                "events": replay_events[-16:],
                                "secagg_aborts": sa_aborts[-8:]}
    restarts = recovery_counters.get("restarts", 0.0)
    salvaged = recovery_counters.get("journal_salvaged", 0.0)
    if restarts:
        verdict.append(
            f"process restarted {restarts:.0f} time(s) mid-run; journal "
            f"replay salvaged {salvaged:.0f} already-received upload(s) — "
            + ("zero uploads lost to the crash window"
               if salvaged else "nothing was in flight at the kill"))
    for e in replay_events:
        if e.get("event") == "journal_replayed":
            verdict.append(
                f"round {e.get('round')} re-entered MID-FLIGHT after a "
                f"restart: clients {e.get('salvaged')} never retrained "
                "(their uploads replayed from the journal)")
        elif e.get("event") == "edge_restarted":
            verdict.append(
                f"tier {e.get('tier')} node {e.get('node')} restarted at "
                f"round {e.get('round')} with {e.get('salvaged')} "
                "salvaged partial sum(s)")
    if recovery_counters.get("journal_truncations"):
        verdict.append(
            f"{recovery_counters['journal_truncations']:.0f} torn journal "
            "tail(s) truncated at the last valid record (expected crash "
            "artifact of a mid-append kill; no valid record was lost)")
    if recovery_counters.get("checkpoints_pruned"):
        verdict.append(
            f"{recovery_counters['checkpoints_pruned']:.0f} half-written "
            "checkpoint(s) pruned — resume fell back to the newest "
            "restorable round")
    for e in sa_aborts:
        verdict.append(
            f"secagg round {e.get('round')} ABORTED to its round boundary "
            f"on restart ({e.get('uploads_dropped', 0)} masked upload(s) "
            "dropped) — pairwise masks are unrecoverable without the "
            "live session; the round restarted from the checkpoint")
    if not recovery_counters and not replay_events and not sa_aborts:
        notes.setdefault(
            "recovery",
            "no data: no restarts or journal activity (the process never "
            "died, or durability was off)")

    # -- job plane (supervision / preemption / rescheduling) --------------
    # sched/* counters land from agents and masters; sched_event records
    # carry the identities (run/job/node) and the doctor-visible reasons
    # (crash-loop containment verdicts especially)
    latest_sched: Dict[Any, float] = {}
    for rec in metric_records:
        name = rec.get("name", "")
        if name.startswith("sched/"):
            labels = tuple(sorted((rec.get("labels") or {}).items()))
            latest_sched[(name, labels)] = float(
                rec.get("value", rec.get("count", 0)) or 0)
    sched_counters: Dict[str, float] = {}
    for (name, _), val in latest_sched.items():
        key = name.split("/", 1)[1]
        sched_counters[key] = sched_counters.get(key, 0.0) + val
    sched_events = [e for e in health_events
                    if e.get("kind") == "sched_event"]
    jobplane: Dict[str, Any] = {"counters": sched_counters,
                                "events": sched_events[-16:]}
    crash_loop_runs = set()
    for e in sched_events:
        ev = e.get("event")
        if ev == "crash_loop":
            crash_loop_runs.add(str(e.get("run_id")))
            verdict.append(
                f"run {e.get('run_id')} CRASH-LOOPED into containment "
                f"after {e.get('attempts')} attempt(s): {e.get('reason')} "
                "— FAILED instead of flapping; fix the job before "
                "resubmitting")
        elif ev == "reschedule_refused":
            verdict.append(
                f"run {e.get('run_id')} could NOT be rescheduled "
                f"({e.get('reason')}; peak-HBM demand "
                f"{e.get('hbm_demand', 0):.0f} B) — no surviving node "
                "admitted the job; add capacity or free HBM headroom")
        elif ev == "node_lost":
            verdict.append(
                f"node {e.get('node')} declared LOST (silent > "
                f"{e.get('deadline_s', 0):g}s) — its durable runs were "
                "rescheduled onto survivors")
    if sched_counters.get("crash_loops", 0.0) > len(crash_loop_runs):
        verdict.append(
            f"{sched_counters['crash_loops']:.0f} crash-loop "
            "containment(s) tripped (run identities not in this sink)")
    restarts_s = sched_counters.get("restarts", 0.0)
    if restarts_s:
        verdict.append(
            f"supervision relaunched run(s) {restarts_s:.0f} time(s) "
            "after abnormal exits (sched/restarts)")
    preempts = sched_counters.get("preemptions", 0.0)
    if preempts:
        verdict.append(
            f"{preempts:.0f} preemption(s) quiesced; "
            f"{sched_counters.get('reschedules', 0.0):.0f} rank(s) "
            f"rescheduled, {sched_counters.get('jobs_resumed', 0.0):.0f} "
            "resumed on a surviving node")
    lost = sched_counters.get("jobs_lost", 0.0)
    resumed_s = sched_counters.get("jobs_resumed", 0.0)
    if lost > resumed_s:
        verdict.append(
            f"{lost - resumed_s:.0f} job rank(s) declared lost on silent "
            "nodes and NEVER resumed — check surviving capacity and the "
            "reschedule_refused events above")
    if not sched_counters and not sched_events:
        notes.setdefault(
            "jobplane",
            "no data: no sched/* metrics or sched_event records (no job "
            "plane activity in this run)")

    # -- tiers (hierarchical federation: tier/<d>/* metrics + events) -----
    latest_tier: Dict[Any, float] = {}
    for rec in metric_records:
        name = rec.get("name", "")
        if name.startswith("tier/"):
            labels = tuple(sorted((rec.get("labels") or {}).items()))
            latest_tier[(name, labels)] = float(
                rec.get("value", rec.get("count", 0)) or 0)
    tier_metrics: Dict[str, Dict[str, float]] = {}
    for (name, _), val in latest_tier.items():
        parts = name.split("/")
        if len(parts) != 3:
            continue
        sig = tier_metrics.setdefault(parts[1], {})
        sig[parts[2]] = sig.get(parts[2], 0.0) + val
    tier_events = [e for e in res_events if e.get("tier") is not None]
    tiers: Dict[str, Any] = {"metrics": tier_metrics,
                             "events": tier_events[-16:]}
    for d, sig in sorted(tier_metrics.items(), key=lambda kv: kv[0]):
        qc = sig.get("quorum_closes", 0.0)
        qf = sig.get("quorum_failures", 0.0)
        ev = sig.get("evicted", 0.0)
        rj = sig.get("rejoined", 0.0)
        if qf:
            verdict.append(
                f"tier {d}: {qf:.0f} cohort close(s) fell BELOW quorum — "
                "that subtree contributed nothing to its global round")
        if qc:
            verdict.append(
                f"tier {d}: {qc:.0f} cohort(s) closed on quorum after "
                "losing children — the missing were reweighted out")
        if ev > rj:
            verdict.append(
                f"tier {d}: {ev - rj:.0f} of {ev:.0f} evicted node(s) "
                "never rejoined — check that tier's processes/links")
        elif ev:
            verdict.append(
                f"tier {d}: {ev:.0f} eviction(s), all rejoined "
                f"({rj:.0f} rejoin syncs, EF residuals reset at the edge)")
    if not tier_metrics and not tier_events:
        notes.setdefault(
            "tiers", "no data: no tier/* metrics or tier-tagged events "
            "(not a hierarchical-federation run)")

    # -- secure aggregation (secagg/* counters + secagg_event records) ----
    latest_sa: Dict[Any, float] = {}
    for rec in metric_records:
        name = rec.get("name", "")
        if name.startswith("secagg/"):
            labels = tuple(sorted((rec.get("labels") or {}).items()))
            latest_sa[(name, labels)] = float(
                rec.get("value", rec.get("count", 0)) or 0)
    sa_counters: Dict[str, float] = {}
    for (name, _), val in latest_sa.items():
        key = name.split("/", 1)[1]
        sa_counters[key] = sa_counters.get(key, 0.0) + val
    sa_events = [e for e in health_events if e.get("kind") == "secagg_event"]
    secagg: Dict[str, Any] = {"counters": sa_counters,
                              "events": sa_events[-16:]}
    for e in sa_events:
        # mask-recovery verdicts: each closed recovery is a round that
        # would have been LOST (or privacy-broken) without the protocol
        if e.get("event") == "recovery_closed":
            verdict.append(
                f"secagg round {e.get('round')} closed via mask recovery: "
                f"evicted {e.get('evicted')}, {e.get('seeds', 0):.0f} "
                "pair-seed(s) revealed — aggregate stayed masked per "
                "client and bit-stable")
    if sa_counters.get("recovery_failures"):
        verdict.append(
            f"{sa_counters['recovery_failures']:.0f} secagg mask "
            "recovery(ies) FAILED — the federation aborted rather than "
            "publish a mask-polluted aggregate (check survivor liveness "
            "/ secagg_recovery_rounds)")
    if sa_counters.get("reveal_refusals"):
        verdict.append(
            f"clients refused {sa_counters['reveal_refusals']:.0f} "
            "seed-reveal request(s) — the server asked for more than the "
            "quorum-compatible dropout set (misconfiguration, or a "
            "privacy probe)")
    if sa_counters.get("invalid_uploads") or sa_counters.get(
            "invalid_reveals"):
        verdict.append(
            f"secagg dropped {sa_counters.get('invalid_uploads', 0):.0f} "
            f"malformed masked upload(s) and "
            f"{sa_counters.get('invalid_reveals', 0):.0f} malformed "
            "reveal(s) — a peer is corrupt or hostile")
    if not sa_counters and not sa_events:
        notes.setdefault(
            "secagg", "no data: no secagg/* metrics or secagg_event "
            "records (secure aggregation was off)")

    # -- update integrity (screen / quarantine / rollback) ----------------
    # integrity/* counters + integrity_event records: who was screened
    # out and why, who sits in quarantine, and which rounds were
    # rejected and rolled back to their last accepted state
    latest_int: Dict[Any, float] = {}
    for rec in metric_records:
        name = rec.get("name", "")
        if name.startswith("integrity/"):
            labels = tuple(sorted((rec.get("labels") or {}).items()))
            latest_int[(name, labels)] = float(
                rec.get("value", rec.get("count", 0)) or 0)
    int_counters: Dict[str, float] = {}
    for (name, _), val in latest_int.items():
        key = name.split("/", 1)[1]
        int_counters[key] = int_counters.get(key, 0.0) + val
    int_events = [e for e in health_events
                  if e.get("kind") == "integrity_event"]
    quarantined_clients: Dict[str, Dict] = {}
    rollback_rounds: List[Dict] = []
    for e in int_events:
        ev = e.get("event")
        if ev == "quarantined":
            quarantined_clients[str(e.get("client"))] = {
                "round": e.get("round"),
                "until_round": e.get("until_round"),
                "reason": e.get("reason"),
            }
        elif ev == "round_rolled_back":
            rollback_rounds.append({
                "round": e.get("round"), "reason": e.get("reason"),
                "suspects": e.get("suspects"),
                "consecutive": e.get("consecutive")})
    integrity: Dict[str, Any] = {
        "counters": int_counters,
        "events": int_events[-16:],
        "quarantined_clients": quarantined_clients,
        "rollbacks": rollback_rounds,
    }
    screened = int_counters.get("screened_uploads", 0.0)
    if screened:
        kinds = []
        for key, label in (("nonfinite_uploads", "non-finite"),
                           ("norm_overflows", "norm overflow"),
                           ("z_outliers", "block-z outlier")):
            if int_counters.get(key):
                kinds.append(f"{int_counters[key]:.0f} {label}")
        verdict.append(
            f"{screened:.0f} corrupt upload(s) SCREENED OUT before "
            f"aggregation ({', '.join(kinds) or 'reasons in events'}) — "
            "senders quarantined, rounds closed over the survivors")
    for cid, q in sorted(quarantined_clients.items()):
        verdict.append(
            f"client {cid} QUARANTINED at round {q['round']} until round "
            f"{q['until_round']}: {q['reason']}")
    for rb in rollback_rounds:
        verdict.append(
            f"round {rb['round']} ROLLED BACK to its last accepted state "
            f"({rb['reason']}) — suspects quarantined, round re-run with "
            "a fresh cohort")
    if int_counters.get("rollback_aborts"):
        verdict.append(
            f"{int_counters['rollback_aborts']:.0f} federation abort(s): "
            "consecutive rollbacks exceeded max_rollbacks — the "
            "corruption was persistent; containment refused to oscillate")
    if int_counters.get("nonfinite_wire"):
        verdict.append(
            f"{int_counters['nonfinite_wire']:.0f} wire payload(s) with "
            "non-finite scales refused at decode — a peer is corrupt or "
            "hostile (see integrity/nonfinite_wire)")
    if not int_counters and not int_events:
        notes.setdefault(
            "integrity",
            "no data: no integrity/* metrics or integrity_event records "
            "(update-integrity containment was off, or nothing was "
            "corrupt)")

    # -- federated analytics (fa/* sketch-round metrics) -------------------
    # fa/rounds, quorum/deadline/stale/abort counters, screened
    # contributors, and the privacy readings (DP epsilon, HH recall) —
    # task identities ride the `task` label, tiers ride the tier section
    latest_fa: Dict[Any, float] = {}
    for rec in metric_records:
        name = rec.get("name", "")
        if name.startswith("fa/"):
            labels = tuple(sorted((rec.get("labels") or {}).items()))
            latest_fa[(name, labels)] = float(
                rec.get("value", rec.get("count", 0)) or 0)
    fa_counters: Dict[str, float] = {}
    fa_rounds_by_task: Dict[str, float] = {}
    for (name, labels), val in latest_fa.items():
        key = name.split("/", 1)[1]
        fa_counters[key] = fa_counters.get(key, 0.0) + val
        if key == "rounds":
            task = dict(labels).get("task", "?")
            fa_rounds_by_task[task] = fa_rounds_by_task.get(task, 0.0) + val
    analytics: Dict[str, Any] = {"counters": fa_counters,
                                 "rounds_by_task": fa_rounds_by_task}
    if fa_counters:
        if fa_rounds_by_task:
            per_task = ", ".join(f"{t}: {v:.0f}" for t, v in
                                 sorted(fa_rounds_by_task.items()))
            verdict.append(
                f"federated analytics ran {fa_counters.get('rounds', 0):.0f} "
                f"sketch round(s) ({per_task})")
        if fa_counters.get("screened"):
            verdict.append(
                f"{fa_counters['screened']:.0f} analytics contribution(s) "
                "screened out before the merge — hostile or corrupt "
                "sketches never touched the aggregate")
        if fa_counters.get("stale_submissions"):
            verdict.append(
                f"{fa_counters['stale_submissions']:.0f} stale analytics "
                "submission(s) dropped (stragglers answering an "
                "already-closed round; nothing aggregated twice)")
        if fa_counters.get("quorum_rounds"):
            verdict.append(
                f"{fa_counters['quorum_rounds']:.0f} analytics round(s) "
                "closed on quorum after the deadline — the missing "
                "clients were named in the log and dropped")
        if fa_counters.get("aborts"):
            verdict.append(
                f"{fa_counters['aborts']:.0f} analytics round(s) ABORTED "
                "below quorum after exhausting deadline extensions — "
                "the task failed loudly rather than publish a "
                "partial answer")
        if fa_counters.get("dp_epsilon"):
            verdict.append(
                f"analytics answers carry central DP: accounted epsilon "
                f"{fa_counters['dp_epsilon']:.2f} (zCDP conversion; see "
                "fa/dp_epsilon)")
        if "hh_recall" in fa_counters and fa_counters["hh_recall"] < 0.95:
            verdict.append(
                f"heavy-hitter recall {fa_counters['hh_recall']:.2f} vs "
                "the plaintext reference — widen the vote table or lower "
                "the threshold")
    else:
        notes.setdefault(
            "analytics",
            "no data: no fa/* metrics (no federated-analytics rounds in "
            "this run)")

    # -- performance attribution (program catalog + roofline) -------------
    # three verdicts the multichip plan and perf triage read directly:
    # the top peak-HBM consumer (ROADMAP item 1's direct input), treedef
    # churn (a program recompiling N times), and a phase whose achieved
    # bandwidth collapsed against its own per-round history
    attribution = report.get("attribution") or {}
    profile: Dict[str, Any] = {}
    if attribution.get("programs"):
        top = attribution.get("top_hbm_program")
        mem_limit = 0.0
        for key, v in (report.get("mem_gauges") or {}).items():
            if key.split("{")[0] == "mem/bytes_limit":
                mem_limit = max(mem_limit, float(v or 0.0))
        profile = {
            "programs": attribution["programs"],
            "top_hbm_program": top,
            "device_kind": attribution.get("device_kind"),
            "hbm_limit_bytes": mem_limit or None,
            "captures": [rec for rec in metric_records
                         if rec.get("kind") == "profile_capture"],
        }
        if top:
            headroom = ""
            if mem_limit > 0:
                headroom = (f"; {_fmt_bytes(mem_limit - top['peak_hbm_bytes'])}"
                            " HBM headroom left on this device")
            # a 4-bit-resident base (quant/base_bytes gauge) is the
            # largest single headroom lever: name what it occupies vs
            # what a bf16 base would, so the verdict explains where the
            # headroom came from (or what enabling int4/nf4 would buy)
            base4 = 0.0
            for key, v in (report.get("mem_gauges") or {}).items():
                if key.split("{")[0] == "quant/base_bytes":
                    base4 = max(base4, float(v or 0.0))
            if base4 > 0:
                # packed nibbles + f32/64-block scale = 0.28125x of bf16
                headroom += (
                    f"; 4-bit-resident base holds {_fmt_bytes(base4)} "
                    f"packed (a bf16 base would hold "
                    f"{_fmt_bytes(base4 / 0.28125)} — "
                    f"{_fmt_bytes(base4 / 0.28125 - base4)} of the "
                    "headroom is int4/nf4 residency)")
            n_shards = int((top.get("mesh_spec") or {}).get("n_shards") or 1)
            if n_shards > 1:
                # XLA memory analysis is per-device, so a sharded
                # program's peak is already ONE shard's plan — judge it
                # against the per-device limit, not the model total
                axes = (top.get("mesh_spec") or {}).get("axes") or {}
                axes_str = ",".join(
                    f"{k}={v}" for k, v in sorted(axes.items()) if v > 1)
                verdict.append(
                    f"top HBM-headroom consumer: program {top['name']!r} "
                    f"holds {_fmt_bytes(top['peak_hbm_bytes'])} live at "
                    f"peak PER SHARD across {n_shards} shards ({axes_str}"
                    f"; {top.get('roofline_class') or 'class unknown'})"
                    + headroom
                    + " — judged against the per-device limit")
            else:
                verdict.append(
                    f"top HBM-headroom consumer: program {top['name']!r} "
                    f"holds {_fmt_bytes(top['peak_hbm_bytes'])} live at "
                    f"peak ({top.get('roofline_class') or 'class unknown'})"
                    + headroom
                    + " — the program multichip sharding must split")
        for prog in attribution["programs"]:
            if prog.get("multi_shape"):
                continue  # per-shape variants are that program's design
            if prog.get("recompiles", 0) >= recompile_threshold:
                verdict.append(
                    f"program {prog['name']!r} recompiled "
                    f"{prog['recompiles']} time(s) — input treedef/shape "
                    "churn; pin the input signature or mark the site "
                    "multi_shape")
        # bandwidth collapse vs own history: an attributed phase whose
        # last-round wall blew past its own median moved the same bytes
        # at a fraction of the bandwidth
        attr_phases = {p["phase"]: p for p in attribution.get("phases") or []
                       if p.get("bytes_accessed") and p.get("wall_ms")}
        for phase, p in sorted(attr_phases.items()):
            walls = [(r["round"], r["phases"].get(phase))
                     for r in report.get("rounds") or []]
            walls = [(n, w) for n, w in walls if w]
            if len(walls) < 4:
                continue
            med = _median([w for _, w in walls[:-1]])
            last_round, last_wall = walls[-1]
            if med > 0 and last_wall > 2.0 * med:
                per_round_bytes = p["bytes_accessed"] / len(walls)
                verdict.append(
                    f"phase {phase!r} bandwidth collapsed at round "
                    f"{last_round}: {per_round_bytes / (last_wall / 1e3) / 1e9:.2f}"
                    f" GB/s vs {per_round_bytes / (med / 1e3) / 1e9:.2f} GB/s "
                    "over its own history — host interference or device "
                    "contention on that round")
        for cap in profile["captures"]:
            verdict.append(
                f"deep trace captured at round {cap.get('round')} "
                f"(trigger: {cap.get('rule')}) -> {cap.get('trace_dir')}")
    else:
        notes.setdefault(
            "profile",
            "no data: programs.jsonl missing (run predates the program "
            "catalog, or profiling was disabled)")

    # -- live plane (online-doctor alerts + stream accounting) ------------
    # doctor_alert records are appended to telemetry.jsonl BY the online
    # doctor at the round a rule trips; surfacing them here proves the
    # alert fired mid-run, not in this autopsy
    alerts = [rec for rec in metric_records
              if rec.get("kind") == "doctor_alert"]
    latest_live: Dict[str, float] = {}
    for rec in metric_records:
        name = rec.get("name", "")
        if name.startswith("live/"):
            labels = tuple(sorted((rec.get("labels") or {}).items()))
            latest_live[(name, labels)] = float(
                rec.get("value", rec.get("count", 0)) or 0)
    live_counters: Dict[str, float] = {}
    for (name, _), val in latest_live.items():
        key = name.split("/", 1)[1]
        live_counters[key] = live_counters.get(key, 0.0) + val
    live: Dict[str, Any] = {"alerts": alerts, "counters": live_counters}
    if alerts:
        first = alerts[0]
        verdict.append(
            f"online doctor fired {len(alerts)} alert(s) MID-RUN — first: "
            f"[{first.get('rule')}] round {first.get('round')}: "
            f"{first.get('verdict')}")
    gaps = live_counters.get("seq_gaps", 0.0)
    if gaps:
        verdict.append(
            f"live metric stream lost {gaps:.0f} frame(s) in flight "
            "(accounted in live/seq_gaps; totals self-healed via "
            "cumulative frames)")
    if not alerts and not live_counters:
        notes.setdefault(
            "live", "no data: no live/* metrics or doctor_alert records "
            "(run predates the live plane, or live_telemetry was off)")

    # -- causal critical path (tracepath) ---------------------------------
    # the report already assembled the federation-wide trace; here we only
    # cross-reference it with the flagged stragglers so the verdict can
    # tell "the round waits on this client" apart from "this client is
    # slow but hidden behind slack"
    cp = dict(report.get("critical_path") or {})
    cp_rounds = cp.get("rounds") or []
    tracepath: Dict[str, Any] = {
        "rounds_traced": len(cp_rounds),
        "by_kind_ms": cp.get("by_kind_ms") or {},
        "clients_on_path": {},
        "stragglers": [],
    }
    if cp_rounds:
        on_path_rounds: Dict[str, List[int]] = {}
        for row in cp_rounds:
            for cid in row.get("clients_on_path") or []:
                on_path_rounds.setdefault(str(cid), []).append(row["round"])
        tracepath["clients_on_path"] = on_path_rounds
        flagged = {str(r["client"]) for r in stragglers}
        flagged.update(str(r["client"]) for r in span_stragglers)
        for cid in sorted(flagged):
            hit = on_path_rounds.get(cid, [])
            savings = [
                float((row.get("straggler") or {}).get("savings_ms") or 0.0)
                for row in cp_rounds
                if str((row.get("straggler") or {}).get("client")) == cid
                and (row.get("straggler") or {}).get("on_critical_path")]
            entry = {
                "client": cid,
                "rounds_on_path": hit,
                "rounds_traced": len(cp_rounds),
                "max_savings_ms": max(savings) if savings else 0.0,
            }
            tracepath["stragglers"].append(entry)
            if hit:
                save = (f" — up to {entry['max_savings_ms']:.0f} ms/round "
                        "recoverable" if savings else "")
                verdict.append(
                    f"straggler client {cid} is ON the critical path in "
                    f"{len(hit)}/{len(cp_rounds)} traced round(s) "
                    f"{hit}: the round waits on it{save}")
            else:
                verdict.append(
                    f"straggler client {cid} has slack: never on the "
                    f"critical path across {len(cp_rounds)} traced "
                    "round(s) — the round does not wait on it")
    else:
        notes.setdefault(
            "tracepath",
            "no data: no spans to assemble a causal trace from")

    if not (fr_events or health_events or report["n_spans"]
            or report.get("n_metrics")):
        notes["run"] = f"no telemetry data of any kind under {run_dir}"
    if not verdict:
        verdict.append("no issues detected")

    return {
        "schema": "fedml_tpu.telemetry.doctor/v1",
        "run_dir": run_dir,
        "notes": notes,
        "crash": crash,
        "clients": sorted(clients),
        "stragglers": stragglers,
        "span_stragglers": span_stragglers,
        "anomalies": anomalies,
        "memory": memory,
        "compression": compression,
        "services": services,
        "serving": serving,
        "connectivity": connectivity,
        "recovery": recovery,
        "jobplane": jobplane,
        "tiers": tiers,
        "secagg": secagg,
        "integrity": integrity,
        "analytics": analytics,
        "profile": profile,
        "live": live,
        "tracepath": tracepath,
        "verdict": verdict,
    }


def format_doctor(d: Dict) -> str:
    lines: List[str] = []
    add = lines.append
    add(f"telemetry doctor: {d['run_dir']}")
    add("")
    add("verdict:")
    for v in d["verdict"]:
        add(f"  - {v}")
    notes = d.get("notes") or {}

    add("")
    add("crash context:")
    crash = d.get("crash")
    if crash:
        add(f"  reason: {crash['reason']}"
            + (f" ({crash['exc_type']}: {crash['exc_message']})"
               if crash.get("exc_type") else ""))
        add(f"  last round seen: {crash['last_round']}; "
            f"last checkpoint: {crash['last_checkpoint_round']}")
        for e in crash["last_events"][-4:]:
            add(f"    last event: {e.get('kind')} "
                + " ".join(f"{k}={v}" for k, v in e.items()
                           if k not in ("kind", "ts") and not
                           isinstance(v, (dict, list))))
    else:
        add(f"  {notes.get('crash', 'no data')}")

    add("")
    add("stragglers (latency EWMA vs cohort median):")
    if d["stragglers"]:
        for r in d["stragglers"]:
            lat = (f" (mean {r['mean_latency_ms']:.0f} ms)"
                   if r["mean_latency_ms"] is not None else "")
            add(f"  client {r['client']}: {r['straggler_score']:.2f}x "
                f"median over {r['rounds']} rounds" + lat)
    elif d["clients"]:
        add("  none flagged")
    elif d.get("span_stragglers"):
        add(f"  {notes.get('stragglers', '')}")
        for r in d["span_stragglers"][:8]:
            add(f"  client {r['client']}: slowest in {r['rounds_slowest']} "
                f"round(s), mean {r['mean_duration_ms']:.1f} ms "
                f"({100 * r['mean_share']:.0f}% of client time)")
    else:
        add(f"  {notes.get('stragglers', notes.get('health', 'no data'))}")

    add("")
    add("anomalous clients (robust z on update-norm / train-loss):")
    if d["anomalies"]:
        for r in d["anomalies"]:
            add(f"  client {r['client']}: median anomaly score "
                f"{r['anomaly_score']:.1f} (max |z| {r['max_abs_z']:.1f})")
    elif d["clients"]:
        add("  none flagged")
    else:
        add(f"  {notes.get('health', 'no data')}")

    add("")
    add("memory (per phase):")
    if d["memory"]:
        for phase, r in sorted(d["memory"].items()):
            line = (f"  {phase:<12s} {r['metric']}: "
                    f"{_fmt_bytes(r['first_bytes'])} -> "
                    f"{_fmt_bytes(r['last_bytes'])} over {r['samples']} "
                    f"samples ({_fmt_bytes(r['slope_bytes_per_round'])}/round)")
            if "rounds_to_limit" in r:
                line += f", ~{r['rounds_to_limit']:.0f} rounds to limit"
            add(line)
    else:
        add(f"  {notes.get('memory', 'no data')}")

    add("")
    add("compression / wire:")
    comp = d["compression"]
    if comp.get("raw_bytes"):
        add(f"  raw {comp['raw_bytes']:.0f} B -> wire "
            f"{comp['wire_bytes']:.0f} B (ratio {comp['ratio']:.2f}x)")
    for name, v in sorted((comp.get("wire_counters") or {}).items()):
        add(f"  {name:<44s}{v:>14.0f}")
    for o in comp.get("outlier_spans", [])[:8]:
        add(f"  outlier: {o['name']} took {o['duration_ms']:.1f} ms "
            f"(p50 {o['p50_ms']:.1f} ms)")
    if not comp.get("raw_bytes") and not comp.get("wire_counters"):
        add(f"  {notes.get('compression', 'no data')}")

    add("")
    add("connectivity (disconnects / retries / quorum / dropout-rejoin):")
    conn = d.get("connectivity") or {}
    counters = conn.get("counters") or {}
    if counters:
        for name, v in sorted(counters.items()):
            add(f"  resilience/{name:<33s}{v:>14.0f}")
    for cid, r in sorted((conn.get("evicted_clients") or {}).items()):
        rj = (conn.get("rejoined_clients") or {}).get(cid)
        add(f"  client {cid}: evicted at round {r}, "
            + (f"rejoined at round {rj}" if rj is not None
               else "never rejoined"))
    if not counters and not conn.get("events"):
        add(f"  {notes.get('connectivity', 'no data')}")

    add("")
    add("recovery (restarts / journal replay):")
    rec = d.get("recovery") or {}
    rec_counters = rec.get("counters") or {}
    if rec_counters or rec.get("events") or rec.get("secagg_aborts"):
        for name, v in sorted(rec_counters.items()):
            add(f"  resilience/{name:<33s}{v:>14.0f}")
        for e in (rec.get("events") or [])[-6:]:
            add("  event: " + " ".join(
                f"{k}={v}" for k, v in e.items()
                if k not in ("kind", "ts") and not isinstance(v, dict)))
        for e in (rec.get("secagg_aborts") or [])[-4:]:
            add(f"  secagg abort: round {e.get('round')} "
                f"({e.get('uploads_dropped', 0)} masked upload(s) "
                "dropped)")
    else:
        add(f"  {notes.get('recovery', 'no data')}")

    add("")
    add("job plane (supervision / preemption / rescheduling):")
    jp = d.get("jobplane") or {}
    jp_counters = jp.get("counters") or {}
    if jp_counters or jp.get("events"):
        for name, v in sorted(jp_counters.items()):
            add(f"  sched/{name:<37s}{v:>14.0f}")
        for e in (jp.get("events") or [])[-8:]:
            add("  event: " + " ".join(
                f"{k}={v}" for k, v in e.items()
                if k not in ("kind", "ts") and not isinstance(v, dict)))
    else:
        add(f"  {notes.get('jobplane', 'no data')}")

    add("")
    add("tiers (hierarchical federation):")
    tiers = d.get("tiers") or {}
    tier_metrics = tiers.get("metrics") or {}
    if tier_metrics:
        for td, sig in sorted(tier_metrics.items(), key=lambda kv: kv[0]):
            row = " ".join(f"{k}={v:.0f}" for k, v in sorted(sig.items()))
            add(f"  tier {td}: {row}")
        for e in (tiers.get("events") or [])[-6:]:
            add("  event: " + " ".join(
                f"{k}={v}" for k, v in e.items()
                if k not in ("kind", "ts") and not isinstance(v, dict)))
    else:
        add(f"  {notes.get('tiers', 'no data')}")

    add("")
    add("secure aggregation (masked rounds / dropout recovery):")
    sa = d.get("secagg") or {}
    sa_counters = sa.get("counters") or {}
    if sa_counters or sa.get("events"):
        for name, v in sorted(sa_counters.items()):
            add(f"  secagg/{name:<36s}{v:>14.0f}")
        for e in (sa.get("events") or [])[-6:]:
            add("  event: " + " ".join(
                f"{k}={v}" for k, v in e.items()
                if k not in ("kind", "ts") and not isinstance(v, dict)))
    else:
        add(f"  {notes.get('secagg', 'no data')}")

    add("")
    add("update integrity (screen / quarantine / rollback):")
    integ = d.get("integrity") or {}
    int_counters = integ.get("counters") or {}
    if int_counters or integ.get("events"):
        for name, v in sorted(int_counters.items()):
            add(f"  integrity/{name:<33s}{v:>14.0f}")
        for cid, q in sorted((integ.get("quarantined_clients")
                              or {}).items()):
            add(f"  client {cid}: quarantined at round {q.get('round')} "
                f"until round {q.get('until_round')} ({q.get('reason')})")
        for rb in (integ.get("rollbacks") or [])[-6:]:
            add(f"  rollback: round {rb.get('round')} ({rb.get('reason')})")
    else:
        add(f"  {notes.get('integrity', 'no data')}")

    add("")
    add("federated analytics (sketch rounds / quorum / privacy):")
    fa = d.get("analytics") or {}
    fa_counters = fa.get("counters") or {}
    if fa_counters:
        for name, v in sorted(fa_counters.items()):
            add(f"  fa/{name:<40s}{v:>14.2f}")
        for task, v in sorted((fa.get("rounds_by_task") or {}).items()):
            add(f"  task {task}: {v:.0f} round(s)")
    else:
        add(f"  {notes.get('analytics', 'no data')}")

    add("")
    add("serving (live endpoint freshness / SLO):")
    serving = d.get("serving") or {}
    if serving:
        cur, pub = serving.get("round_current"), serving.get("round_published")
        add(f"  endpoint round {cur} / published round {pub} "
            f"({serving.get('swaps', 0)} swap(s), "
            f"{serving.get('rejected', 0)} rejected)")
        if serving.get("swap_stall_max_ms") is not None:
            add(f"  swap stall p99 {serving.get('swap_stall_p99_ms')} ms, "
                f"max {serving['swap_stall_max_ms']} ms")
        if serving.get("request_p99_ms") is not None:
            slo = serving.get("slo_ms")
            add(f"  request p99 {serving['request_p99_ms']} ms"
                + (f" (SLO {slo:.0f} ms)" if slo else ""))
        if serving.get("ttft_p95_ms") is not None:
            add(f"  ttft p95 {serving['ttft_p95_ms']} ms, tpot p95 "
                f"{serving.get('tpot_p95_ms')} ms, "
                f"{serving.get('tokens_per_s', 0)} tok/s")
        if serving.get("queue_wait_p95_ms") is not None:
            add(f"  admission queue wait p95 "
                f"{serving['queue_wait_p95_ms']} ms")
        if serving.get("batch_occupancy") is not None:
            add(f"  saturation: occupancy "
                f"{serving['batch_occupancy']:.2f}, queue depth "
                f"{serving.get('queue_depth', 0):.0f}, "
                f"{serving.get('tokens_in_flight', 0):.0f} tokens in "
                f"flight, KV {serving.get('kv_bytes_in_use', 0):.0f}/"
                f"{serving.get('kv_bytes_allocated', 0):.0f} B")
        for kind, row in sorted((serving.get("slo") or {}).items()):
            add(f"  slo[{kind}]: {row.get('slo_breaches', 0):.0f}/"
                f"{row.get('slo_total', 0):.0f} over the "
                f"{row.get('slo_target_ms', 0):.1f} ms target "
                f"(objective {serving.get('slo_objective') or 0.99:g})")
        if serving.get("shed_bursts"):
            add(f"  {serving['shed_bursts']} shed burst(s) recorded "
                f"(queue depth {serving.get('shed_queue_depth')} at last "
                "trip)")
    else:
        add(f"  {notes.get('serving', 'no data')}")

    add("")
    add("performance attribution (program catalog / roofline):")
    profile = d.get("profile") or {}
    if profile.get("programs"):
        top = profile.get("top_hbm_program")
        if top:
            n_shards = int((top.get("mesh_spec") or {}).get("n_shards") or 1)
            shard_note = (f" per shard x{n_shards}" if n_shards > 1 else "")
            add(f"  top HBM consumer: {top['name']} "
                f"({_fmt_bytes(top['peak_hbm_bytes'])} live at peak"
                f"{shard_note}, "
                f"{top.get('roofline_class') or 'class unknown'})")
        for p in profile["programs"][:8]:
            ai = p.get("arithmetic_intensity")
            add(f"  {p['name']:<30s} calls {p['calls']:>6d}  "
                f"AI {'-' if ai is None else format(ai, '.1f'):>7s}  "
                f"{p.get('roofline_class') or '-':<14s} "
                f"peak {_fmt_bytes(p['peak_hbm_bytes'])}  "
                f"recompiles {p['recompiles']}")
        for cap in profile.get("captures", [])[-4:]:
            add(f"  capture: round {cap.get('round')} "
                f"[{cap.get('rule')}] {cap.get('trace_dir')} "
                f"({cap.get('trace_bytes', 0)} B)")
    else:
        add(f"  {notes.get('profile', 'no data')}")

    add("")
    add("live plane (online doctor / metric stream):")
    live = d.get("live") or {}
    live_alerts = live.get("alerts") or []
    live_counters = live.get("counters") or {}
    if live_alerts or live_counters:
        for name, v in sorted(live_counters.items()):
            add(f"  live/{name:<38s}{v:>14.0f}")
        for a in live_alerts[-8:]:
            add(f"  alert [{a.get('rule')}] round {a.get('round')}: "
                f"{a.get('verdict')}")
    else:
        add(f"  {notes.get('live', 'no data')}")

    add("")
    add("service health:")
    if d["services"]:
        for name, v in sorted(d["services"].items()):
            add(f"  {name:<44s}{v!s:>14s}")
    else:
        add(f"  {notes.get('services', 'no data')}")

    add("")
    add("critical path:")
    tp = d.get("tracepath") or {}
    if tp.get("rounds_traced"):
        add(f"  rounds traced: {tp['rounds_traced']}")
        kinds = tp.get("by_kind_ms") or {}
        if kinds:
            add("  time by kind: " + ", ".join(
                f"{k} {v:.0f} ms" for k, v in sorted(kinds.items())))
        for s in tp.get("stragglers") or []:
            where = (f"ON path in rounds {s['rounds_on_path']}"
                     if s["rounds_on_path"] else "has slack (never on path)")
            add(f"  straggler client {s['client']}: {where}")
    else:
        add(f"  {notes.get('tracepath', 'no data')}")
    return "\n".join(lines)
