"""Typed metrics registry — Counter / Gauge / Histogram.

The reference streams metrics to a hosted MLOps plane over MQTT
(``core/mlops/mlops_metrics.py``); this registry is its process-local
replacement: thread-safe typed instruments with fixed histogram bucket
boundaries, exported both as JSONL (the run-dir sink the report CLI
consumes) and Prometheus text exposition (for scrape-based collection).

Metric names are ``/``-separated lowercase segments (``broker/bytes_in``)
— the same taxonomy the span layer uses; ``tools/check_span_names.py``
lints every instrumented literal against it.
"""
from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-z0-9_]+(/[a-z0-9_]+)*$")

# Latency buckets in milliseconds: sub-ms (JAX dispatch) through minutes
# (7B-scale compiles). The +inf bucket is implicit.
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000, 30000, 60000, 300000,
)

# Byte-size buckets: 64B frames through GB-scale model payloads.
BYTES_BUCKETS: Tuple[float, ...] = (
    64, 256, 1024, 4096, 16384, 65536, 262144, 1048576,
    4194304, 16777216, 67108864, 268435456, 1073741824,
)


class Counter:
    """Monotonic counter. ``inc`` only; negative increments are rejected."""

    kind = "counter"

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict:
        return {"kind": self.kind, "value": self.value}


class Gauge:
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> Dict:
        return {"kind": self.kind, "value": self.value}


class Histogram:
    """Fixed-boundary histogram with percentile estimation.

    Percentiles are estimated Prometheus-style: find the bucket holding the
    target rank, interpolate linearly inside it (the +inf bucket clamps to
    the observed max so a long tail can't fabricate infinity).
    """

    kind = "histogram"

    def __init__(self, name: str, lock: threading.Lock,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self._lock = lock
        bounds = tuple(sorted(buckets or DEFAULT_BUCKETS_MS))
        if not bounds:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1 → the +inf bucket
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def _percentile_locked(self, q: float) -> float:
        if self._count == 0:
            return 0.0
        rank = q * self._count
        seen = 0
        lo = 0.0
        for i, b in enumerate(self.bounds):
            c = self._counts[i]
            if seen + c >= rank:
                frac = (rank - seen) / max(c, 1)
                return min(lo + (b - lo) * frac, self._max)
            seen += c
            lo = b
        return self._max  # rank lands in the +inf bucket

    def percentile(self, q: float) -> float:
        with self._lock:
            return self._percentile_locked(q)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> Dict:
        with self._lock:
            empty = self._count == 0
            return {
                "kind": self.kind,
                "count": self._count,
                "sum": self._sum,
                "min": 0.0 if empty else self._min,
                "max": 0.0 if empty else self._max,
                "p50": self._percentile_locked(0.50),
                "p95": self._percentile_locked(0.95),
                "p99": self._percentile_locked(0.99),
                "buckets": dict(zip([*map(str, self.bounds), "+inf"],
                                    self._counts)),
            }

    def merge_delta(self, bucket_deltas: List[int], count_delta: int,
                    sum_delta: float, observed_min: Optional[float] = None,
                    observed_max: Optional[float] = None) -> None:
        """Fold another histogram's *delta* into this one (live-telemetry
        collector merge). ``bucket_deltas`` must align with ``bounds`` +1
        for the +inf bucket; min/max are the REMOTE observed extremes, not
        deltas, so they merge as min/max."""
        if len(bucket_deltas) != len(self._counts):
            raise ValueError(
                f"histogram {self.name}: merge of {len(bucket_deltas)} "
                f"buckets into {len(self._counts)}")
        with self._lock:
            for i, d in enumerate(bucket_deltas):
                self._counts[i] += int(d)
            self._count += int(count_delta)
            self._sum += float(sum_delta)
            if observed_min is not None:
                self._min = min(self._min, float(observed_min))
            if observed_max is not None:
                self._max = max(self._max, float(observed_max))


def _labels_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class MetricsRegistry:
    """Process-local, thread-safe registry of typed instruments.

    One instrument per (name, labels); re-requesting returns the existing
    one, and requesting an existing name with a different type raises —
    that's the drift the span-name lint also catches statically.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple], object] = {}

    def _get(self, cls, name: str, labels, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} violates the taxonomy "
                "(lowercase [a-z0-9_] segments joined by '/')")
        key = (name, _labels_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, threading.Lock(), **kw)
                m.labels = dict(labels or {})
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    # -- exports ----------------------------------------------------------
    def _items(self) -> List:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def snapshot(self) -> List[Dict]:
        return [
            {"name": m.name, "labels": m.labels, **m.snapshot()}
            for m in self._items()
        ]

    def export_jsonl(self) -> List[str]:
        ts = time.time()
        return [json.dumps({"ts": ts, **rec}) for rec in self.snapshot()]

    def flush_jsonl(self, run_dir: str, filename: str = "telemetry.jsonl") -> str:
        """Append a snapshot of every instrument to the run-dir sink."""
        os.makedirs(run_dir, exist_ok=True)
        path = os.path.join(run_dir, filename)
        with open(path, "a") as f:
            for line in self.export_jsonl():
                f.write(line + "\n")
        return path

    def export_prometheus(self, name_prefix: Optional[str] = None) -> str:
        """Prometheus text exposition format, version 0.0.4.

        ``name_prefix`` restricts the export to one metric namespace
        (e.g. ``"live/"`` — the scrape endpoint appends the collector
        plane's own health to the aggregated node metrics this way)."""
        out: List[str] = []
        seen_types = set()
        for m in self._items():
            if name_prefix is not None and not m.name.startswith(name_prefix):
                continue
            pname = m.name.replace("/", "_")
            if pname not in seen_types:
                seen_types.add(pname)
                out.append(f"# TYPE {pname} {m.kind}")
            lbl = ",".join(f'{k}="{v}"' for k, v in sorted(m.labels.items()))
            suffix = "{" + lbl + "}" if lbl else ""
            if isinstance(m, Histogram):
                snap = m.snapshot()
                cum = 0
                for bound, c in snap["buckets"].items():
                    cum += c
                    le = f'le="{bound}"'
                    blbl = "{" + (lbl + "," if lbl else "") + le + "}"
                    out.append(f"{pname}_bucket{blbl} {cum}")
                out.append(f"{pname}_sum{suffix} {snap['sum']}")
                out.append(f"{pname}_count{suffix} {snap['count']}")
            else:
                out.append(f"{pname}{suffix} {m.value}")
        return "\n".join(out) + "\n"


_GLOBAL: Optional[MetricsRegistry] = None
_GLOBAL_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = MetricsRegistry()
        return _GLOBAL


def set_registry(registry: MetricsRegistry) -> None:
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = registry


def reset_registry() -> None:
    """Drop the process-global registry (test isolation)."""
    set_registry(MetricsRegistry())
