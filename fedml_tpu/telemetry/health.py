"""Per-client run health — straggler and anomaly scoring while training.

FedScale-style per-client runtime attribution, computed server-side from
signals the federation already carries (no new message round-trips):

- **round latency** per client (broadcast→upload wall time, or the SP
  loop's per-client train wall): each round scores a client as
  ``latency / cohort median``, and the straggler flag fires on the
  MEDIAN of those per-round scores — robust by construction, so one
  compile-heavy round 0 cannot brand a client for the whole run (the
  latency EWMA is also kept, as the reported smoothed latency);
- **update norm** of each client's delta vs the round's broadcast base —
  computed on the already-decoded aggregate path, including compressed
  deltas (int8 blocks / top-k values are summed without materializing a
  full f32 tree), so a noise-injected or diverging client stands out
  even under the PR 3 lossy transport;
- **train loss** piggybacked on the existing model-upload header.

Norms and losses are scored per round with a robust z (median/MAD over
this round's cohort, cohorts of ≥ 4); the per-client anomaly score is
the MEDIAN of per-round max-|z| values. Medians everywhere is
deliberate: small cohorts make single-round z spikes of 6–8 normal for
honest-but-heterogeneous clients (MAD instability), while an attacker
is extreme *every* round — and a flag additionally needs ≥ 3 scored
rounds of evidence, so a client seen once can't be branded. Scores land
three ways: ``health/*`` gauges in the metrics registry (labelled by
client), one ``client_health`` event per client per round in
``<run_dir>/health.jsonl``, and the flight-recorder ring — so both
``telemetry report`` and ``telemetry doctor`` can reconstruct who was
slow or weird, round by round, after the fact.
"""
from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from fedml_tpu.telemetry import flight_recorder
from fedml_tpu.telemetry.registry import get_registry

logger = logging.getLogger(__name__)

__all__ = [
    "HEALTH_FILENAME",
    "ClientHealthTracker",
    "log_health_event",
    "update_norm",
]

HEALTH_FILENAME = "health.jsonl"

_log_lock = threading.Lock()
_log_fh = None
_log_path: Optional[str] = None


def _sink_dir() -> Optional[str]:
    from fedml_tpu.telemetry.spans import get_tracer

    return get_tracer().sink_dir


def log_health_event(rec: Dict[str, Any]) -> None:
    """Append one event to ``<run_dir>/health.jsonl`` (write-through, so a
    crashed run keeps everything up to its last event). No-op until the
    tracer is bound to a run dir; the flight recorder still sees the
    event either way."""
    global _log_fh, _log_path
    run_dir = _sink_dir()
    if run_dir is None:
        return
    rec = {"ts": rec.get("ts", time.time()), **rec}
    path = os.path.join(run_dir, HEALTH_FILENAME)
    with _log_lock:
        if _log_fh is None or _log_path != path or not os.path.exists(path):
            if _log_fh is not None:
                try:
                    _log_fh.close()
                except OSError:  # pragma: no cover
                    pass
            os.makedirs(run_dir, exist_ok=True)
            _log_fh = open(path, "a")
            _log_path = path
        _log_fh.write(json.dumps(rec, default=str) + "\n")
        _log_fh.flush()


def reset_health_log() -> None:
    """Drop the cached append handle (test isolation)."""
    global _log_fh, _log_path
    with _log_lock:
        if _log_fh is not None:
            try:
                _log_fh.close()
            except OSError:  # pragma: no cover
                pass
        _log_fh = None
        _log_path = None


# -- update-norm helper ----------------------------------------------------
def update_norm(update: Any, base: Any = None) -> Optional[float]:
    """L2 norm of a client update, compression-aware.

    - ``CompressedTree`` **delta**: the norm is read straight off the
      compressed blocks (int8 q·scale, bf16 leaves, top-k values) — no
      full-tree decode, so the fused-aggregation path keeps its memory
      contract;
    - ``CompressedTree`` full model: decoded, then diffed against
      ``base``;
    - plain pytree: ``‖update − base‖₂`` (or ``‖update‖₂`` without a
      base).

    Returns None when the payload isn't norm-able (unknown codec, FHE
    ciphertexts, non-array leaves).
    """
    import jax
    import jax.numpy as jnp

    from fedml_tpu.compression import CompressedTree, get_codec

    def _tree_sq(tree, ref=None):
        # accumulate a TRACED scalar across leaves; the single float()
        # at the end is the only device→host sync, not one per leaf
        total = jnp.float32(0.0)
        leaves = jax.tree.leaves(tree)
        refs = jax.tree.leaves(ref) if ref is not None else [None] * len(leaves)
        for a, b in zip(leaves, refs):
            a = jnp.asarray(a).astype(jnp.float32)
            if b is not None:
                a = a - jnp.asarray(b).astype(jnp.float32)
            total = total + jnp.sum(jnp.square(a))
        return total

    try:
        if isinstance(update, CompressedTree):
            codec = get_codec(update.codec)
            if codec is None:
                return None
            if getattr(codec, "maskable", False):
                # a masked (secure-aggregation) update is exactly the
                # thing the server must NOT be able to introspect — no
                # norm, by design, not by limitation
                return None
            if not update.is_delta:
                tree = codec.decode(update)
                return math.sqrt(float(_tree_sq(tree, base)))
            from fedml_tpu.compression.codecs import _is_float_meta

            total = jnp.float32(0.0)
            for parts, (dt, shape) in zip(update.arrays, update.meta):
                if not _is_float_meta(dt):
                    # int/bool leaves ride the wire uncompressed as a
                    # single passthrough array — multi-part decode_leaf
                    # would unpack-fail on them
                    total = total + jnp.sum(jnp.square(
                        jnp.asarray(parts[0]).astype(jnp.float32)))
                elif codec.name == "topk":
                    # values carry the whole mass; indices are positions
                    total = total + jnp.sum(jnp.square(
                        jnp.asarray(parts[0]).astype(jnp.float32)))
                else:
                    leaf = codec.decode_leaf(parts, dt, shape)
                    total = total + jnp.sum(jnp.square(
                        leaf.astype(jnp.float32)))
            return math.sqrt(float(total))
        return math.sqrt(float(_tree_sq(update, base)))
    except (TypeError, ValueError):
        return None


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def robust_z(values: Dict[Any, float]) -> Dict[Any, float]:
    """Median/MAD z-score per key; {} when the cohort is too small for a
    meaningful spread. n < 4 is degenerate: with three values the MAD is
    the *smaller* of two deviations, so any legitimate spread between two
    honest clients explodes the third's z."""
    if len(values) < 4:
        return {}
    vals = list(values.values())
    med = _median(vals)
    mad = _median([abs(v - med) for v in vals])
    scale = 1.4826 * mad
    if scale <= 0:
        # degenerate cohort (ties): fall back to mean absolute deviation
        scale = sum(abs(v - med) for v in vals) / len(vals) or 1e-12
    return {k: (v - med) / scale for k, v in values.items()}


class ClientHealthTracker:
    """Server-side per-client health state machine.

    Drive it with :meth:`observe` as uploads arrive, then
    :meth:`finish_round` once the round's cohort is complete — that is
    when cross-client z-scores are computable. Thread-safe: cross-silo
    handlers run on the comm receive thread.
    """

    def __init__(self, registry=None, ewma_alpha: float = 0.4,
                 straggler_threshold: float = 2.0,
                 anomaly_threshold: float = 4.0,
                 min_rounds: int = 3,
                 heartbeat_window_s: float = 300.0):
        self._reg = registry or get_registry()
        self.ewma_alpha = float(ewma_alpha)
        self.straggler_threshold = float(straggler_threshold)
        self.anomaly_threshold = float(anomaly_threshold)
        self.min_rounds = int(min_rounds)
        self.heartbeat_window_s = float(heartbeat_window_s)
        self._lock = threading.Lock()
        self._pending: Dict[int, Dict[Any, Dict]] = {}
        self.latency_ewma: Dict[Any, float] = {}
        # per-round score histories, newest last (bounded); client-level
        # scores are MEDIANS of these
        self._score_hist: Dict[Any, deque] = {}
        self._z_hist: Dict[Any, deque] = {}
        self.last_seen: Dict[Any, float] = {}
        self.rounds_scored = 0

    def straggler_score(self, client_id: Any) -> float:
        """Median of the client's per-round latency/cohort-median scores
        (1.0 until any latency is observed)."""
        with self._lock:
            hist = self._score_hist.get(client_id)
            return _median(list(hist)) if hist else 1.0

    def anomaly_score(self, client_id: Any) -> float:
        """Median of the client's per-round max-|z| values."""
        with self._lock:
            hist = self._z_hist.get(client_id)
            return _median(list(hist)) if hist else 0.0

    # -- inputs -----------------------------------------------------------
    def heartbeat(self, client_id: Any, fields: Optional[Dict] = None) -> None:
        """A liveness ping piggybacked on an existing comm header."""
        now = time.time()
        with self._lock:
            self.last_seen[client_id] = now
            # "reporting" means heard from recently — a client that went
            # silent must age out of the gauge, not count forever
            n = sum(1 for ts in self.last_seen.values()
                    if now - ts <= self.heartbeat_window_s)
        self._reg.gauge("health/clients_reporting").set(n)
        if fields and fields.get("mem_bytes"):
            try:
                mem = float(fields["mem_bytes"])
            except (TypeError, ValueError):
                mem = float("nan")
            if math.isfinite(mem):
                self._reg.gauge(
                    "health/client_mem_bytes",
                    labels={"client": str(client_id)}).set(mem)
            else:
                self._nonfinite_dropped(client_id, "mem_bytes")

    def _nonfinite_dropped(self, client_id: Any, field: str) -> None:
        """A sick client shipped NaN/Inf in a heartbeat field — the
        reading is dropped (a single NaN would poison every median/MAD
        statistic downstream: NaN is absorbing under sort-based
        medians), counted, and left visible to the doctor."""
        self._reg.counter("health/nonfinite_dropped").inc()
        logger.warning("dropping non-finite %s heartbeat field from "
                       "client %s", field, client_id)

    def observe(self, client_id: Any, round_idx: int,
                latency_s: Optional[float] = None,
                update_norm: Optional[float] = None,
                train_loss: Optional[float] = None,
                heartbeat: Optional[Dict] = None) -> None:
        with self._lock:
            obs = self._pending.setdefault(int(round_idx), {}).setdefault(
                client_id, {})
            if latency_s is not None:
                # a NaN latency (sick client clock, poisoned train_ms)
                # would ride into the cohort-median straggler scoring
                if math.isfinite(latency_s):
                    obs["latency_s"] = float(latency_s)
                else:
                    self._nonfinite_dropped(client_id, "latency")
            if update_norm is not None and math.isfinite(update_norm):
                obs["update_norm"] = float(update_norm)
            if train_loss is not None:
                try:
                    loss = float(train_loss)
                except (TypeError, ValueError):
                    loss = None
                if loss is not None:
                    # same rule as update_norm: non-finite never enters
                    # the median-MAD z scoring
                    if math.isfinite(loss):
                        obs["train_loss"] = loss
                    else:
                        self._nonfinite_dropped(client_id, "train_loss")
            self.last_seen[client_id] = time.time()
        if heartbeat:
            self.heartbeat(client_id, heartbeat)

    # -- scoring ----------------------------------------------------------
    def finish_round(self, round_idx: int) -> Dict[Any, Dict]:
        """Score the round's cohort; returns {client: health record}."""
        with self._lock:
            cohort = self._pending.pop(int(round_idx), {})
            if not cohort:
                return {}
            a = self.ewma_alpha
            lats = {}
            for cid, obs in cohort.items():
                lat = obs.get("latency_s")
                if lat is None:
                    continue
                lats[cid] = lat
                prev = self.latency_ewma.get(cid)
                self.latency_ewma[cid] = (
                    lat if prev is None else a * lat + (1 - a) * prev)
            med_lat = _median(list(lats.values())) if lats else 0.0
            z_norm = robust_z({c: o["update_norm"] for c, o in cohort.items()
                               if "update_norm" in o})
            z_loss = robust_z({c: o["train_loss"] for c, o in cohort.items()
                               if "train_loss" in o})
            out: Dict[Any, Dict] = {}
            for cid, obs in cohort.items():
                # per-round scores vs THIS round's cohort; client-level
                # scores are medians across rounds, so one compile- or
                # MAD-instability-polluted round cannot brand an honest
                # client (nor absolve a consistently bad one), and a
                # flag needs min_rounds of evidence
                round_score = (lats[cid] / med_lat
                               if cid in lats and med_lat > 0 else 1.0)
                hist = self._score_hist.setdefault(cid, deque(maxlen=64))
                hist.append(round_score)
                s_score = _median(list(hist))
                raw_anom = max(abs(z_norm.get(cid, 0.0)),
                               abs(z_loss.get(cid, 0.0)))
                zh = self._z_hist.setdefault(cid, deque(maxlen=64))
                zh.append(raw_anom)
                anom = _median(list(zh))
                enough = len(hist) >= self.min_rounds
                out[cid] = {
                    "kind": "client_health",
                    "round": int(round_idx),
                    "client": cid,
                    "latency_ms": round(obs["latency_s"] * 1e3, 3)
                    if "latency_s" in obs else None,
                    "latency_ewma_ms": (
                        round(self.latency_ewma[cid] * 1e3, 3)
                        if cid in self.latency_ewma else None),
                    "update_norm": obs.get("update_norm"),
                    "train_loss": obs.get("train_loss"),
                    "z_norm": round(z_norm.get(cid, 0.0), 3),
                    "z_loss": round(z_loss.get(cid, 0.0), 3),
                    "round_straggler_score": round(round_score, 3),
                    "straggler_score": round(s_score, 3),
                    "round_max_abs_z": round(raw_anom, 3),
                    "anomaly_score": round(anom, 3),
                    "flagged_straggler": (
                        enough and s_score >= self.straggler_threshold),
                    "flagged_anomaly": (
                        enough and anom >= self.anomaly_threshold),
                }
            self.rounds_scored += 1
        for cid, rec in out.items():
            labels = {"client": str(cid)}
            self._reg.gauge("health/straggler_score", labels=labels).set(
                rec["straggler_score"])
            self._reg.gauge("health/anomaly_score", labels=labels).set(
                rec["anomaly_score"])
            if rec["latency_ms"] is not None:
                self._reg.histogram("health/client_round_ms",
                                    labels=labels).observe(rec["latency_ms"])
            log_health_event(rec)
            if rec["flagged_straggler"] or rec["flagged_anomaly"]:
                flight_recorder.record(
                    "health_flag",
                    **{k: v for k, v in rec.items() if k != "kind"})
        self._reg.counter("health/rounds_scored").inc()
        return out

    # -- outputs ----------------------------------------------------------
    def flagged(self) -> Dict[str, List]:
        with self._lock:
            return {
                "stragglers": sorted(
                    c for c, h in self._score_hist.items()
                    if len(h) >= self.min_rounds
                    and _median(list(h)) >= self.straggler_threshold),
                "anomalies": sorted(
                    c for c, h in self._z_hist.items()
                    if len(h) >= self.min_rounds
                    and _median(list(h)) >= self.anomaly_threshold),
            }

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                "latency_ewma_s": dict(self.latency_ewma),
                "straggler_score": {
                    c: _median(list(h))
                    for c, h in self._score_hist.items() if h},
                "anomaly_score": {
                    c: _median(list(h))
                    for c, h in self._z_hist.items() if h},
                "rounds_scored": self.rounds_scored,
            }
