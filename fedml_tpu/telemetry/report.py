"""Run-report builder — turns a run dir's JSONL sinks into a timeline.

Consumes the files the telemetry layer writes under
``.fedml_logs/run_<id>/``:

- ``spans.jsonl``    — tracer spans (round/client phases, comm dispatch)
- ``events.jsonl``   — legacy MLOpsProfilerEvent spans (facade output)
- ``telemetry.jsonl``— metrics-registry snapshots (counters/gauges/hists)
- ``metrics.jsonl``  — MLOpsMetrics records (accuracy/loss per round)

and produces per-round wall time, per-phase p50/p95 (computed from the
raw recorded spans, not bucket estimates), straggler attribution, the
JAX compile-vs-execute split, and the broker comm-bytes breakdown.
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict, List

_ROUND_RE = re.compile(r"^round/(\d+)(?:/|$)")
_CLIENT_RE = re.compile(r"^round/\d+/client/([^/]+)/")
_NUM_SEG = re.compile(r"(?<=/)\d+(?=/|$)|^\d+(?=/|$)")


def _load_jsonl(path: str) -> List[Dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail line from a crashed writer
    return out


def normalize_name(name: str) -> str:
    """Collapse numeric ids to taxonomy placeholders:
    ``round/3/client/7/train`` → ``round/<n>/client/<id>/train``."""
    name = re.sub(r"^round/\d+", "round/<n>", name)
    name = re.sub(r"/client/[^/]+/", "/client/<id>/", name)
    name = _NUM_SEG.sub("<n>", name)
    return name


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def load_spans(run_dir: str) -> List[Dict]:
    spans = _load_jsonl(os.path.join(run_dir, "spans.jsonl"))
    for e in _load_jsonl(os.path.join(run_dir, "events.jsonl")):
        # legacy event records: {"event", "edge_id", started/ended/duration}
        if "event" in e and "name" not in e:
            e = dict(e)
            e["name"] = f"event/{e.pop('event')}"
        spans.append(e)
    return [s for s in spans if "name" in s and "duration_ms" in s]


def load_metrics(run_dir: str) -> List[Dict]:
    return _load_jsonl(os.path.join(run_dir, "telemetry.jsonl"))


def build_report(run_dir: str) -> Dict:
    spans = load_spans(run_dir)
    metrics = load_metrics(run_dir)

    # -- per-round timeline (one pass; client spans collected for the
    # straggler section as we go) ----------------------------------------
    rounds: Dict[int, Dict] = {}
    for s in spans:
        m = _ROUND_RE.match(s["name"])
        if not m:
            continue
        n = int(m.group(1))
        r = rounds.setdefault(n, {"round": n, "started": s["started"],
                                  "ended": s["ended"], "phases": {},
                                  "client_spans": []})
        r["started"] = min(r["started"], s["started"])
        r["ended"] = max(r["ended"], s["ended"])
        phase = normalize_name(s["name"])
        r["phases"].setdefault(phase, []).append(s["duration_ms"])
        if _CLIENT_RE.match(s["name"]):
            r["client_spans"].append(s)
    round_rows = []
    for n in sorted(rounds):
        r = rounds[n]
        round_rows.append({
            "round": n,
            "wall_ms": (r["ended"] - r["started"]) * 1e3,
            "phases": {p: sum(v) for p, v in sorted(r["phases"].items())},
        })

    # -- per-phase percentiles over the whole run -------------------------
    by_phase: Dict[str, List[float]] = {}
    for s in spans:
        by_phase.setdefault(normalize_name(s["name"]), []).append(
            s["duration_ms"])
    phase_rows = []
    for phase in sorted(by_phase):
        vals = sorted(by_phase[phase])
        phase_rows.append({
            "phase": phase,
            "count": len(vals),
            "p50_ms": _pct(vals, 0.50),
            "p95_ms": _pct(vals, 0.95),
            "p99_ms": _pct(vals, 0.99),
            "total_ms": sum(vals),
        })

    # -- straggler attribution -------------------------------------------
    stragglers = []
    for n in sorted(rounds):
        client_spans = rounds[n]["client_spans"]
        if not client_spans:
            continue
        worst = max(client_spans, key=lambda s: s["duration_ms"])
        total = sum(s["duration_ms"] for s in client_spans)
        stragglers.append({
            "round": n,
            "client": _CLIENT_RE.match(worst["name"]).group(1),
            "duration_ms": worst["duration_ms"],
            "share": worst["duration_ms"] / total if total else 0.0,
        })

    # -- compile vs execute ----------------------------------------------
    compile_ms = sum(s.get("compile_ms", 0.0) for s in spans)
    round_total = sum(r["wall_ms"] for r in round_rows)

    # -- comm bytes (latest snapshot per metric name+labels) --------------
    comm: Dict[str, float] = {}
    for rec in metrics:
        name = rec.get("name", "")
        if rec.get("kind") == "counter" and (
                name.startswith("broker/") or name.startswith("comm/")):
            lbl = ",".join(f"{k}={v}"
                           for k, v in sorted((rec.get("labels") or {}).items()))
            comm[name + ("{" + lbl + "}" if lbl else "")] = rec["value"]

    # -- stitched (cross-process) spans ----------------------------------
    stitched = [s for s in spans if s.get("remote_parent")]

    return {
        "run_dir": run_dir,
        "n_spans": len(spans),
        "rounds": round_rows,
        "phases": phase_rows,
        "stragglers": stragglers,
        "compile_ms": compile_ms,
        "execute_ms": max(round_total - compile_ms, 0.0),
        "comm_bytes": comm,
        "stitched_spans": stitched,
    }


def format_report(report: Dict) -> str:
    lines: List[str] = []
    add = lines.append
    add(f"telemetry report: {report['run_dir']} "
        f"({report['n_spans']} spans)")
    add("")
    add("per-round timeline:")
    for r in report["rounds"]:
        add(f"  round {r['round']}: wall {r['wall_ms']:.1f} ms")
        for phase, total in r["phases"].items():
            add(f"    {phase:<42s} {total:>10.1f} ms")
    add("")
    add("per-phase percentiles (all rounds):")
    add(f"  {'phase':<44s}{'count':>6s}{'p50 ms':>10s}{'p95 ms':>10s}"
        f"{'p99 ms':>10s}")
    for p in report["phases"]:
        add(f"  {p['phase']:<44s}{p['count']:>6d}{p['p50_ms']:>10.1f}"
            f"{p['p95_ms']:>10.1f}{p['p99_ms']:>10.1f}")
    if report["compile_ms"]:
        add("")
        add(f"jax compile-vs-execute: compile {report['compile_ms']:.1f} ms, "
            f"execute {report['execute_ms']:.1f} ms")
    if report["stragglers"]:
        add("")
        add("straggler attribution (slowest client per round):")
        for s in report["stragglers"]:
            add(f"  round {s['round']}: client {s['client']} "
                f"{s['duration_ms']:.1f} ms ({100 * s['share']:.0f}% of "
                "client time)")
    if report["comm_bytes"]:
        add("")
        add("comm bytes breakdown:")
        for name, v in sorted(report["comm_bytes"].items()):
            add(f"  {name:<44s}{v:>14.0f}")
    if report["stitched_spans"]:
        add("")
        add(f"cross-process stitched spans: {len(report['stitched_spans'])}")
        for s in report["stitched_spans"][:10]:
            add(f"  {s['name']} trace={s['trace_id'][:8]} "
                f"parent={s['parent_id']} (publisher-side origin)")
    return "\n".join(lines)
