"""Run-report builder — turns a run dir's JSONL sinks into a timeline.

Consumes the files the telemetry layer writes under
``.fedml_logs/run_<id>/``:

- ``spans.jsonl``    — tracer spans (round/client phases, comm dispatch)
- ``events.jsonl``   — legacy MLOpsProfilerEvent spans (facade output)
- ``telemetry.jsonl``— metrics-registry snapshots (counters/gauges/hists)
- ``metrics.jsonl``  — MLOpsMetrics records (accuracy/loss per round)

and produces per-round wall time, per-phase p50/p95 (computed from the
raw recorded spans, not bucket estimates), straggler attribution, the
JAX compile-vs-execute split, and the broker comm-bytes breakdown.
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict, List

_ROUND_RE = re.compile(r"^round/(\d+)(?:/|$)")
_CLIENT_RE = re.compile(r"^round/\d+/client/([^/]+)/")
_NUM_SEG = re.compile(r"(?<=/)\d+(?=/|$)|^\d+(?=/|$)")


def _load_jsonl(path: str) -> List[Dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail line from a crashed writer
    return out


def normalize_name(name: str) -> str:
    """Collapse numeric ids to taxonomy placeholders:
    ``round/3/client/7/train`` → ``round/<n>/client/<id>/train``."""
    name = re.sub(r"^round/\d+", "round/<n>", name)
    name = re.sub(r"/client/[^/]+/", "/client/<id>/", name)
    name = _NUM_SEG.sub("<n>", name)
    return name


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _spans_from_raw(spans_raw: List[Dict], events_raw: List[Dict]
                    ) -> List[Dict]:
    spans = list(spans_raw)
    for e in events_raw:
        # legacy event records: {"event", "edge_id", started/ended/duration}
        if "event" in e and "name" not in e:
            e = dict(e)
            e["name"] = f"event/{e.pop('event')}"
        spans.append(e)
    return [s for s in spans if "name" in s and "duration_ms" in s]


class RunData:
    """Single-pass shared load of a run dir's JSONL sinks.

    Every sink file is parsed at most once, whoever asks first; the
    report's sections, the doctor, and the trace assembler all consume
    the same cached parse. Build one per run dir and pass it to
    ``build_report``/``build_doctor`` when composing them (the CLI's
    ``doctor`` builds the report internally and would otherwise re-read
    every file)."""

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        self._raw: Dict[str, List[Dict]] = {}

    def raw(self, filename: str) -> List[Dict]:
        if filename not in self._raw:
            self._raw[filename] = _load_jsonl(
                os.path.join(self.run_dir, filename))
        return self._raw[filename]

    @property
    def spans(self) -> List[Dict]:
        return _spans_from_raw(self.raw("spans.jsonl"),
                               self.raw("events.jsonl"))

    @property
    def metrics(self) -> List[Dict]:
        return self.raw("telemetry.jsonl")

    @property
    def programs(self) -> List[Dict]:
        return self.raw("programs.jsonl")

    @property
    def health(self) -> List[Dict]:
        return self.raw("health.jsonl")

    @property
    def flight(self) -> List[Dict]:
        return self.raw("flight_recorder.jsonl")

    @property
    def trace_records(self) -> List[Dict]:
        """Raw span + point-event records for trace assembly: the local
        sink plus the live-plane-collected remote sink."""
        from fedml_tpu.telemetry.tracing.assemble import (
            REMOTE_SPANS_FILENAME,
        )

        return self.raw("spans.jsonl") + self.raw(REMOTE_SPANS_FILENAME)


def load_spans(run_dir: str) -> List[Dict]:
    return _spans_from_raw(
        _load_jsonl(os.path.join(run_dir, "spans.jsonl")),
        _load_jsonl(os.path.join(run_dir, "events.jsonl")))


def load_metrics(run_dir: str) -> List[Dict]:
    return _load_jsonl(os.path.join(run_dir, "telemetry.jsonl"))


def load_programs(run_dir: str) -> List[Dict]:
    """``programs.jsonl`` — the per-run program-catalog snapshot (one
    line per named XLA program; the file is rewritten whole at flush, so
    every line is current)."""
    return _load_jsonl(os.path.join(run_dir, "programs.jsonl"))


def build_report(run_dir) -> Dict:
    data = run_dir if isinstance(run_dir, RunData) else RunData(run_dir)
    run_dir = data.run_dir
    spans = data.spans
    metrics = data.metrics

    # partial runs degrade to explicit per-section notes, not tracebacks:
    # a crashed writer leaves missing/truncated sinks and the report must
    # still triage whatever did land
    notes: Dict[str, str] = {}
    if not spans:
        have = [f for f in ("spans.jsonl", "events.jsonl")
                if os.path.exists(os.path.join(run_dir, f))]
        notes["spans"] = (
            "no data: " + (" and ".join(have) + " present but empty/"
                           "unparseable" if have
                           else "spans.jsonl/events.jsonl missing"))
    if not metrics:
        notes["metrics"] = (
            "no data: telemetry.jsonl "
            + ("present but empty/unparseable" if os.path.exists(
                os.path.join(run_dir, "telemetry.jsonl")) else "missing"))

    # -- per-round timeline (one pass; client spans collected for the
    # straggler section as we go) ----------------------------------------
    rounds: Dict[int, Dict] = {}
    for s in spans:
        m = _ROUND_RE.match(s["name"])
        if not m:
            continue
        n = int(m.group(1))
        phase = normalize_name(s["name"])
        prefetch = phase.endswith("/prefetch")
        r = rounds.get(n)
        if r is None:
            r = rounds[n] = {"round": n, "started": None, "ended": None,
                             "phases": {}, "client_spans": []}
        # prefetch spans run DURING the previous round (that is the
        # point) — counting them into this round's wall bounds would
        # overlap consecutive rounds and double-count execute time; they
        # get the dedicated stage_overlap section instead
        if not prefetch:
            r["started"] = (s["started"] if r["started"] is None
                            else min(r["started"], s["started"]))
            r["ended"] = (s["ended"] if r["ended"] is None
                          else max(r["ended"], s["ended"]))
        r["phases"].setdefault(phase, []).append(s["duration_ms"])
        if _CLIENT_RE.match(s["name"]):
            r["client_spans"].append(s)
    round_rows = []
    for n in sorted(rounds):
        r = rounds[n]
        # a round with only a prefetch span (staged but never dispatched,
        # e.g. an aborted run) has no wall bounds
        wall_ms = ((r["ended"] - r["started"]) * 1e3
                   if r["started"] is not None else 0.0)
        round_rows.append({
            "round": n,
            "wall_ms": wall_ms,
            "phases": {p: sum(v) for p, v in sorted(r["phases"].items())},
        })

    # -- per-phase percentiles over the whole run -------------------------
    by_phase: Dict[str, List[float]] = {}
    for s in spans:
        by_phase.setdefault(normalize_name(s["name"]), []).append(
            s["duration_ms"])
    phase_rows = []
    for phase in sorted(by_phase):
        vals = sorted(by_phase[phase])
        phase_rows.append({
            "phase": phase,
            "count": len(vals),
            "p50_ms": _pct(vals, 0.50),
            "p95_ms": _pct(vals, 0.95),
            "p99_ms": _pct(vals, 0.99),
            "total_ms": sum(vals),
        })

    # -- straggler attribution -------------------------------------------
    stragglers = []
    for n in sorted(rounds):
        client_spans = rounds[n]["client_spans"]
        if not client_spans:
            continue
        worst = max(client_spans, key=lambda s: s["duration_ms"])
        total = sum(s["duration_ms"] for s in client_spans)
        stragglers.append({
            "round": n,
            "client": _CLIENT_RE.match(worst["name"]).group(1),
            "duration_ms": worst["duration_ms"],
            "share": worst["duration_ms"] / total if total else 0.0,
        })

    # -- stage overlap (pipelined round engine) ---------------------------
    # how much of round r's host staging (the round/<r>/prefetch span,
    # recorded on the prefetch worker) ran while round r-1's program was
    # in flight. Rounds chain without a host barrier, so the device-busy
    # window for round r-1 is approximated by the wall interval between
    # consecutive train_agg dispatches — the chained-timing caveat from
    # PERF_NOTES applies (host spans cannot see device queue drain).
    ta_by_round: Dict[int, Dict] = {}
    prefetch_by_round: Dict[int, Dict] = {}
    for s in spans:
        m = _ROUND_RE.match(s["name"])
        if not m:
            continue
        n = int(m.group(1))
        tail = normalize_name(s["name"])
        if tail == "round/<n>/train_agg":
            ta_by_round.setdefault(n, s)
        elif tail == "round/<n>/prefetch":
            prefetch_by_round.setdefault(n, s)
    overlap_rows = []
    for n in sorted(prefetch_by_round):
        # rounds chain: the device is (assumed) busy from the FIRST prior
        # dispatch through the dispatch of round n, not just since n-1 —
        # prefetch(n) legitimately starts a hair before dispatch(n-1)
        # while rounds < n-1 are still in flight
        prior = [t for k, t in ta_by_round.items() if k < n]
        if not prior:
            continue
        p = prefetch_by_round[n]
        cur = ta_by_round.get(n)
        win_end = (cur["started"] if cur is not None
                   else max(t["ended"] for t in prior))
        lo = max(p["started"], min(t["started"] for t in prior))
        hi = min(p["ended"], win_end)
        dur_ms = max(p["duration_ms"], 1e-9)
        overlapped_ms = max(0.0, hi - lo) * 1e3
        overlap_rows.append({
            "round": n,
            "prefetch_ms": p["duration_ms"],
            "overlapped_ms": overlapped_ms,
            "ratio": min(overlapped_ms / dur_ms, 1.0),
        })
    total_prefetch = sum(r["prefetch_ms"] for r in overlap_rows)
    total_overlap = sum(r["overlapped_ms"] for r in overlap_rows)
    stage_overlap = {
        "rounds": overlap_rows,
        "prefetch_ms": total_prefetch,
        "overlapped_ms": total_overlap,
        "ratio": (total_overlap / total_prefetch) if total_prefetch else 0.0,
    }

    # -- compile vs execute ----------------------------------------------
    compile_ms = sum(s.get("compile_ms", 0.0) for s in spans)
    round_total = sum(r["wall_ms"] for r in round_rows)

    # -- comm bytes (latest snapshot per metric name+labels) --------------
    comm: Dict[str, float] = {}
    for rec in metrics:
        name = rec.get("name", "")
        if rec.get("kind") == "counter" and (
                name.startswith("broker/") or name.startswith("comm/")):
            lbl = ",".join(f"{k}={v}"
                           for k, v in sorted((rec.get("labels") or {}).items()))
            comm[name + ("{" + lbl + "}" if lbl else "")] = rec["value"]

    # -- compression ratio (raw payload bytes vs what hit the wire) -------
    def _sum_counter(prefix: str) -> float:
        return sum(v for name, v in comm.items()
                   if name.split("{")[0] == prefix)

    raw_bytes = _sum_counter("comm/raw_bytes")
    wire_bytes = (_sum_counter("comm/wire_bytes_out")
                  + _sum_counter("comm/offload_wire_bytes"))
    codec_phases = {
        p["phase"]: p for p in phase_rows
        if p["phase"].startswith("compress/")
    }
    compression = {
        "raw_bytes": raw_bytes,
        "wire_bytes": wire_bytes,
        # wire counters include control-frame overhead, so the ratio is a
        # lower bound on the payload compression factor
        "ratio": (raw_bytes / wire_bytes) if wire_bytes else 0.0,
        "encode": codec_phases.get("compress/encode"),
        "decode": codec_phases.get("compress/decode"),
    }

    # -- client health (health/* gauges, latest snapshot per client) ------
    client_health: Dict[str, Dict[str, float]] = {}
    mem_gauges: Dict[str, float] = {}
    services: Dict[str, float] = {}
    for rec in metrics:
        name = rec.get("name", "")
        labels = rec.get("labels") or {}
        if name in ("health/straggler_score", "health/anomaly_score") and (
                "client" in labels):
            row = client_health.setdefault(str(labels["client"]), {})
            row[name.split("/")[1]] = rec.get("value", 0.0)
        elif name.startswith(("mem/", "quant/")) and (
                rec.get("kind") == "gauge"):
            lbl = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            mem_gauges[name + ("{" + lbl + "}" if lbl else "")] = rec.get(
                "value", 0.0)
        elif name.startswith(("serving/", "scheduler/")):
            # endpoint/job health routed through the registry (not the old
            # private monitor dicts) — latest snapshot per name+labels
            lbl = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            key = name + ("{" + lbl + "}" if lbl else "")
            if rec.get("kind") == "histogram":
                services[key + ".p95"] = rec.get("p95", 0.0)
                services[key + ".count"] = rec.get("count", 0)
            else:
                services[key] = rec.get("value", 0.0)

    # -- serving token-latency attribution (TTFT / TPOT / decode rate) ----
    # full percentile rows per endpoint ("engine" = the engine's own
    # unlabeled instruments); metrics.jsonl is append-order cumulative
    # snapshots, so plain overwrite keeps the latest record per key
    serving_latency: Dict[str, Dict[str, float]] = {}
    for rec in metrics:
        name = rec.get("name", "")
        if name not in ("serving/ttft_ms", "serving/tpot_ms",
                        "serving/tokens_per_s", "serving/queue_wait_ms"):
            continue
        labels = rec.get("labels") or {}
        row = serving_latency.setdefault(labels.get("endpoint", "engine"), {})
        # "ttft_ms" -> "ttft": percentile keys carry ms already
        key = name.split("/", 1)[1]
        key = key[:-3] if key.endswith("_ms") else key
        if rec.get("kind") == "histogram":
            if not rec.get("count"):
                continue
            for q in ("p50", "p95", "p99"):
                row[f"{key}_{q}"] = rec.get(q, 0.0)
            row[f"{key}_count"] = rec.get("count", 0)
        else:
            row[key] = rec.get("value", 0.0)
    serving_latency = {ep: row for ep, row in serving_latency.items() if row}

    # -- performance attribution (program catalog × phase walls) ----------
    # programs.jsonl names every hot-path compiled program with its XLA
    # cost/memory analysis; joining against the measured phase walls
    # yields achieved FLOP/s + bytes/s per phase, a roofline class per
    # program, and the per-round MFU decomposition (same "xla"
    # provenance as bench.py's whole-run number)
    programs = data.programs
    attribution: Dict = {}
    if programs:
        from fedml_tpu.telemetry.profiling.roofline import build_attribution

        attribution = build_attribution(
            phases=phase_rows, rounds=round_rows, programs=programs,
            device_kind=next((p.get("device_kind") for p in programs
                              if p.get("device_kind")), None))
    else:
        notes.setdefault(
            "attribution",
            "no data: programs.jsonl missing (run predates the program "
            "catalog, or profiling was disabled via FEDML_PROFILE=0)")

    # -- stitched (cross-process) spans ----------------------------------
    stitched = [s for s in spans if s.get("remote_parent")]

    # -- causal critical path (per-round assembled-trace walk) ------------
    critical_path: Dict = {}
    if spans:
        try:
            from fedml_tpu.telemetry.tracing import (
                assemble_records,
                compute_critical_paths,
                summarize_critical_paths,
            )

            trace = assemble_records(data.trace_records)
            cps = compute_critical_paths(trace, programs=programs or None)
            if cps:
                critical_path = summarize_critical_paths(cps)
                critical_path["clocks"] = [
                    c.to_dict() for c in sorted(trace.clocks.values(),
                                                key=lambda c: c.node)]
        except Exception as e:  # report must degrade, never traceback
            notes["critical_path"] = f"trace assembly failed: {e!r}"

    return {
        "schema": "fedml_tpu.telemetry.report/v1",
        "run_dir": run_dir,
        "n_spans": len(spans),
        "n_metrics": len(metrics),
        "notes": notes,
        "rounds": round_rows,
        "phases": phase_rows,
        "stragglers": stragglers,
        "stage_overlap": stage_overlap,
        "compile_ms": compile_ms,
        "execute_ms": max(round_total - compile_ms, 0.0),
        "comm_bytes": comm,
        "compression": compression,
        "client_health": client_health,
        "mem_gauges": mem_gauges,
        "services": services,
        "serving_latency": serving_latency,
        "attribution": attribution,
        "critical_path": critical_path,
        "stitched_spans": stitched,
    }


def format_report(report: Dict) -> str:
    lines: List[str] = []
    add = lines.append
    add(f"telemetry report: {report['run_dir']} "
        f"({report['n_spans']} spans)")
    notes = report.get("notes") or {}
    add("")
    add("per-round timeline:")
    if not report["rounds"] and "spans" in notes:
        add(f"  {notes['spans']}")
    for r in report["rounds"]:
        add(f"  round {r['round']}: wall {r['wall_ms']:.1f} ms")
        for phase, total in r["phases"].items():
            add(f"    {phase:<42s} {total:>10.1f} ms")
    add("")
    add("per-phase percentiles (all rounds):")
    add(f"  {'phase':<44s}{'count':>6s}{'p50 ms':>10s}{'p95 ms':>10s}"
        f"{'p99 ms':>10s}")
    for p in report["phases"]:
        add(f"  {p['phase']:<44s}{p['count']:>6d}{p['p50_ms']:>10.1f}"
            f"{p['p95_ms']:>10.1f}{p['p99_ms']:>10.1f}")
    overlap = report.get("stage_overlap") or {}
    if overlap.get("rounds"):
        add("")
        add("stage overlap (prefetched staging vs in-flight round, "
            "chained-timing caveat applies):")
        for r in overlap["rounds"]:
            add(f"  round {r['round']}: prefetch {r['prefetch_ms']:.1f} ms, "
                f"overlapped {r['overlapped_ms']:.1f} ms "
                f"(ratio {r['ratio']:.2f})")
        add(f"  overall overlap ratio: {overlap['ratio']:.2f}")
    if report["compile_ms"]:
        add("")
        add(f"jax compile-vs-execute: compile {report['compile_ms']:.1f} ms, "
            f"execute {report['execute_ms']:.1f} ms")
    if report["stragglers"]:
        add("")
        add("straggler attribution (slowest client per round):")
        for s in report["stragglers"]:
            add(f"  round {s['round']}: client {s['client']} "
                f"{s['duration_ms']:.1f} ms ({100 * s['share']:.0f}% of "
                "client time)")
    if report["comm_bytes"]:
        add("")
        add("comm bytes breakdown:")
        for name, v in sorted(report["comm_bytes"].items()):
            add(f"  {name:<44s}{v:>14.0f}")
    elif "metrics" in notes:
        add("")
        add(f"comm bytes breakdown: {notes['metrics']}")
    if report.get("client_health"):
        add("")
        add("client health (latest straggler/anomaly scores):")
        for cid, row in sorted(report["client_health"].items()):
            add(f"  client {cid}: straggler "
                f"{row.get('straggler_score', 0.0):.2f}x, anomaly "
                f"{row.get('anomaly_score', 0.0):.2f}")
    if report.get("mem_gauges"):
        add("")
        add("device/host memory (latest sampled gauges):")
        for name, v in sorted(report["mem_gauges"].items()):
            add(f"  {name:<44s}{v:>14.0f}")
    if report.get("services"):
        add("")
        add("service health (serving/scheduler):")
        for name, v in sorted(report["services"].items()):
            add(f"  {name:<44s}{v:>14}")
    if report.get("serving_latency"):
        add("")
        add("serving token latency (TTFT / inter-token / decode rate):")
        for ep, row in sorted(report["serving_latency"].items()):
            add(f"  endpoint {ep}:")
            for kind in ("ttft", "tpot", "queue_wait"):
                if f"{kind}_count" in row:
                    add(f"    {kind + '_ms':<14s} p50 "
                        f"{row.get(kind + '_p50', 0.0):>8.2f}  p95 "
                        f"{row.get(kind + '_p95', 0.0):>8.2f}  p99 "
                        f"{row.get(kind + '_p99', 0.0):>8.2f}  "
                        f"(n={row.get(kind + '_count', 0)})")
            if "tokens_per_s" in row:
                add(f"    {'tokens_per_s':<14s} {row['tokens_per_s']:.2f}")
    comp = report.get("compression") or {}
    if comp.get("raw_bytes") or comp.get("encode") or comp.get("decode"):
        add("")
        add("compression (payload raw bytes vs wire bytes, control-frame "
            "overhead included):")
        if comp.get("raw_bytes"):
            add(f"  raw {comp['raw_bytes']:.0f} B → wire "
                f"{comp['wire_bytes']:.0f} B "
                f"(ratio {comp['ratio']:.2f}x)")
        else:
            add("  in-process run: codec spans only (no transport bytes "
                "recorded)")
        for phase_key in ("encode", "decode"):
            p = comp.get(phase_key)
            if p:
                add(f"  {p['phase']:<24s} count {p['count']:>5d}  "
                    f"p50 {p['p50_ms']:.1f} ms  p95 {p['p95_ms']:.1f} ms  "
                    f"total {p['total_ms']:.1f} ms")
    attr = report.get("attribution") or {}
    if attr.get("programs"):
        add("")
        ridge = attr.get("ridge_flops_per_byte")
        dev = attr.get("device_kind") or "unknown device"
        add(f"performance attribution ({dev}, roofline ridge "
            f"{ridge:.1f} flop/byte):")
        add(f"  {'program':<30s}{'calls':>7s}{'GFLOP':>9s}{'MB acc':>9s}"
            f"{'AI':>8s}{'class':>15s}{'peakHBM':>10s}{'recomp':>7s}")
        for p in attr["programs"][:16]:
            ai = p.get("arithmetic_intensity")
            ai_s = "-" if ai is None else f"{ai:.1f}"
            add(f"  {p['name']:<30s}{p['calls']:>7d}"
                f"{p['flops'] / 1e9:>9.3f}"
                f"{p['bytes_accessed'] / 1e6:>9.2f}"
                f"{ai_s:>8s}"
                f"{p.get('roofline_class') or '-':>15s}"
                f"{p['peak_hbm_bytes'] / 1e6:>9.1f}M"
                f"{p['recompiles']:>7d}")
        phase_attr = [p for p in attr.get("phases") or []
                      if p.get("wall_ms")]
        if phase_attr:
            add("  per-phase achieved rates:")
            for p in phase_attr:
                rate = p.get("achieved_flops_per_s")
                bw = p.get("achieved_bytes_per_s")
                mfu = p.get("mfu")
                line = (f"    {p['phase']:<40s}"
                        f"{(rate or 0) / 1e9:>9.2f} GFLOP/s"
                        f"{(bw or 0) / 1e9:>9.3f} GB/s"
                        f"  {p.get('roofline_class') or '-'}")
                if mfu is not None:
                    line += f"  mfu {mfu:.3f}"
                add(line)
        overall = attr.get("overall") or {}
        if overall.get("achieved_flops_per_s"):
            line = (f"  whole-run: {overall['achieved_flops_per_s'] / 1e9:.2f}"
                    f" GFLOP/s over {overall['round_wall_ms']:.0f} ms of "
                    f"round wall (provenance: {overall.get('provenance')})")
            if overall.get("mfu") is not None:
                line += f", MFU {overall['mfu']:.4f}"
            add(line)
        top = attr.get("top_hbm_program")
        if top:
            add(f"  top peak-HBM consumer: {top['name']} "
                f"({top['peak_hbm_bytes'] / 1e6:.1f} MB live at peak, "
                f"{top.get('roofline_class') or 'class unknown'})")
    elif "attribution" in notes:
        add("")
        add(f"performance attribution: {notes['attribution']}")
    cp = report.get("critical_path") or {}
    if cp.get("rounds"):
        add("")
        add("critical path (per-round longest causal chain, aligned "
            "timeline):")
        for r in cp["rounds"]:
            strag = r.get("straggler") or {}
            extra = ""
            if strag:
                extra = (f"  straggler client {strag['client']} "
                         + ("ON path" if strag.get("on_critical_path")
                            else "has slack")
                         + f", removing saves <= {strag['savings_ms']:.1f} ms")
            add(f"  round {r['round']}: path {r['path_ms']:.1f} ms / wall "
                f"{r['wall_ms']:.1f} ms, top phase {r['top_phase']} "
                f"({100 * (r.get('top_share') or 0):.0f}%)" + extra)
            kinds = r.get("by_kind") or {}
            if kinds:
                add("    " + "  ".join(f"{k} {v:.1f} ms"
                                       for k, v in sorted(kinds.items())))
        clocks = [c for c in cp.get("clocks") or []
                  if c.get("method") not in ("reference", None)]
        if clocks:
            add("  clock alignment:")
            for c in clocks:
                unc = c.get("uncertainty_ms")
                add(f"    node {c['node']}: offset {c['offset_ms']:+.2f} ms "
                    f"+/- {unc if unc is not None else '?'} ms "
                    f"({c['method']}, {c['pairs']} pairs)")
    if report["stitched_spans"]:
        add("")
        add(f"cross-process stitched spans: {len(report['stitched_spans'])}")
        for s in report["stitched_spans"][:10]:
            add(f"  {s['name']} trace={s['trace_id'][:8]} "
                f"parent={s['parent_id']} (publisher-side origin)")
    return "\n".join(lines)
