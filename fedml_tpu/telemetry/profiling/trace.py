"""TraceController — budgeted deep device traces, one owner per process.

``jax.profiler.start_trace`` is a process singleton: two owners fighting
over it lose both traces. This controller is the ONE place a trace may
start from, with three arms:

- **explicit**: ``fedml_tpu telemetry profile <cmd>`` (or ``bench.py
  --trace-rounds``) arms round indices via the ``FEDML_TRACE_ROUNDS`` /
  ``FEDML_TRACE_DIR`` env, read at first use;
- **manual**: the legacy ``MLOpsProfilerEvent.start_trace/stop_trace``
  facade delegates here instead of owning a second profiler path;
- **automatic**: when the :class:`~..live.online_doctor.OnlineDoctor`
  edge-triggers a straggler / memory-slope / serving-stall alert it calls
  :meth:`request_capture`, and the next round boundary on the implicated
  (in-process) node captures ONE bounded trace — at most one auto capture
  per rule per run, at most ``max_captures`` total, cumulative trace
  bytes capped by ``byte_budget``.

Every capture lands a ``profile_capture`` marker in the flight recorder
AND in ``<run_dir>/telemetry.jsonl`` (the post-hoc doctor's proof the
capture happened at the trip round), plus a ``profile/captures`` counter
labeled by trigger.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Set

from fedml_tpu.telemetry import flight_recorder
from fedml_tpu.telemetry.registry import get_registry

__all__ = ["TraceController", "get_trace_controller", "parse_rounds",
           "reset_trace_controller"]

logger = logging.getLogger(__name__)

# rules whose online-doctor alerts request an automatic capture
AUTO_CAPTURE_RULES = ("straggler", "memory_growth", "stale_serving_round",
                      "slo_burn")


def parse_rounds(spec: Any) -> List[int]:
    """The ONE parser for every round-list surface (``--trace-rounds``
    on bench/tree/serve, the ``trace_rounds`` yaml knob, the
    ``FEDML_TRACE_ROUNDS`` env): comma-separated non-negative round
    indices; anything else in the list is rejected loudly rather than
    silently dropped."""
    if spec is None:
        return []
    out: List[int] = []
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if not tok.isdigit():
            raise ValueError(
                f"trace rounds must be comma-separated non-negative "
                f"integers; got {tok!r} in {spec!r}")
        out.append(int(tok))
    return out


class TraceController:
    def __init__(self, max_captures: int = 3,
                 byte_budget: int = 512 * 1024 * 1024,
                 trace_dir: Optional[str] = None):
        self.max_captures = int(
            os.environ.get("FEDML_TRACE_MAX_CAPTURES", max_captures))
        self.byte_budget = int(
            os.environ.get("FEDML_TRACE_BYTE_BUDGET", byte_budget))
        self._trace_dir = trace_dir or os.environ.get("FEDML_TRACE_DIR")
        self._armed_rounds: Set[int] = set(
            parse_rounds(os.environ.get("FEDML_TRACE_ROUNDS", "")))
        self._lock = threading.Lock()
        self._active: Optional[Dict[str, Any]] = None
        self._pending: List[Dict[str, Any]] = []
        self._rules_captured: Set[str] = set()
        self.captures: List[Dict[str, Any]] = []
        self.bytes_captured = 0
        self.unavailable: Optional[str] = None

    # -- arming ------------------------------------------------------------
    def arm_rounds(self, rounds, trace_dir: Optional[str] = None) -> None:
        with self._lock:
            self._armed_rounds.update(int(r) for r in rounds)
            if trace_dir:
                self._trace_dir = trace_dir

    def request_capture(self, rule: str, reason: str = "",
                        node: Optional[str] = None,
                        round_idx: Optional[int] = None) -> bool:
        """Arm ONE bounded capture for the next round boundary. Deduped:
        at most one auto capture per rule per run; refused past the
        count/byte budget. Returns whether the request was accepted."""
        with self._lock:
            if rule in self._rules_captured:
                return False
            if len(self.captures) + len(self._pending) >= self.max_captures:
                return False
            if self.bytes_captured >= self.byte_budget:
                return False
            self._rules_captured.add(rule)
            self._pending.append({"rule": rule, "reason": reason,
                                  "node": node, "alert_round": round_idx})
        return True

    # -- round hooks (sp / mesh / tree / cross-silo loops) -----------------
    def on_round_start(self, round_idx: int,
                       run_dir: Optional[str] = None) -> bool:
        """Start a capture for this round if one is armed (explicit round
        list or a pending auto request). Returns whether a trace is now
        recording."""
        round_idx = int(round_idx)
        with self._lock:
            if self._active is not None or self.unavailable:
                return self._active is not None
            trigger = None
            if round_idx in self._armed_rounds:
                trigger = {"rule": "explicit", "reason": "armed round",
                           "node": None, "alert_round": None}
            elif self._pending:
                trigger = self._pending.pop(0)
            if trigger is None:
                return False
            trace_dir = self._capture_dir(round_idx, trigger["rule"],
                                          run_dir)
            self._active = {**trigger, "round": round_idx,
                            "trace_dir": trace_dir,
                            "started": time.time()}
        return self._start(trace_dir)

    def on_round_end(self, round_idx: int,
                     run_dir: Optional[str] = None) -> Optional[Dict]:
        """Stop the capture this round owns (no-op otherwise) and land
        the ``profile_capture`` marker."""
        with self._lock:
            active = self._active
            if active is None or active["round"] != int(round_idx):
                return None
            self._active = None
        ok = self._stop()
        nbytes = _dir_bytes(active["trace_dir"]) if ok else 0
        marker = {
            "kind": "profile_capture",
            "ts": time.time(),
            "round": active["round"],
            "rule": active["rule"],
            "reason": active.get("reason"),
            "node": active.get("node"),
            "alert_round": active.get("alert_round"),
            "trace_dir": active["trace_dir"],
            "trace_bytes": nbytes,
            "ok": ok,
        }
        with self._lock:
            # budget state mutates under the SAME lock request_capture
            # reads it with, so a concurrent alert can't slip past the
            # count/byte budget mid-update
            self.bytes_captured += nbytes
            self.captures.append(marker)
        get_registry().counter(
            "profile/captures", labels={"trigger": active["rule"]}).inc()
        flight_recorder.record(**marker)
        self._append_marker(marker, run_dir)
        if self.bytes_captured >= self.byte_budget:
            logger.warning(
                "trace byte budget exhausted (%d >= %d): no further "
                "captures this run", self.bytes_captured, self.byte_budget)
        return marker

    def finish(self) -> None:
        """Stop any capture left open (run teardown safety)."""
        with self._lock:
            active, self._active = self._active, None
        if active is not None:
            self._stop()

    # -- manual arm (legacy mlops facade) ----------------------------------
    def start_manual(self, trace_dir: str) -> bool:
        with self._lock:
            if self._active is not None or self.unavailable:
                return False
            self._active = {"rule": "manual", "reason": "mlops facade",
                            "node": None, "alert_round": None,
                            "round": -1, "trace_dir": trace_dir,
                            "started": time.time()}
        return self._start(trace_dir)

    def stop_manual(self) -> Optional[Dict]:
        with self._lock:
            if self._active is None or self._active["rule"] != "manual":
                return None
        return self.on_round_end(-1)

    # -- internals ---------------------------------------------------------
    def _capture_dir(self, round_idx: int, rule: str,
                     run_dir: Optional[str]) -> str:
        base = self._trace_dir
        if base is None:
            if run_dir is None:
                from fedml_tpu.telemetry.spans import get_tracer

                run_dir = get_tracer().sink_dir or ".fedml_logs/traces"
            base = os.path.join(run_dir, "traces")
        return os.path.join(base, f"round{round_idx}_{rule}")

    def _start(self, trace_dir: str) -> bool:
        try:
            import jax

            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            return True
        except Exception as e:  # profiler missing/broken must not kill runs
            logger.warning("deep trace unavailable: %s", e)
            with self._lock:
                self.unavailable = f"{type(e).__name__}: {e}"[:200]
                self._active = None
            return False

    def _stop(self) -> bool:
        try:
            import jax

            jax.profiler.stop_trace()
            return True
        except Exception as e:  # pragma: no cover - stop after failed start
            logger.warning("stop_trace failed: %s", e)
            return False

    def _append_marker(self, marker: Dict, run_dir: Optional[str]) -> None:
        if run_dir is None:
            from fedml_tpu.telemetry.spans import get_tracer

            run_dir = get_tracer().sink_dir
        if run_dir is None:
            return
        try:
            os.makedirs(run_dir, exist_ok=True)
            with open(os.path.join(run_dir, "telemetry.jsonl"), "a") as f:
                f.write(json.dumps(marker, default=str) + "\n")
        except OSError:  # pragma: no cover - sink dir gone
            pass


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _, files in os.walk(path):
        for fn in files:
            try:
                total += os.path.getsize(os.path.join(root, fn))
            except OSError:  # pragma: no cover - raced deletion
                pass
    return total


def configure_from_args(args: Any) -> None:
    """Apply run-config trace knobs (``tracking_args`` in the yaml):
    ``trace_max_captures`` / ``trace_byte_budget`` budget the captures,
    ``trace_rounds`` + ``trace_dir`` arm explicit rounds — the yaml twin
    of the ``FEDML_TRACE_*`` env and the ``--trace-rounds`` CLI flags."""
    tc = get_trace_controller()
    mc = getattr(args, "trace_max_captures", None)
    if mc is not None:
        tc.max_captures = int(mc)
    bb = getattr(args, "trace_byte_budget", None)
    if bb is not None:
        tc.byte_budget = int(bb)
    rounds = getattr(args, "trace_rounds", None)
    if rounds:
        tc.arm_rounds(parse_rounds(rounds),
                      trace_dir=getattr(args, "trace_dir", None))


_controller: Optional[TraceController] = None
_controller_lock = threading.Lock()


def get_trace_controller() -> TraceController:
    global _controller
    with _controller_lock:
        if _controller is None:
            _controller = TraceController()
        return _controller


def reset_trace_controller() -> None:
    """Drop the process-global controller (test isolation); stops any
    trace left recording."""
    global _controller
    with _controller_lock:
        old, _controller = _controller, None
    if old is not None:
        old.finish()
