"""Program catalog — every hot-path XLA program, named and accounted.

The catalog answers the question the doctor and the multichip plan both
need: *which compiled program* owns each reported second and byte. Every
hot-path jitted function (mesh fused round, sp local-train step, the
compression codecs, secagg ``unmask_finalize``, hierarchy chunk programs,
the serving decode/prefill family) registers under a stable name via
:func:`wrap_jit`; the returned :class:`CatalogedProgram` then OWNS
execution:

- first call per input signature: ``jitted.lower(*args).compile()`` —
  exactly ONE backend compile (the jit path and the AOT path do not share
  a cache in jax 0.4.x, so letting both run would double-compile), and
  the executable's ``cost_analysis()`` FLOPs / bytes-accessed plus
  ``memory_analysis()`` argument/output/temp HBM come free off the same
  object;
- subsequent calls: a last-used fastpath straight into the compiled
  executable. ``Compiled.__call__`` validates pytree + avals itself and
  raises ``TypeError`` *before* dispatch (donated buffers still alive),
  so the fastpath needs no per-call signature hashing — a mismatch falls
  back to the keyed-variant slow path, and a brand-new signature becomes
  a new variant (that is the recompile counter treedef churn is read off).

Anything that fails to lower/compile/execute through the AOT path falls
back permanently to the raw jitted callable for that signature — the
catalog records the fallback and the program still gets compile-time
attribution via the ``jax.monitoring`` listener (compiles that fire while
a cataloged call is on this thread's stack are booked to that program;
all others land in ``uncataloged``, so
``sum(per-program compile events) + uncataloged == jax/compile_ms count``
holds exactly).

Snapshots persist as ``<run_dir>/programs.jsonl`` (one line per program,
rewritten whole at each flush) and as ``profile/*`` registry instruments
so the live plane streams them (see :mod:`..live`).
"""
from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from fedml_tpu.telemetry.registry import get_registry

__all__ = [
    "CatalogedProgram",
    "ProgramCatalog",
    "ProgramRecord",
    "get_catalog",
    "pump_profile_gauges",
    "reset_catalog",
    "wrap_jit",
]

# the program whose wrapped call is on this thread's stack — the
# jax.monitoring compile listener attributes backend-compile events here
_PROGRAM_VAR: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "fedml_profile_program", default=None)

_ENV_DISABLE = "FEDML_PROFILE"  # "0" disables the catalog process-wide


def _enabled_from_env() -> bool:
    return os.environ.get(_ENV_DISABLE, "1") not in ("0", "false", "off")


class _Variant:
    """One compiled input signature of a cataloged program."""

    __slots__ = ("compiled", "statics", "fallback", "flops", "bytes_accessed")

    def __init__(self, compiled=None, statics: Tuple = (),
                 fallback: bool = False, flops: float = 0.0,
                 bytes_accessed: float = 0.0):
        self.compiled = compiled
        self.statics = statics
        self.fallback = fallback
        self.flops = flops
        self.bytes_accessed = bytes_accessed


class ProgramRecord:
    """Mutable accounting for one named program (all variants)."""

    def __init__(self, name: str, multi_shape: bool = False):
        self.name = name
        self.multi_shape = bool(multi_shape)
        self.flops = 0.0            # latest-variant cost_analysis flops
        self.bytes_accessed = 0.0   # latest-variant bytes accessed
        self.argument_bytes = 0.0
        self.output_bytes = 0.0
        self.temp_bytes = 0.0
        self.peak_hbm_bytes = 0.0   # max over variants of arg+out+temp
        self.generated_code_bytes = 0.0
        self.compile_ms = 0.0       # attributed backend-compile wall (listener)
        self.compile_wall_ms = 0.0  # measured lower+compile wall (AOT path)
        self.compile_events = 0     # backend_compile events booked here
        self.n_signatures = 0       # distinct compiled input signatures
        self.calls = 0
        self.fallback_calls = 0
        self.analysis_error: Optional[str] = None
        self.treedef: Optional[str] = None
        self.first_call_ts: Optional[float] = None
        self.phase_calls: Dict[str, int] = {}
        # mesh/sharding spec of the latest compiled variant (None for
        # single-device programs): {"axes": {name: size}, "n_shards": N,
        # "in_shardings": [...], "out_shardings": [...]}. When n_shards
        # > 1 every byte figure above (argument/output/temp/peak HBM) is
        # PER SHARD — XLA's memory_analysis plans one device's slice —
        # which is exactly the number the per-device admission gate and
        # the doctor's headroom verdict must compare against the limit.
        self.mesh_spec: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        from fedml_tpu.telemetry.profiling.roofline import (
            arithmetic_intensity,
            classify,
        )

        ai = arithmetic_intensity(self.flops, self.bytes_accessed)
        return {
            "name": self.name,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "compile_ms": round(self.compile_ms, 3),
            "compile_wall_ms": round(self.compile_wall_ms, 3),
            "compile_events": self.compile_events,
            "n_signatures": self.n_signatures,
            "recompiles": max(self.n_signatures - 1, 0),
            "multi_shape": self.multi_shape,
            "calls": self.calls,
            "fallback_calls": self.fallback_calls,
            "analysis_error": self.analysis_error,
            "treedef": self.treedef,
            "phase_calls": dict(self.phase_calls),
            "mesh_spec": self.mesh_spec,
            "arithmetic_intensity": ai,
            "roofline_class": classify(ai) if ai is not None else None,
        }


def _phase_of(span_name: Optional[str], memo: Dict[str, str]) -> str:
    """Normalize the enclosing span's name to a stable phase key
    (``round/3/client/7/train`` → ``round/<n>/client/<id>/train``)."""
    if not span_name:
        return "unattributed"
    hit = memo.get(span_name)
    if hit is not None:
        return hit
    from fedml_tpu.telemetry.report import normalize_name

    phase = normalize_name(span_name)
    if len(memo) < 4096:  # runs are rounds×phases; cap pathological churn
        memo[span_name] = phase
    return phase


def _shard_token(leaf) -> Any:
    """A hashable token for a leaf's multi-device sharding, else None.

    Single-device and host leaves all map to None so the signature of
    every pre-existing (unsharded) call is unchanged — only arrays laid
    out over a >1-device mesh (the per-shard aggregation path, fsdp
    params) key distinct compiled variants. Without this, a program
    called first unsharded then sharded at the same shapes would reuse
    the wrong executable.
    """
    s = getattr(leaf, "sharding", None)
    if s is None or getattr(s, "mesh", None) is None:
        return None
    try:
        if s.mesh.size <= 1:
            return None
        return s  # NamedSharding is hashable
    except Exception:  # pragma: no cover - exotic sharding type
        return None


def _sig_of(args: Sequence[Any], kwargs: Dict[str, Any],
            static_argnums: Tuple[int, ...]) -> Tuple:
    """Hashable input signature: static args by value, array leaves by
    (shape, dtype[, mesh sharding]), other hashables by (type, value)."""
    import jax

    parts: List[Any] = []
    for i, a in enumerate(args):
        if i in static_argnums:
            parts.append(("s", a))
            continue
        leaves, treedef = jax.tree_util.tree_flatten(a)
        sig = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            if shape is not None:
                tok = _shard_token(leaf)
                sig.append((tuple(shape), str(leaf.dtype)) if tok is None
                           else (tuple(shape), str(leaf.dtype), tok))
            else:
                sig.append((type(leaf),))  # python scalar: dynamic weak arg
        parts.append((treedef, tuple(sig)))
    if kwargs:
        for k in sorted(kwargs):
            leaves, treedef = jax.tree_util.tree_flatten(kwargs[k])
            parts.append((k, treedef, tuple(
                (tuple(x.shape), str(x.dtype)) if hasattr(x, "shape")
                else (type(x),) for x in leaves)))
    return tuple(parts)


def _mesh_spec_of(compiled) -> Optional[Dict[str, Any]]:
    """The mesh/sharding spec of a compiled executable, or None.

    Introspected off the executable itself (``input_shardings`` /
    ``output_shardings``) so EVERY cataloged program that runs sharded —
    the fsdp LLM round, the shard_map mesh simulator, the per-shard
    fused aggregation — records its partition layout without any caller
    plumbing. Single-device programs (no mesh, or a 1-device mesh)
    record nothing: ``mesh_spec is None`` means the byte figures are
    whole-program, not per-shard.
    """
    import jax

    from fedml_tpu.utils.jax_compat import pspec_str, sharding_mesh_axes

    try:
        in_shardings = jax.tree_util.tree_leaves(compiled.input_shardings)
        out_shardings = jax.tree_util.tree_leaves(compiled.output_shardings)
    except Exception:
        return None
    axes: Dict[str, int] = {}
    for s in in_shardings + out_shardings:
        for name, size in sharding_mesh_axes(s).items():
            axes[name] = max(axes.get(name, 1), size)
    n_shards = 1
    for size in axes.values():
        n_shards *= size
    if n_shards <= 1:
        return None

    def _specs(shardings, cap: int = 16) -> List[str]:
        seen: List[str] = []
        for s in shardings:
            label = pspec_str(s)
            if label not in seen:
                seen.append(label)
            if len(seen) >= cap:
                break
        return seen

    return {
        "axes": axes,
        "n_shards": n_shards,
        "in_shardings": _specs(in_shardings),
        "out_shardings": _specs(out_shardings),
    }


class CatalogedProgram:
    """Callable wrapper that owns AOT compile + execution of one program."""

    def __init__(self, catalog: "ProgramCatalog", name: str, jitted,
                 static_argnums: Tuple[int, ...] = (),
                 multi_shape: bool = False):
        self._catalog = catalog
        self._name = name
        self._jitted = jitted
        self._static = tuple(int(i) for i in static_argnums)
        self._variants: Dict[Tuple, _Variant] = {}
        self._last: Optional[_Variant] = None
        self._lock = threading.Lock()
        self.record = catalog._record(name, multi_shape=multi_shape)

    # expose the underlying jit for callers that need AOT stages directly
    @property
    def jitted(self):
        return self._jitted

    def lower(self, *args, **kwargs):
        """AOT-stage passthrough so wrapped programs keep the jit API."""
        return self._jitted.lower(*args, **kwargs)

    @property
    def name(self) -> str:
        return self._name

    def _dynamic(self, args: Sequence[Any]) -> List[Any]:
        if not self._static:
            return list(args)
        return [a for i, a in enumerate(args) if i not in self._static]

    def _statics_match(self, variant: _Variant, args: Sequence[Any]) -> bool:
        if not self._static:
            return True
        for (i, v) in variant.statics:
            if i >= len(args):
                return False
            a = args[i]
            if a is not v and a != v:
                return False
        return True

    def _note_call(self, variant: Optional[_Variant]) -> None:
        rec = self.record
        from fedml_tpu.telemetry import spans as _spans

        span = _spans._current.get()
        phase = _phase_of(span.name if span is not None else None,
                          self._catalog._phase_memo)
        # one short lock covers calls/phase/rate totals: cataloged
        # programs run from concurrent threads (serving engine, prefetch
        # worker) and unlocked read-modify-writes would drop counts the
        # MFU gauges are computed from (~100 ns, inside the <1% seam)
        cat = self._catalog
        with cat._rate_lock:
            rec.calls += 1
            if rec.first_call_ts is None:
                rec.first_call_ts = time.time()
            rec.phase_calls[phase] = rec.phase_calls.get(phase, 0) + 1
            if variant is not None and not variant.fallback:
                cat._flops_total += variant.flops
                cat._bytes_total += variant.bytes_accessed
                if variant.flops and not rec.flops:
                    # a REUSED variant calling into a fresh (re-homed
                    # after reset_catalog) record re-lands its analysis:
                    # flops/bytes are properties of the compiled program,
                    # not of the accounting epoch — without this, any
                    # earlier run that already compiled this signature
                    # would leave the new epoch's record claiming
                    # flops=0 for a program that demonstrably ran
                    rec.flops = variant.flops
                    rec.bytes_accessed = variant.bytes_accessed

    def __call__(self, *args, **kwargs):
        cat = self._catalog
        if not cat.enabled:
            return self._jitted(*args, **kwargs)
        token = _PROGRAM_VAR.set(self._name)
        try:
            last = self._last
            if last is not None and not kwargs and not last.fallback \
                    and self._statics_match(last, args):
                try:
                    out = last.compiled(*self._dynamic(args))
                except (TypeError, ValueError):
                    # pytree/aval mismatch (TypeError) and input-sharding
                    # mismatch (ValueError) are both raised BEFORE
                    # dispatch (no donation happened) — take the keyed
                    # slow path, which keys per-mesh-sharding variants
                    out = self._slow_call(args, kwargs)
                else:
                    self._note_call(last)
                return out
            return self._slow_call(args, kwargs)
        finally:
            _PROGRAM_VAR.reset(token)

    # -- slow path: keyed variant lookup / first-compile ------------------
    def _slow_call(self, args: Sequence[Any], kwargs: Dict[str, Any]):
        try:
            key = _sig_of(args, kwargs, self._static)
        except TypeError:
            # unhashable static/leaf — permanent fallback territory
            self.record.fallback_calls += 1
            self._note_call(None)
            return self._jitted(*args, **kwargs)
        with self._lock:
            variant = self._variants.get(key)
        if variant is None:
            variant = self._compile_variant(key, args, kwargs)
        self._last = variant
        if variant.fallback:
            self.record.fallback_calls += 1
            self._note_call(None)
            return self._jitted(*args, **kwargs)
        out = variant.compiled(*self._dynamic(args), **kwargs)
        self._note_call(variant)
        return out

    def _compile_variant(self, key: Tuple, args: Sequence[Any],
                         kwargs: Dict[str, Any]) -> _Variant:
        rec = self.record
        statics = tuple((i, args[i]) for i in self._static if i < len(args))
        t0 = time.perf_counter()
        try:
            compiled = self._jitted.lower(*args, **kwargs).compile()
        except Exception as e:  # AOT unsupported here — fall back forever
            variant = _Variant(statics=statics, fallback=True)
            with self._lock:
                self._variants[key] = variant
                rec.analysis_error = f"{type(e).__name__}: {e}"[:200]
            return variant
        wall_ms = (time.perf_counter() - t0) * 1e3
        variant = _Variant(compiled=compiled, statics=statics)
        self._analyze(compiled, variant)
        try:
            import jax

            rec.treedef = str(jax.tree_util.tree_structure(
                (tuple(args), kwargs)))[:400]
        except Exception:  # pragma: no cover - structure of a lowerable tree
            pass
        with self._lock:
            self._variants[key] = variant
            rec.compile_wall_ms += wall_ms
            rec.n_signatures += 1
            if rec.n_signatures > 1:
                get_registry().counter(
                    "profile/recompiles",
                    labels={"program": self._name}).inc()
        return variant

    def _analyze(self, compiled, variant: _Variant) -> None:
        rec = self.record
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):  # older jax returns [dict]
                cost = cost[0] if cost else {}
            variant.flops = float(cost.get("flops", 0.0) or 0.0)
            variant.bytes_accessed = float(
                cost.get("bytes accessed", 0.0) or 0.0)
        except Exception as e:
            rec.analysis_error = f"cost_analysis: {type(e).__name__}"[:200]
        try:
            mem = compiled.memory_analysis()
            arg = float(getattr(mem, "argument_size_in_bytes", 0) or 0)
            out = float(getattr(mem, "output_size_in_bytes", 0) or 0)
            tmp = float(getattr(mem, "temp_size_in_bytes", 0) or 0)
            alias = float(getattr(mem, "alias_size_in_bytes", 0) or 0)
            gen = float(getattr(mem, "generated_code_size_in_bytes", 0) or 0)
            rec.argument_bytes = arg
            rec.output_bytes = out
            rec.temp_bytes = tmp
            rec.generated_code_bytes = gen
            # live-at-peak upper bound: args + outputs + temporaries minus
            # donated aliasing — the number HBM planning reads
            rec.peak_hbm_bytes = max(rec.peak_hbm_bytes,
                                     arg + out + tmp - alias)
        except Exception as e:
            rec.analysis_error = f"memory_analysis: {type(e).__name__}"[:200]
        spec = _mesh_spec_of(compiled)
        if spec is not None or rec.mesh_spec is None:
            rec.mesh_spec = spec
        if variant.flops:
            rec.flops = variant.flops
            rec.bytes_accessed = variant.bytes_accessed


class ProgramCatalog:
    """Process-wide registry of cataloged programs."""

    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = _enabled_from_env() if enabled is None else enabled
        self._records: Dict[str, ProgramRecord] = {}
        self._programs: Dict[str, CatalogedProgram] = {}
        self._lock = threading.Lock()
        self._rate_lock = threading.Lock()  # per-call counters/totals
        self._phase_memo: Dict[str, str] = {}
        self._flops_total = 0.0
        self._bytes_total = 0.0
        self.uncataloged_compiles = 0
        self.uncataloged_compile_ms = 0.0
        self._pump_t0: Optional[float] = None
        self._pump_flops = 0.0
        _install_compile_listener()

    # -- registration -----------------------------------------------------
    def _record(self, name: str, multi_shape: bool = False) -> ProgramRecord:
        with self._lock:
            rec = self._records.get(name)
            if rec is None:
                rec = self._records[name] = ProgramRecord(
                    name, multi_shape=multi_shape)
            return rec

    def wrap(self, name: str, jitted,
             static_argnums: Tuple[int, ...] = (),
             multi_shape: bool = False) -> CatalogedProgram:
        prog = CatalogedProgram(self, name, jitted,
                                static_argnums=static_argnums,
                                multi_shape=multi_shape)
        with self._lock:
            self._programs[name] = prog
        return prog

    # -- compile attribution (jax.monitoring) ------------------------------
    def on_compile_event(self, ms: float) -> None:
        name = _PROGRAM_VAR.get()
        if name is None:
            self.uncataloged_compiles += 1
            self.uncataloged_compile_ms += ms
            return
        rec = self._record(name)
        rec.compile_events += 1
        rec.compile_ms += ms

    # -- reads -------------------------------------------------------------
    def records(self) -> List[ProgramRecord]:
        with self._lock:
            return sorted(self._records.values(), key=lambda r: r.name)

    def snapshot(self) -> List[Dict[str, Any]]:
        return [r.to_dict() for r in self.records()]

    def programs_summary(self) -> Dict[str, Dict[str, float]]:
        """The compact name → {flops, bytes, peak-HBM} map BENCH json and
        bench_compare consume."""
        out: Dict[str, Dict[str, float]] = {}
        for rec in self.records():
            if rec.calls == 0 and rec.n_signatures == 0:
                continue
            out[rec.name] = {
                "flops": rec.flops,
                "bytes_accessed": rec.bytes_accessed,
                "peak_hbm_bytes": rec.peak_hbm_bytes,
                "compile_ms": round(rec.compile_ms, 3),
                "calls": rec.calls,
                "recompiles": max(rec.n_signatures - 1, 0),
                # per-shape-variant programs are exempt from recompile
                # regression flags downstream (bench_compare, doctor)
                "multi_shape": rec.multi_shape,
                # per-shard layout (None = single-device program); when
                # present, peak_hbm_bytes above is one shard's plan
                "mesh_spec": rec.mesh_spec,
            }
        return out

    # -- sinks -------------------------------------------------------------
    def flush_jsonl(self, run_dir: str,
                    filename: str = "programs.jsonl") -> Optional[str]:
        """Rewrite the per-run program catalog snapshot (one line per
        program — a snapshot, not an append stream). Programs that never
        ran in this catalog's lifetime (registered wrappers from other
        engines in the process) are not part of this run."""
        rows = [r for r in self.snapshot()
                if r["calls"] or r["compile_events"] or r["n_signatures"]]
        if not rows:
            return None
        import jax

        try:
            dev = jax.devices()[0]
            device_kind, platform = dev.device_kind, dev.platform
        except Exception:  # pragma: no cover - backend init failure
            device_kind = platform = None
        os.makedirs(run_dir, exist_ok=True)
        path = os.path.join(run_dir, filename)
        tmp = path + ".tmp"
        ts = time.time()
        with open(tmp, "w") as f:
            for row in rows:
                f.write(json.dumps({
                    "ts": ts, "device_kind": device_kind,
                    "platform": platform, **row}, default=str) + "\n")
        os.replace(tmp, path)
        # deliberately NO pump_gauges here: flush runs AFTER the live
        # plane's final frame, and mutating profile/* gauges then would
        # break the collector==post-hoc exact-totals invariant — the
        # device-stats phase tick is the only gauge refresher
        return path

    def pump_gauges(self) -> None:
        """Land the catalog state in ``profile/*`` registry instruments so
        the live plane streams it (counter/gauge only — lint-enforced)."""
        from fedml_tpu.telemetry.profiling.roofline import (
            arithmetic_intensity,
            device_peaks,
            ridge_point,
        )

        reg = get_registry()
        records = self.records()
        reg.gauge("profile/programs").set(float(len(records)))
        reg.gauge("profile/uncataloged_compiles").set(
            float(self.uncataloged_compiles))
        for rec in records:
            labels = {"program": rec.name}
            reg.gauge("profile/flops", labels=labels).set(rec.flops)
            reg.gauge("profile/bytes_accessed", labels=labels).set(
                rec.bytes_accessed)
            reg.gauge("profile/peak_hbm_bytes", labels=labels).set(
                rec.peak_hbm_bytes)
            reg.gauge("profile/compile_ms", labels=labels).set(
                rec.compile_ms)
            reg.gauge("profile/calls", labels=labels).set(float(rec.calls))
            if rec.mesh_spec:
                # shard/* namespace: per-shard layout levels the live
                # plane streams next to profile/* (lint: gauge/counter
                # only, one segment, program rides the label)
                reg.gauge("shard/n_shards", labels=labels).set(
                    float(rec.mesh_spec["n_shards"]))
                reg.gauge("shard/per_shard_hbm_bytes", labels=labels).set(
                    rec.peak_hbm_bytes)
        # rolling achieved rate since the last pump → live MFU + roofline
        now = time.perf_counter()
        peaks = device_peaks()
        ridge = ridge_point(peaks)
        ai = arithmetic_intensity(self._flops_total, self._bytes_total)
        if ai is not None:
            reg.gauge("profile/ai").set(ai)
            reg.gauge("profile/ridge").set(ridge)
            reg.gauge("profile/hbm_bound").set(1.0 if ai < ridge else 0.0)
        if self._pump_t0 is not None:
            dt = now - self._pump_t0
            dflops = self._flops_total - self._pump_flops
            if dt > 1e-3 and dflops > 0:
                rate = dflops / dt
                reg.gauge("profile/flops_per_s").set(rate)
                if peaks[0]:
                    reg.gauge("profile/mfu").set(rate / peaks[0])
        self._pump_t0 = now
        self._pump_flops = self._flops_total


_catalog: Optional[ProgramCatalog] = None
_catalog_lock = threading.Lock()
_listener_installed = False
_listener_lock = threading.Lock()


def _install_compile_listener() -> None:
    """Book backend-compile events to the cataloged program on this
    thread's stack (installed once per process; reads the CURRENT global
    catalog at event time so registry/test resets stay honest)."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        try:
            import jax.monitoring
        except ImportError:  # pragma: no cover - jax is a hard dep in-tree
            return
        # the jax/compile_ms histogram listener must observe the SAME
        # event stream, or the exact accounting invariant
        # (hist.count == attributed + uncataloged) breaks when a tracer
        # is constructed later than the first cataloged program
        from fedml_tpu.telemetry.spans import install_jax_compile_listener

        install_jax_compile_listener()

        def _on_duration(event: str, duration_secs: float, **kw) -> None:
            if "backend_compile" not in event:
                return
            cat = _catalog
            if cat is not None:
                cat.on_compile_event(duration_secs * 1e3)

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _listener_installed = True


def get_catalog() -> ProgramCatalog:
    global _catalog
    with _catalog_lock:
        if _catalog is None:
            _catalog = ProgramCatalog()
        return _catalog


def reset_catalog() -> None:
    """Drop the process-global catalog (test isolation). Already-wrapped
    programs keep their compiled variants (recompiling every test would
    be the real regression) but re-home their accounting into the fresh
    catalog on next call."""
    global _catalog
    with _catalog_lock:
        old, _catalog = _catalog, ProgramCatalog()
        if old is not None:
            # re-home live wrappers: fresh records, same compiled variants
            for name, prog in old._programs.items():
                prog._catalog = _catalog
                prog.record = _catalog._record(
                    name, multi_shape=prog.record.multi_shape)
                _catalog._programs[name] = prog


def wrap_jit(name: str, jitted, static_argnums: Tuple[int, ...] = (),
             multi_shape: bool = False) -> CatalogedProgram:
    """Register ``jitted`` in the process catalog under ``name``.

    ``static_argnums`` must mirror the jit's own static argnums (the AOT
    executable is called with them stripped). ``multi_shape=True`` marks
    programs that legitimately compile one variant per input shape (the
    serving ``decode_group`` family) so the doctor's treedef-churn verdict
    skips them.
    """
    return get_catalog().wrap(name, jitted, static_argnums=static_argnums,
                              multi_shape=multi_shape)


def pump_profile_gauges() -> None:
    """Refresh ``profile/*`` gauges from the current catalog (cheap no-op
    when nothing registered) — called from the device-stats sampler so
    every phase sample also refreshes live MFU/roofline."""
    cat = _catalog
    if cat is not None and cat._records:
        cat.pump_gauges()
