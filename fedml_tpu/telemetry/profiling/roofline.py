"""Roofline model + per-phase performance attribution.

Williams et al., "Roofline: An Insightful Visual Performance Model"
(CACM '09): a program with arithmetic intensity AI = flops / bytes below
the machine balance (peak FLOP/s ÷ peak memory bandwidth) is
bandwidth-bound no matter how well it schedules; above it, compute-bound.
This module owns the device peak tables (moved here from ``bench.py`` so
every consumer — bench, report, doctor, live watch — reads ONE source),
the classification, and the report's attribution join: measured phase
walls (spans) × catalog flops/bytes (``programs.jsonl``) → achieved
FLOP/s, achieved bytes/s, and a per-phase MFU decomposition that sums to
the same whole-run MFU bench.py stamps (same ``xla`` provenance — both
read ``cost_analysis()`` off the compiled executables).
"""
from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "PEAK_BF16",
    "PEAK_FLOPS",
    "PEAK_HBM_BW",
    "DEFAULT_RIDGE",
    "arithmetic_intensity",
    "build_attribution",
    "classify",
    "device_peaks",
    "ridge_point",
]

# chip peak bf16 FLOP/s by device kind (public spec sheets) — the table
# bench.py's MFU has always used, now owned here
PEAK_FLOPS: Dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
    "TPU v6e": 918e12,
}
PEAK_BF16 = PEAK_FLOPS  # bench.py's historical name

# HBM bandwidth, bytes/s (public spec sheets)
PEAK_HBM_BW: Dict[str, float] = {
    "TPU v4": 1.23e12,
    "TPU v5 lite": 8.19e11,  # v5e
    "TPU v5e": 8.19e11,
    "TPU v5p": 2.765e12,
    "TPU v6 lite": 1.64e12,  # v6e
    "TPU v6e": 1.64e12,
}

# machine balance used when the device is unknown (CPU dev boxes, new
# chips): programs denser than this many flops/byte are called
# compute-bound. Documented nominal, overridable via FEDML_PEAK_*.
DEFAULT_RIDGE = 10.0


def device_peaks(device_kind: Optional[str] = None
                 ) -> Tuple[Optional[float], Optional[float]]:
    """(peak FLOP/s, peak bytes/s) for ``device_kind`` (default: the
    current backend's first device). ``FEDML_PEAK_FLOPS`` /
    ``FEDML_PEAK_BW`` env overrides win — that is how CPU test rigs and
    unlisted chips get deterministic MFU/roofline numbers."""
    flops = os.environ.get("FEDML_PEAK_FLOPS")
    bw = os.environ.get("FEDML_PEAK_BW")
    if flops or bw:
        return (float(flops) if flops else None,
                float(bw) if bw else None)
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:  # pragma: no cover - backend init failure
            return None, None
    return PEAK_FLOPS.get(device_kind), PEAK_HBM_BW.get(device_kind)


def ridge_point(peaks: Optional[Tuple[Optional[float],
                                      Optional[float]]] = None) -> float:
    """Machine balance (flops/byte) — the roofline's compute/bandwidth
    boundary. Falls back to :data:`DEFAULT_RIDGE` when either peak is
    unknown."""
    if peaks is None:
        peaks = device_peaks()
    pf, pb = peaks
    if pf and pb:
        return pf / pb
    return DEFAULT_RIDGE


def arithmetic_intensity(flops: float, bytes_accessed: float
                         ) -> Optional[float]:
    if not flops or not bytes_accessed:
        return None
    return flops / bytes_accessed


def classify(ai: Optional[float],
             ridge: Optional[float] = None) -> Optional[str]:
    """``"compute-bound"`` or ``"hbm-bound"`` (None when AI unknown)."""
    if ai is None:
        return None
    if ridge is None:
        ridge = ridge_point()
    return "compute-bound" if ai >= ridge else "hbm-bound"


_ROUND_PHASE = re.compile(r"^round/<n>/")


def build_attribution(phases: List[Dict[str, Any]],
                      rounds: List[Dict[str, Any]],
                      programs: List[Dict[str, Any]],
                      device_kind: Optional[str] = None) -> Dict[str, Any]:
    """Join measured phase walls against the program catalog.

    ``phases``/``rounds`` are the report's span-derived rows;
    ``programs`` the loaded ``programs.jsonl`` records. Returns the
    report's ``attribution`` section: per-program roofline rows, per-phase
    achieved FLOP/s + bytes/s + MFU, the whole-run decomposition, and the
    top peak-HBM consumer (the direct input the multichip plan asks for).
    """
    peaks = device_peaks(device_kind)
    ridge = ridge_point(peaks)
    pf, pb = peaks

    program_rows: List[Dict[str, Any]] = []
    by_phase: Dict[str, Dict[str, float]] = {}
    for rec in programs:
        flops = float(rec.get("flops") or 0.0)
        nbytes = float(rec.get("bytes_accessed") or 0.0)
        ai = arithmetic_intensity(flops, nbytes)
        program_rows.append({
            "name": rec.get("name"),
            "calls": int(rec.get("calls") or 0),
            "flops": flops,
            "bytes_accessed": nbytes,
            "peak_hbm_bytes": float(rec.get("peak_hbm_bytes") or 0.0),
            "compile_ms": float(rec.get("compile_ms") or 0.0),
            "recompiles": int(rec.get("recompiles") or 0),
            "multi_shape": bool(rec.get("multi_shape")),
            # per-shard layout (catalog mesh_spec): when present, every
            # byte figure in this row is ONE shard's plan, not the whole
            # program's footprint — the doctor's HBM verdict reads it
            "mesh_spec": rec.get("mesh_spec"),
            "arithmetic_intensity": ai,
            "roofline_class": classify(ai, ridge),
        })
        for phase, calls in (rec.get("phase_calls") or {}).items():
            agg = by_phase.setdefault(phase, {"flops": 0.0, "bytes": 0.0,
                                              "calls": 0.0})
            agg["flops"] += flops * int(calls)
            agg["bytes"] += nbytes * int(calls)
            agg["calls"] += int(calls)
    program_rows.sort(key=lambda r: -r["flops"] * max(r["calls"], 1))

    phase_wall_ms = {p["phase"]: float(p.get("total_ms") or 0.0)
                     for p in phases}
    phase_rows: List[Dict[str, Any]] = []
    total_flops = total_bytes = attributed_wall_ms = 0.0
    for phase in sorted(by_phase):
        agg = by_phase[phase]
        wall_ms = phase_wall_ms.get(phase, 0.0)
        row: Dict[str, Any] = {
            "phase": phase,
            "calls": int(agg["calls"]),
            "flops": agg["flops"],
            "bytes_accessed": agg["bytes"],
            "wall_ms": wall_ms,
        }
        ai = arithmetic_intensity(agg["flops"], agg["bytes"])
        row["arithmetic_intensity"] = ai
        row["roofline_class"] = classify(ai, ridge)
        if wall_ms > 0:
            wall_s = wall_ms / 1e3
            row["achieved_flops_per_s"] = agg["flops"] / wall_s
            row["achieved_bytes_per_s"] = agg["bytes"] / wall_s
            if pf:
                row["mfu"] = agg["flops"] / wall_s / pf
            if pb:
                row["bw_utilization"] = agg["bytes"] / wall_s / pb
            if _ROUND_PHASE.match(phase):
                # round phases are wall-disjoint within a round, so their
                # flops AND walls sum into the whole-run decomposition
                total_flops += agg["flops"]
                total_bytes += agg["bytes"]
                attributed_wall_ms += wall_ms
        phase_rows.append(row)

    round_wall_ms = sum(float(r.get("wall_ms") or 0.0) for r in rounds)
    overall: Dict[str, Any] = {
        "flops": total_flops,
        "bytes_accessed": total_bytes,
        "attributed_wall_ms": attributed_wall_ms,
        "round_wall_ms": round_wall_ms,
        # same provenance as bench.py's mfu_source="xla": both sides of
        # the comparison read cost_analysis() off compiled executables
        "provenance": "xla",
    }
    wall = round_wall_ms or attributed_wall_ms
    if wall > 0 and total_flops:
        overall["achieved_flops_per_s"] = total_flops / (wall / 1e3)
        if pf:
            overall["mfu"] = total_flops / (wall / 1e3) / pf
    top_hbm = max(program_rows, key=lambda r: r["peak_hbm_bytes"],
                  default=None)
    return {
        "device_kind": device_kind,
        "peak_flops_per_s": pf,
        "peak_bytes_per_s": pb,
        "ridge_flops_per_byte": ridge,
        "programs": program_rows,
        "phases": phase_rows,
        "overall": overall,
        "top_hbm_program": (top_hbm if top_hbm
                            and top_hbm["peak_hbm_bytes"] > 0 else None),
    }
