"""Performance attribution layer — program catalog, roofline, deep traces.

Three parts (see ``docs/performance.md`` §attribution):

- :mod:`.catalog` — a process-wide registry where every hot-path jitted
  program registers under a stable name at first compile, recording XLA
  ``cost_analysis()`` flops/bytes, ``memory_analysis()`` HBM footprint,
  compile wall time, input treedef, and recompile count; persisted as
  ``programs.jsonl`` per run and streamed as ``profile/*`` instruments;
- :mod:`.roofline` — device peak tables + arithmetic-intensity
  classification (compute- vs HBM-bound) and the report's per-phase
  attribution join (achieved FLOP/s, bytes/s, per-round MFU
  decomposition);
- :mod:`.trace` — the budgeted :class:`TraceController` wrapping
  ``jax.profiler`` with explicit, manual, and alert-triggered capture
  arms (one trace owner per process).
"""
from fedml_tpu.telemetry.profiling.catalog import (
    CatalogedProgram,
    ProgramCatalog,
    ProgramRecord,
    get_catalog,
    pump_profile_gauges,
    reset_catalog,
    wrap_jit,
)
from fedml_tpu.telemetry.profiling.roofline import (
    DEFAULT_RIDGE,
    PEAK_BF16,
    PEAK_FLOPS,
    PEAK_HBM_BW,
    arithmetic_intensity,
    build_attribution,
    classify,
    device_peaks,
    ridge_point,
)
from fedml_tpu.telemetry.profiling.trace import (
    AUTO_CAPTURE_RULES,
    TraceController,
    get_trace_controller,
    parse_rounds,
    reset_trace_controller,
)

__all__ = [
    "AUTO_CAPTURE_RULES",
    "CatalogedProgram",
    "DEFAULT_RIDGE",
    "PEAK_BF16",
    "PEAK_FLOPS",
    "PEAK_HBM_BW",
    "ProgramCatalog",
    "ProgramRecord",
    "TraceController",
    "arithmetic_intensity",
    "build_attribution",
    "classify",
    "device_peaks",
    "get_catalog",
    "get_trace_controller",
    "parse_rounds",
    "pump_profile_gauges",
    "reset_catalog",
    "reset_trace_controller",
    "ridge_point",
    "wrap_jit",
]
