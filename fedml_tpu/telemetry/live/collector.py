"""Live collector — merges metric frames into one labeled registry.

The collector is the receiving half of the live plane: every node's
frames (piggybacked on federation traffic, POSTed over HTTP, or pumped
in-process) merge into ONE aggregate :class:`MetricsRegistry` whose
instruments carry ``node`` and ``job`` labels on top of the original
metric labels. That registry is what the ``/metrics`` scrape endpoint
exposes and what the online doctor evaluates incrementally.

Merge contract (the chaos tests pin this down):

- frames apply **in seq order per node**; a frame whose seq is ≤ the
  last applied one is a duplicate/stale replay and is discarded whole
  (``live/duplicate_frames``) — entry values are cumulative, so even a
  partially-overlapping replay would apply zero deltas, but discarding
  at the seq gate keeps the account exact;
- a seq jump of k counts k-1 into ``live/seq_gaps`` — the *data* self-
  heals (cumulative entries + periodic full frames), the *account* of
  what the wire lost does not;
- counters merge by cumulative difference; a negative difference means
  the node restarted its process (registry reset) and the full new value
  re-applies (``live/counter_resets``);
- histograms merge by per-bucket count difference (bounds come with the
  frame), min/max as min/max;
- gauges are last-write-wins per node, like everywhere else.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from fedml_tpu.telemetry.registry import MetricsRegistry, get_registry

__all__ = ["LiveCollector"]


class LiveCollector:
    """Thread-safe frame merger with per-node seq accounting."""

    def __init__(self, job: Optional[str] = None):
        self.job = job
        self.registry = MetricsRegistry()
        self._lock = threading.Lock()
        self._last_seq: Dict[str, int] = {}
        self._last_ts: Dict[str, float] = {}
        self._gaps: Dict[str, int] = {}
        self._applied: Dict[Tuple, Dict] = {}  # (node, key) -> last entry
        self._hooks: List[Callable[[Dict, "LiveCollector"], None]] = []
        self.started = time.time()
        reg = get_registry()
        self._m_ingested = reg.counter("live/frames_ingested")
        self._m_dup = reg.counter("live/duplicate_frames")
        self._m_gaps = reg.counter("live/seq_gaps")
        self._m_resets = reg.counter("live/counter_resets")
        self._m_bad = reg.counter("live/bad_frames")
        self._g_nodes = reg.gauge("live/nodes")

    def add_hook(self, fn: Callable[[Dict, "LiveCollector"], None]) -> None:
        """``fn(frame, collector)`` after every applied frame (the online
        doctor registers here). Hook failures never poison the merge."""
        self._hooks.append(fn)

    # -- merge -------------------------------------------------------------
    def ingest(self, frame: Any) -> bool:
        """Apply one frame; returns False for duplicates/garbage."""
        if not isinstance(frame, dict) or "node" not in frame \
                or "seq" not in frame or "metrics" not in frame:
            self._m_bad.inc()
            return False
        if self.job is not None and frame.get("job") not in (None, self.job):
            self._m_bad.inc()
            return False
        node = str(frame["node"])
        try:
            seq = int(frame["seq"])
        except (TypeError, ValueError):
            self._m_bad.inc()
            return False
        with self._lock:
            last = self._last_seq.get(node, 0)
            if seq <= last:
                self._m_dup.inc()
                return False
            if seq > last + 1:
                gap = seq - last - 1
                self._gaps[node] = self._gaps.get(node, 0) + gap
                self._m_gaps.inc(gap)
            self._last_seq[node] = seq
            self._last_ts[node] = float(frame.get("ts") or time.time())
            for entry in frame["metrics"]:
                try:
                    self._apply_locked(node, frame.get("job"), entry)
                except (KeyError, TypeError, ValueError):
                    self._m_bad.inc()
            self._g_nodes.set(len(self._last_seq))
        self._m_ingested.inc()
        for fn in self._hooks:
            try:
                fn(frame, self)
            except Exception:  # pragma: no cover - hook must not poison merge
                import logging

                logging.getLogger(__name__).exception(
                    "live collector hook failed")
        return True

    def _labels_for(self, node: str, job, entry: Dict) -> Dict[str, str]:
        labels = dict(entry.get("labels") or {})
        labels["node"] = node
        labels["job"] = str(job if job is not None else (self.job or "default"))
        return labels

    def _apply_locked(self, node: str, job, entry: Dict) -> None:
        kind = entry["kind"]
        name = entry["name"]
        key = (node, name, tuple(sorted((entry.get("labels") or {}).items())))
        prev = self._applied.get(key)
        labels = self._labels_for(node, job, entry)
        if kind == "counter":
            value = float(entry["value"])
            delta = value - (float(prev["value"]) if prev else 0.0)
            if delta < 0:
                # node restart: its registry reset to zero and re-grew
                self._m_resets.inc()
                delta = value
            if delta:
                self.registry.counter(name, labels=labels).inc(delta)
            else:
                self.registry.counter(name, labels=labels)
        elif kind == "gauge":
            self.registry.gauge(name, labels=labels).set(float(entry["value"]))
        elif kind == "histogram":
            buckets = entry["buckets"]
            # bucket keys are the SOURCE's str(bound) spellings ("1", not
            # "1.0") — keep the original key per parsed bound so lookups
            # never miss on float formatting
            key_of = {float(b): b for b in buckets if b != "+inf"}
            bounds = tuple(sorted(key_of))
            h = self.registry.histogram(name, labels=labels, buckets=bounds)
            order = [key_of[b] for b in h.bounds] + ["+inf"]
            prev_buckets = (prev or {}).get("buckets") or {}
            deltas = [int(buckets.get(b, 0)) - int(prev_buckets.get(b, 0))
                      for b in order]
            count_d = int(entry["count"]) - int((prev or {}).get("count", 0))
            sum_d = float(entry["sum"]) - float((prev or {}).get("sum", 0.0))
            if any(d < 0 for d in deltas) or count_d < 0:
                # node restart: re-apply the whole new histogram
                self._m_resets.inc()
                deltas = [int(buckets.get(b, 0)) for b in order]
                count_d = int(entry["count"])
                sum_d = float(entry["sum"])
            if count_d:
                h.merge_delta(deltas, count_d, sum_d,
                              observed_min=entry.get("min"),
                              observed_max=entry.get("max"))
        else:
            raise ValueError(f"unknown metric kind {kind!r}")
        self._applied[key] = entry

    # -- reads -------------------------------------------------------------
    def nodes(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                n: {"seq": s, "last_ts": self._last_ts.get(n),
                    "seq_gaps": self._gaps.get(n, 0)}
                for n, s in sorted(self._last_seq.items())
            }

    def snapshot(self) -> List[Dict]:
        return self.registry.snapshot()

    def value(self, name: str, **labels) -> Optional[float]:
        """Latest merged value of a counter/gauge (None if absent)."""
        for rec in self.registry.snapshot():
            if rec["name"] != name:
                continue
            lab = rec.get("labels") or {}
            if all(lab.get(k) == v for k, v in labels.items()):
                return float(rec.get("value", rec.get("count", 0)) or 0)
        return None

    def export_prometheus(self, include_plane: bool = True) -> str:
        """Aggregate node metrics + (optionally) this process's own
        ``live/*`` plane-health instruments."""
        text = self.registry.export_prometheus()
        if include_plane:
            plane = get_registry().export_prometheus(name_prefix="live/")
            if plane.strip():
                text = text.rstrip("\n") + "\n" + plane
        return text

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "job": self.job,
                "nodes": len(self._last_seq),
                "frames": int(self._m_ingested.value),
                "duplicate_frames": int(self._m_dup.value),
                "seq_gaps": int(self._m_gaps.value),
                "uptime_s": round(time.time() - self.started, 1),
            }
