"""``fedml_tpu telemetry watch`` — a refreshing per-node terminal view.

Renders the live plane's state as a compact per-round/per-node table: one
row per streaming node (round, clients reporting, worst straggler,
memory, wire bytes, serving round, seq gaps), followed by the online
doctor's most recent alerts. Two targets:

- a scrape endpoint URL (``http://host:port``) — the live path: fetches
  ``/metrics.json`` each refresh;
- a run dir — the offline fallback: reconstructs the same view from the
  latest ``telemetry.jsonl`` registry snapshots (no node attribution
  beyond what labels carry), so the command also works post-hoc.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

__all__ = ["fetch_state", "render_state", "watch"]


def _fetch_url(url: str, timeout: float = 5.0) -> Dict[str, Any]:
    from urllib.request import urlopen

    base = url.rstrip("/")
    if not base.endswith("/metrics.json"):
        base += "/metrics.json"
    with urlopen(base, timeout=timeout) as resp:
        return json.loads(resp.read())


def _state_from_run_dir(run_dir: str) -> Dict[str, Any]:
    """Offline view: latest registry record per (name, labels) + alerts."""
    from fedml_tpu.telemetry.report import load_metrics

    latest: Dict[tuple, Dict] = {}
    alerts: List[Dict] = []
    for rec in load_metrics(run_dir):
        if rec.get("kind") == "doctor_alert":
            alerts.append(rec)
            continue
        name = rec.get("name")
        if not name:
            continue
        key = (name, tuple(sorted((rec.get("labels") or {}).items())))
        latest[key] = rec
    metrics = list(latest.values())
    nodes = sorted({(rec.get("labels") or {}).get("node", "local")
                    for rec in metrics}) or ["local"]
    return {
        "job": os.path.basename(run_dir.rstrip("/")),
        "nodes": len(nodes),
        "frames": 0,
        "seq_gaps": 0,
        "nodes_detail": {n: {"seq": None, "seq_gaps": 0, "last_ts": None}
                         for n in nodes},
        "metrics": metrics,
        "alerts": alerts[-32:],
        "offline": True,
    }


def fetch_state(target: str) -> Dict[str, Any]:
    if target.startswith(("http://", "https://")):
        return _fetch_url(target)
    return _state_from_run_dir(target)


def _fmt_bytes(b: Optional[float]) -> str:
    if not b:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(b) < 1024 or unit == "GiB":
            return f"{b:.0f}{unit}" if unit == "B" else f"{b:.1f}{unit}"
        b /= 1024
    return f"{b:.1f}GiB"  # pragma: no cover


def _node_rows(state: Dict[str, Any]) -> List[Dict[str, Any]]:
    by_node: Dict[str, Dict[str, Any]] = {}
    for rec in state.get("metrics") or []:
        labels = rec.get("labels") or {}
        node = labels.get("node", "local")
        row = by_node.setdefault(node, {
            "node": node, "round": None, "clients": None,
            "straggler": None, "straggler_client": None,
            "mem_bytes": None, "wire_bytes": 0.0, "serving_round": None,
            "mfu": None, "hbm_bound": None,
            "critical_phase": None, "critical_share": None,
            "ttft_p95": None, "occupancy": None, "queue_depth": None})
        name = rec.get("name", "")
        val = float(rec.get("value", rec.get("count", 0)) or 0)
        if name == "health/rounds_scored" and val:
            row["round"] = int(val) - 1
        elif name == "health/clients_reporting":
            row["clients"] = int(val)
        elif name == "health/straggler_score":
            if row["straggler"] is None or val > row["straggler"]:
                row["straggler"] = val
                row["straggler_client"] = labels.get("client")
        elif name in ("mem/device_bytes_in_use", "mem/live_buffer_bytes"):
            row["mem_bytes"] = max(row["mem_bytes"] or 0.0, val)
        elif name in ("comm/wire_bytes_out", "comm/offload_wire_bytes"):
            row["wire_bytes"] += val
        elif name == "serving/round_current":
            row["serving_round"] = int(val)
        elif name == "serving/ttft_ms":
            # histogram record: the p95 is the fleet-ready latency column
            if rec.get("count"):
                row["ttft_p95"] = float(rec.get("p95") or 0.0)
        elif name == "serving/batch_occupancy":
            row["occupancy"] = val
        elif name == "serving/queue_depth":
            row["queue_depth"] = val
        elif name == "profile/mfu":
            # streamed by the program catalog's gauge pump: achieved
            # FLOP/s over the device peak, refreshed each phase sample
            row["mfu"] = val
        elif name == "profile/hbm_bound":
            row["hbm_bound"] = bool(val)
        elif name == "tracepath/critical_phase":
            # phase_code()-encoded top phase of the latest round's
            # critical path, pumped by the live plane each round
            row["critical_phase"] = int(val)
        elif name == "tracepath/critical_share":
            row["critical_share"] = val
    detail = state.get("nodes_detail") or {}
    for node, d in detail.items():
        row = by_node.setdefault(node, {
            "node": node, "round": None, "clients": None, "straggler": None,
            "straggler_client": None, "mem_bytes": None, "wire_bytes": 0.0,
            "serving_round": None, "mfu": None, "hbm_bound": None,
            "critical_phase": None, "critical_share": None,
            "ttft_p95": None, "occupancy": None, "queue_depth": None})
        row["seq"] = d.get("seq")
        row["seq_gaps"] = d.get("seq_gaps", 0)
    return [by_node[n] for n in sorted(by_node)]


def render_state(state: Dict[str, Any], now: Optional[float] = None) -> str:
    now = now or time.time()
    lines: List[str] = []
    add = lines.append
    head = (f"live telemetry — job {state.get('job')!s}: "
            f"{state.get('nodes', 0)} node(s), "
            f"{state.get('frames', 0)} frame(s), "
            f"{state.get('seq_gaps', 0)} seq gap(s)")
    if state.get("offline"):
        head += "  [offline: rendered from run-dir snapshots]"
    add(head)
    add("")
    add(f"  {'node':<14s}{'round':>6s}{'clients':>8s}{'straggler':>12s}"
        f"{'mem':>10s}{'wire':>10s}{'mfu':>7s}{'roofline':>10s}"
        f"{'critical':>16s}{'serving':>8s}{'ttft':>9s}{'sat':>9s}"
        f"{'gaps':>6s}")
    for row in _node_rows(state):
        strag = ("-" if row.get("straggler") is None else
                 f"{row['straggler']:.1f}x"
                 + (f"@{row['straggler_client']}"
                    if row.get("straggler_client") else ""))
        mfu = ("-" if row.get("mfu") is None
               else f"{row['mfu']:.2f}")
        roofline = ("-" if row.get("hbm_bound") is None
                    else ("HBM" if row["hbm_bound"] else "compute"))
        if row.get("critical_phase") is None:
            critical = "-"
        else:
            from fedml_tpu.telemetry.tracing import phase_label

            critical = phase_label(row["critical_phase"])
            if row.get("critical_share") is not None:
                critical += f" {100 * row['critical_share']:.0f}%"
        ttft = ("-" if row.get("ttft_p95") is None
                else f"{row['ttft_p95']:.0f}ms")
        # saturation: batch-slot occupancy fraction / admission queue depth
        sat = ("-" if row.get("occupancy") is None
               else f"{100 * row['occupancy']:.0f}%"
               + (f"+{row['queue_depth']:.0f}q"
                  if row.get("queue_depth") else ""))
        add(f"  {row['node']:<14s}"
            f"{row['round'] if row['round'] is not None else '-':>6}"
            f"{row['clients'] if row['clients'] is not None else '-':>8}"
            f"{strag:>12s}"
            f"{_fmt_bytes(row.get('mem_bytes')):>10s}"
            f"{_fmt_bytes(row.get('wire_bytes')):>10s}"
            f"{mfu:>7s}"
            f"{roofline:>10s}"
            f"{critical:>16s}"
            f"{row['serving_round'] if row['serving_round'] is not None else '-':>8}"
            f"{ttft:>9s}"
            f"{sat:>9s}"
            f"{row.get('seq_gaps', 0):>6}")
    alerts = state.get("alerts") or []
    add("")
    if alerts:
        shown = min(len(alerts), 8)
        add(f"alerts (last {shown} of {len(alerts)}, newest last):")
        for a in alerts[-8:]:
            rnd = a.get("round")
            add(f"  [{a.get('rule')}] round {rnd if rnd is not None else '?'}"
                f": {a.get('verdict')}")
    else:
        add("alerts: none")
    return "\n".join(lines)


def watch(target: str, interval_s: float = 2.0, once: bool = False,
          out=None, max_refreshes: Optional[int] = None) -> int:
    """Render the target's live state; refresh every ``interval_s`` until
    interrupted (``once=True`` prints a single frame and exits — the CI
    smoke path). Returns 0, or 1 when the target is unreachable."""
    import sys

    write = out or (lambda s: (sys.stdout.write(s + "\n"),
                               sys.stdout.flush()))
    n = 0
    while True:
        try:
            state = fetch_state(target)
        except (OSError, ValueError) as e:
            write(f"telemetry watch: cannot read {target}: {e}")
            return 1
        text = render_state(state)
        if once or max_refreshes is not None:
            write(text)
        else:  # pragma: no cover - interactive path
            write("\x1b[2J\x1b[H" + text)
        n += 1
        if once or (max_refreshes is not None and n >= max_refreshes):
            return 0
        try:  # pragma: no cover - interactive path
            time.sleep(interval_s)
        except KeyboardInterrupt:  # pragma: no cover
            return 0
