"""Live telemetry plane — stream metrics while the run is in flight.

PR 1/4 made telemetry *post-hoc*: JSONL sinks that ``telemetry report``
and ``doctor`` read after the process exits. This package makes it
**live**: every node (cross-silo clients, hierarchy aggregators, the
serving endpoint, the scheduler) periodically snapshots its metric
registry off-thread and streams seq-numbered cumulative-delta frames to
a central :class:`LiveCollector` — piggybacked on existing round traffic
where it exists (``FedMLCommManager`` pops a prepared frame onto
outgoing messages), a low-frequency dedicated frame otherwise. The
collector merges frames into a node-/job-labeled aggregate registry with
duplicate-frame idempotence and seq-gap accounting, serves it on a live
``/metrics`` Prometheus scrape endpoint (+ ``/healthz``), and powers
``fedml_tpu telemetry watch`` plus the :class:`OnlineDoctor` — the
post-hoc triage rules evaluated mid-run, alerting at the round a
condition trips instead of in the autopsy.

Enable on a federation with ``live_telemetry: true`` (plus an optional
``metrics_port``) in the train args; see ``docs/observability.md``.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from fedml_tpu.telemetry.live.collector import LiveCollector
from fedml_tpu.telemetry.live.frames import (
    FRAME_VERSION,
    MetricStreamer,
    frame_nbytes,
)
from fedml_tpu.telemetry.live.online_doctor import OnlineDoctor
from fedml_tpu.telemetry.live.scrape import MetricsScrapeServer
from fedml_tpu.telemetry.live.watch import fetch_state, render_state, watch

__all__ = [
    "FRAME_VERSION",
    "LiveCollector",
    "LivePlane",
    "MetricStreamer",
    "MetricsScrapeServer",
    "OnlineDoctor",
    "current_live_plane",
    "fetch_state",
    "frame_nbytes",
    "ingest_frame",
    "ingest_trace_frame",
    "render_state",
    "reset_live_plane",
    "watch",
]

_plane_lock = threading.Lock()
_plane: Optional["LivePlane"] = None


class LivePlane:
    """The collector-side bundle one process hosts: loopback streamer for
    its own registry, the collector, the online doctor, and (optionally)
    the HTTP scrape endpoint. Construct via :meth:`from_args` on whatever
    node aggregates the run (the cross-silo server, the tree root, a
    scheduler) — remote frames arriving at ANY comm manager in this
    process are routed here via :func:`ingest_frame`."""

    def __init__(self, job: str, node: str = "rank0",
                 run_dir: Optional[str] = None,
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "127.0.0.1",
                 interval_s: float = 1.0,
                 doctor_kwargs: Optional[Dict[str, Any]] = None,
                 tracing: bool = True):
        self.collector = LiveCollector(job=job)
        self.doctor = OnlineDoctor(self.collector, run_dir=run_dir,
                                   **(doctor_kwargs or {}))
        self.streamer = MetricStreamer(node, job=job,
                                       interval_s=interval_s).start()
        self._run_dir = run_dir
        # causal tracing: merge span-batch frames from every node (and
        # this process's own spans via a loopback SpanStreamer) so the
        # per-round critical path is computable while the run is live,
        # and the merged set persists as spans_remote.jsonl on close
        self.trace_collector = None
        self.trace_streamer = None
        if tracing:
            from fedml_tpu.telemetry.tracing import (
                SpanStreamer,
                TraceCollector,
            )

            self.trace_collector = TraceCollector(job=job)
            self.trace_streamer = SpanStreamer(
                node, job=job, interval_s=interval_s).attach()
        self.scrape: Optional[MetricsScrapeServer] = None
        if metrics_port is not None:
            self.scrape = MetricsScrapeServer(
                self.collector, host=metrics_host, port=int(metrics_port),
                doctor=self.doctor).start()
        self._closed = False
        global _plane
        with _plane_lock:
            _plane = self

    @classmethod
    def from_args(cls, args: Any, node: str,
                  run_dir: Optional[str] = None) -> Optional["LivePlane"]:
        """None unless ``args.live_telemetry`` is truthy — the production
        hot path stays a None-check."""
        if not bool(getattr(args, "live_telemetry", False)):
            return None
        port = getattr(args, "metrics_port", None)
        return cls(
            job=str(getattr(args, "run_id", "0") or "0"),
            node=node,
            run_dir=run_dir,
            metrics_port=int(port) if port is not None else None,
            metrics_host=str(getattr(args, "metrics_host", "127.0.0.1")),
            interval_s=float(getattr(args, "live_interval_s", 1.0)),
            doctor_kwargs={
                "straggler_threshold": float(
                    getattr(args, "straggler_threshold", 2.0)),
                "anomaly_threshold": float(
                    getattr(args, "anomaly_threshold", 4.0)),
            },
            tracing=bool(getattr(args, "trace_streaming", True)),
        )

    @property
    def url(self) -> Optional[str]:
        return self.scrape.url if self.scrape is not None else None

    def pump(self, round_idx: Optional[int] = None) -> None:
        """Loopback this process's own registry into the collector (the
        server calls this once per closed round; rounds are derived from
        the pumped health/rounds_scored metric). With ``round_idx`` and
        tracing enabled, also compute the just-closed round's critical
        path from the merged span set and publish it as ``tracepath/*``
        gauges (the ``telemetry watch`` critical-phase column)."""
        if self.trace_streamer is not None:
            self.trace_streamer.pump(self.trace_collector, force=True)
        if round_idx is not None and self.trace_collector is not None:
            try:
                self._pump_critical_path(int(round_idx))
            except Exception:  # observability must never break the round
                import logging

                logging.getLogger(__name__).exception(
                    "critical-path pump failed at round %s", round_idx)
        self.streamer.pump(self.collector, force=True)

    def _pump_critical_path(self, round_idx: int) -> None:
        from fedml_tpu.telemetry.registry import get_registry
        from fedml_tpu.telemetry.tracing import (
            assemble_records,
            compute_critical_path,
            phase_code,
        )

        trace = assemble_records(self.trace_collector.records())
        cp = compute_critical_path(trace, round_idx)
        if cp is None or not cp.segments:
            return
        reg = get_registry()
        reg.gauge("tracepath/critical_round").set(float(cp.round))
        reg.gauge("tracepath/critical_phase").set(
            float(phase_code(cp.top_phase())))
        reg.gauge("tracepath/critical_share").set(float(cp.top_share()))

    def close(self, drain_s: float = 3.0) -> None:
        """Final full loopback frame, then stop the plane's threads. The
        scrape endpoint keeps serving until stop — callers that want the
        endpoint to outlive the run simply don't close."""
        if self._closed:
            return
        self._closed = True
        # bounded drain: on distributed backends the server's FINISH is
        # what makes each client flush its final FULL frame — those
        # frames are still in flight when the training loop reaches
        # close, and tearing down now would drop them (totals would
        # never become exact). Wait for the stream to go quiet, bounded
        # by drain_s; runs where only the loopback node ever streamed
        # (in-proc LOCAL) have nothing in flight and skip the wait.
        if drain_s > 0 and any(n != self.streamer.node
                               for n in self.collector.nodes()):
            deadline = time.time() + drain_s
            last_count = self.collector.stats()["frames"]
            last_change = time.time()
            while time.time() < deadline:
                time.sleep(0.05)
                count = self.collector.stats()["frames"]
                if count != last_count:
                    last_count, last_change = count, time.time()
                elif time.time() - last_change >= 0.25:
                    break
        # span stream closes FIRST: its close/ingest bump tracepath/*
        # counters in the process registry, and the final metric FULL
        # frame below must snapshot those totals — the other order
        # leaves the collector's mirror permanently short of post-hoc
        if self.trace_streamer is not None:
            tfinal = self.trace_streamer.close()
            if tfinal is not None and self.trace_collector is not None:
                self.trace_collector.ingest(tfinal)
        final = self.streamer.close()
        if final is not None:
            self.collector.ingest(final)
        if self.trace_collector is not None and self._run_dir:
            try:
                # the merged federation-wide span set lands next to the
                # local sink for post-hoc assembly (trace CLI / report)
                self.trace_collector.persist(self._run_dir)
            except OSError:  # pragma: no cover - sink dir gone at exit
                pass
        if self.scrape is not None:
            self.scrape.stop()
        global _plane
        with _plane_lock:
            if _plane is self:
                _plane = None


def current_live_plane() -> Optional[LivePlane]:
    with _plane_lock:
        return _plane


def ingest_frame(frame: Any) -> bool:
    """Route a remote node's frame to this process's plane (no-op when no
    plane is bound — the receiving manager need not know whether it is
    the collector host)."""
    plane = current_live_plane()
    if plane is None:
        return False
    return plane.collector.ingest(frame)


def ingest_trace_frame(frame: Any) -> bool:
    """Route a remote node's span-batch frame to this process's plane's
    TraceCollector (no-op when no plane, or tracing is off)."""
    plane = current_live_plane()
    if plane is None or plane.trace_collector is None:
        return False
    return plane.trace_collector.ingest(frame)


def reset_live_plane() -> None:
    """Drop the process-global plane (test isolation)."""
    global _plane
    with _plane_lock:
        plane, _plane = _plane, None
    if plane is not None:
        try:
            if plane.scrape is not None:
                plane.scrape.stop()
            plane.streamer.stop()
            if plane.trace_streamer is not None:
                plane.trace_streamer.stop()
        except Exception:  # pragma: no cover - teardown best effort
            pass
