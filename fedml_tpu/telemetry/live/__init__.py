"""Live telemetry plane — stream metrics while the run is in flight.

PR 1/4 made telemetry *post-hoc*: JSONL sinks that ``telemetry report``
and ``doctor`` read after the process exits. This package makes it
**live**: every node (cross-silo clients, hierarchy aggregators, the
serving endpoint, the scheduler) periodically snapshots its metric
registry off-thread and streams seq-numbered cumulative-delta frames to
a central :class:`LiveCollector` — piggybacked on existing round traffic
where it exists (``FedMLCommManager`` pops a prepared frame onto
outgoing messages), a low-frequency dedicated frame otherwise. The
collector merges frames into a node-/job-labeled aggregate registry with
duplicate-frame idempotence and seq-gap accounting, serves it on a live
``/metrics`` Prometheus scrape endpoint (+ ``/healthz``), and powers
``fedml_tpu telemetry watch`` plus the :class:`OnlineDoctor` — the
post-hoc triage rules evaluated mid-run, alerting at the round a
condition trips instead of in the autopsy.

Enable on a federation with ``live_telemetry: true`` (plus an optional
``metrics_port``) in the train args; see ``docs/observability.md``.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from fedml_tpu.telemetry.live.collector import LiveCollector
from fedml_tpu.telemetry.live.frames import (
    FRAME_VERSION,
    MetricStreamer,
    frame_nbytes,
)
from fedml_tpu.telemetry.live.online_doctor import OnlineDoctor
from fedml_tpu.telemetry.live.scrape import MetricsScrapeServer
from fedml_tpu.telemetry.live.watch import fetch_state, render_state, watch

__all__ = [
    "FRAME_VERSION",
    "LiveCollector",
    "LivePlane",
    "MetricStreamer",
    "MetricsScrapeServer",
    "OnlineDoctor",
    "current_live_plane",
    "fetch_state",
    "frame_nbytes",
    "ingest_frame",
    "render_state",
    "reset_live_plane",
    "watch",
]

_plane_lock = threading.Lock()
_plane: Optional["LivePlane"] = None


class LivePlane:
    """The collector-side bundle one process hosts: loopback streamer for
    its own registry, the collector, the online doctor, and (optionally)
    the HTTP scrape endpoint. Construct via :meth:`from_args` on whatever
    node aggregates the run (the cross-silo server, the tree root, a
    scheduler) — remote frames arriving at ANY comm manager in this
    process are routed here via :func:`ingest_frame`."""

    def __init__(self, job: str, node: str = "rank0",
                 run_dir: Optional[str] = None,
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "127.0.0.1",
                 interval_s: float = 1.0,
                 doctor_kwargs: Optional[Dict[str, Any]] = None):
        self.collector = LiveCollector(job=job)
        self.doctor = OnlineDoctor(self.collector, run_dir=run_dir,
                                   **(doctor_kwargs or {}))
        self.streamer = MetricStreamer(node, job=job,
                                       interval_s=interval_s).start()
        self.scrape: Optional[MetricsScrapeServer] = None
        if metrics_port is not None:
            self.scrape = MetricsScrapeServer(
                self.collector, host=metrics_host, port=int(metrics_port),
                doctor=self.doctor).start()
        self._closed = False
        global _plane
        with _plane_lock:
            _plane = self

    @classmethod
    def from_args(cls, args: Any, node: str,
                  run_dir: Optional[str] = None) -> Optional["LivePlane"]:
        """None unless ``args.live_telemetry`` is truthy — the production
        hot path stays a None-check."""
        if not bool(getattr(args, "live_telemetry", False)):
            return None
        port = getattr(args, "metrics_port", None)
        return cls(
            job=str(getattr(args, "run_id", "0") or "0"),
            node=node,
            run_dir=run_dir,
            metrics_port=int(port) if port is not None else None,
            metrics_host=str(getattr(args, "metrics_host", "127.0.0.1")),
            interval_s=float(getattr(args, "live_interval_s", 1.0)),
            doctor_kwargs={
                "straggler_threshold": float(
                    getattr(args, "straggler_threshold", 2.0)),
                "anomaly_threshold": float(
                    getattr(args, "anomaly_threshold", 4.0)),
            },
        )

    @property
    def url(self) -> Optional[str]:
        return self.scrape.url if self.scrape is not None else None

    def pump(self) -> None:
        """Loopback this process's own registry into the collector (the
        server calls this once per closed round; rounds are derived from
        the pumped health/rounds_scored metric, not passed in)."""
        self.streamer.pump(self.collector, force=True)

    def close(self, drain_s: float = 3.0) -> None:
        """Final full loopback frame, then stop the plane's threads. The
        scrape endpoint keeps serving until stop — callers that want the
        endpoint to outlive the run simply don't close."""
        if self._closed:
            return
        self._closed = True
        # bounded drain: on distributed backends the server's FINISH is
        # what makes each client flush its final FULL frame — those
        # frames are still in flight when the training loop reaches
        # close, and tearing down now would drop them (totals would
        # never become exact). Wait for the stream to go quiet, bounded
        # by drain_s; runs where only the loopback node ever streamed
        # (in-proc LOCAL) have nothing in flight and skip the wait.
        if drain_s > 0 and any(n != self.streamer.node
                               for n in self.collector.nodes()):
            deadline = time.time() + drain_s
            last_count = self.collector.stats()["frames"]
            last_change = time.time()
            while time.time() < deadline:
                time.sleep(0.05)
                count = self.collector.stats()["frames"]
                if count != last_count:
                    last_count, last_change = count, time.time()
                elif time.time() - last_change >= 0.25:
                    break
        final = self.streamer.close()
        if final is not None:
            self.collector.ingest(final)
        if self.scrape is not None:
            self.scrape.stop()
        global _plane
        with _plane_lock:
            if _plane is self:
                _plane = None


def current_live_plane() -> Optional[LivePlane]:
    with _plane_lock:
        return _plane


def ingest_frame(frame: Any) -> bool:
    """Route a remote node's frame to this process's plane (no-op when no
    plane is bound — the receiving manager need not know whether it is
    the collector host)."""
    plane = current_live_plane()
    if plane is None:
        return False
    return plane.collector.ingest(frame)


def reset_live_plane() -> None:
    """Drop the process-global plane (test isolation)."""
    global _plane
    with _plane_lock:
        plane, _plane = _plane, None
    if plane is not None:
        try:
            if plane.scrape is not None:
                plane.scrape.stop()
            plane.streamer.stop()
        except Exception:  # pragma: no cover - teardown best effort
            pass
