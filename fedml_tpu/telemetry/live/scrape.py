"""Live ``/metrics`` scrape endpoint for a collector.

The same bounded ``ThreadingHTTPServer`` pattern as the PR 7 serving
runner (one OS thread per connection, but admission gated by a semaphore
that sheds with 429 instead of queueing unboundedly), serving:

- ``GET /metrics``      Prometheus text: the collector's node-/job-
                        labeled aggregate + this process's ``live/*``
                        plane health (frames, gaps, alerts);
- ``GET /metrics.json`` machine-readable state: per-node seq/gap
                        accounting, the merged metric snapshot, and the
                        online doctor's alerts (what ``telemetry watch``
                        renders);
- ``GET /healthz``      liveness + plane stats;
- ``POST /ingest``      one JSON metric frame — the dedicated-transport
                        path for nodes with no federation traffic to
                        piggyback on (a serving endpoint, a scheduler).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from fedml_tpu.telemetry.registry import get_registry
from fedml_tpu.utils.bounded_http import AdmissionGate, drain_body

__all__ = ["MetricsScrapeServer"]

_MAX_FRAME_BYTES = 4 << 20  # a POSTed frame larger than this is garbage


class MetricsScrapeServer:
    def __init__(self, collector, host: str = "127.0.0.1", port: int = 0,
                 doctor=None, max_inflight: int = 8,
                 queue_wait_s: float = 0.05):
        self.collector = collector
        self.doctor = doctor
        server = self
        reg = get_registry()
        self._m_scrapes = reg.counter("live/scrapes")
        self._m_rejected = reg.counter("live/scrapes_rejected")
        # shared bounded-admission policy (same gate as the inference
        # runner); a shed scrape only bumps the counter — the live plane
        # has no per-request latency story to tell
        self._gate = AdmissionGate(
            max_inflight, queue_wait_s, max_drain_bytes=_MAX_FRAME_BYTES,
            on_shed=lambda depth, wait_s: self._m_rejected.inc())

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _send(self, body: bytes, status: int = 200,
                      ctype: str = "application/json") -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if not server._gate.admit(self):
                    return
                try:
                    path = self.path.split("?")[0].rstrip("/")
                    if path == "/metrics":
                        server._m_scrapes.inc()
                        text = server.collector.export_prometheus()
                        self._send(text.encode(), ctype="text/plain; "
                                   "version=0.0.4; charset=utf-8")
                    elif path == "/metrics.json":
                        server._m_scrapes.inc()
                        self._send(json.dumps(
                            server.state(), default=str).encode())
                    elif path in ("", "/healthz", "/health"):
                        self._send(json.dumps(
                            {"ok": True, **server.collector.stats()}).encode())
                    else:
                        self.send_error(404)
                except BrokenPipeError:  # pragma: no cover - client gone
                    pass
                finally:
                    server._gate.release()

            def do_POST(self):
                if not server._gate.admit(self):
                    return
                try:
                    path = self.path.rstrip("/")
                    n = int(self.headers.get("Content-Length", 0))
                    if path != "/ingest":
                        drain_body(self, _MAX_FRAME_BYTES)
                        self.send_error(404)
                        return
                    if n <= 0 or n > _MAX_FRAME_BYTES:
                        drain_body(self, _MAX_FRAME_BYTES)
                        self._send(json.dumps(
                            {"error": "bad frame size"}).encode(), status=400)
                        return
                    try:
                        frame = json.loads(self.rfile.read(n))
                    except ValueError:
                        self._send(json.dumps(
                            {"error": "not json"}).encode(), status=400)
                        return
                    applied = server.collector.ingest(frame)
                    self._send(json.dumps({"applied": bool(applied)}).encode())
                except BrokenPipeError:  # pragma: no cover
                    pass
                finally:
                    server._gate.release()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    def state(self) -> dict:
        """The ``/metrics.json`` payload (also what watch renders)."""
        return {
            **self.collector.stats(),
            "nodes_detail": self.collector.nodes(),
            "metrics": self.collector.snapshot(),
            "alerts": (self.doctor.snapshot()[-32:]
                       if self.doctor is not None else []),
        }

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "MetricsScrapeServer":
        if self._thread is None:
            self._thread = threading.Thread(
                # 50ms poll: the default 0.5s makes shutdown() block up
                # to half a second INSIDE a closing run's wall clock —
                # measured as a fake 20% rounds/s hit on short runs
                target=lambda: self._server.serve_forever(
                    poll_interval=0.05),
                daemon=True, name="metrics-scrape")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
