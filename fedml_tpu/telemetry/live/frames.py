"""Metric frames — the unit of live telemetry streaming.

A **frame** is a seq-numbered, node-/job-stamped batch of metric readings
a node ships to the collector while the run is in flight. Readings are
CUMULATIVE (a counter's total, a gauge's value, a histogram's full bucket
counts), *delta-filtered*: a frame only carries the instruments that
changed since the last frame this streamer emitted. Cumulative-but-
delta-filtered is the load-bearing choice:

- **duplicate frames are idempotent** — the collector diffs each reading
  against the last value it applied for that (node, metric), so replaying
  a frame applies a zero delta;
- **dropped frames self-heal** — the next frame that carries the metric
  re-ships its full cumulative value, and every ``resync_every``-th frame
  (plus the final frame at :meth:`MetricStreamer.close`) is a FULL
  snapshot, so the collector converges to exact totals even over a lossy
  path (the seq gap is still *accounted*: ``live/seq_gaps``);
- **bounded bytes** — steady state ships only what moved, capped at
  ``max_entries`` per frame with carry-over rotation, so a node's wire
  cost per round is bounded no matter how many instruments exist.

The streamer snapshots its registry OFF-THREAD (a daemon thread prepares
the next frame every ``interval_s``); the hot send path only pops the
prepared frame — no device sync, no JSON work on the sending thread
beyond what the transport does anyway. Frames piggyback on existing
federation traffic via ``FedMLCommManager`` (see
``Message.MSG_ARG_KEY_TELEMETRY``) where traffic exists; where it does
not, pass ``send_cb`` and the off-thread loop emits a low-frequency
dedicated frame itself.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from fedml_tpu.telemetry.registry import (
    BYTES_BUCKETS,
    MetricsRegistry,
    get_registry,
)

__all__ = ["FRAME_VERSION", "MetricStreamer", "frame_nbytes"]

FRAME_VERSION = 1

# collector-plane meta-metrics never ride frames: the collector's own
# live/* instruments would otherwise chase their tails (each ingest
# changes them, making every frame "changed"), and equality between the
# collector's merged totals and the node's post-hoc snapshot would be
# unprovable
DEFAULT_EXCLUDE_PREFIXES: Tuple[str, ...] = ("live/",)


def frame_nbytes(frame: Dict[str, Any]) -> int:
    """Wire-cost estimate of a frame (its JSON length)."""
    return len(json.dumps(frame))


def _entry_of(rec: Dict[str, Any]) -> Dict[str, Any]:
    """One frame entry from a registry snapshot record (cumulative)."""
    entry: Dict[str, Any] = {
        "name": rec["name"],
        "kind": rec["kind"],
    }
    if rec.get("labels"):
        entry["labels"] = dict(rec["labels"])
    if rec["kind"] == "histogram":
        entry["count"] = rec["count"]
        entry["sum"] = rec["sum"]
        entry["min"] = rec["min"]
        entry["max"] = rec["max"]
        entry["buckets"] = dict(rec["buckets"])
    else:
        entry["value"] = rec["value"]
    return entry


def _changed(entry: Dict, prev: Optional[Dict]) -> bool:
    if prev is None:
        return True
    if entry["kind"] == "histogram":
        return (entry["count"] != prev["count"]
                or entry["sum"] != prev["sum"])
    return entry["value"] != prev["value"]


class MetricStreamer:
    """Periodic off-thread snapshotter of one registry into metric frames.

    ``node`` is this stream's identity at the collector (one streamer per
    process in a real deployment — the process-global registry IS the
    node's registry); ``job`` namespaces multi-tenant collectors.

    Usage::

        streamer = MetricStreamer("rank1", job=run_id).start()
        # hot path (FedMLCommManager.send_message does this):
        frame = streamer.pop_frame()     # None unless one is due
        # loopback (server-side own metrics):
        streamer.pump(collector, force=True)
        final = streamer.close()         # full snapshot, stream end
    """

    def __init__(self, node: str, job: str = "default",
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 1.0,
                 max_entries: int = 256,
                 resync_every: int = 8,
                 exclude_prefixes: Tuple[str, ...] = DEFAULT_EXCLUDE_PREFIXES,
                 send_cb: Optional[Callable[[Dict], None]] = None):
        self.node = str(node)
        self.job = str(job)
        self._registry = registry
        self.interval_s = float(interval_s)
        self.max_entries = max(1, int(max_entries))
        self.resync_every = max(1, int(resync_every))
        self.exclude_prefixes = tuple(exclude_prefixes)
        self._send_cb = send_cb
        self._lock = threading.Lock()
        self._seq = 0
        self._last_sent: Dict[Tuple, Dict] = {}
        self._carry: List[Tuple] = []  # changed keys deferred by the cap
        self._prepared: Optional[List[Dict]] = None
        self._prepared_full = False
        self._last_emit = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # frame cost instruments land in the PROCESS registry (they are
        # live/*, so they never ride frames themselves)
        reg = get_registry()
        self._m_frames = reg.counter("live/frames_emitted")
        self._h_bytes = reg.histogram("live/frame_bytes",
                                      buckets=BYTES_BUCKETS)

    # -- snapshot + delta filter ------------------------------------------
    def _source(self) -> MetricsRegistry:
        return self._registry if self._registry is not None else get_registry()

    def _scan(self) -> Dict[Tuple, Dict]:
        out: Dict[Tuple, Dict] = {}
        for rec in self._source().snapshot():
            name = rec["name"]
            if name.startswith(self.exclude_prefixes):
                continue
            key = (name, tuple(sorted((rec.get("labels") or {}).items())))
            out[key] = _entry_of(rec)
        return out

    def _build_entries(self, full: bool) -> Optional[List[Dict]]:
        """Entries for the next frame (None = nothing changed)."""
        scan = self._scan()
        with self._lock:
            if full:
                keys = sorted(scan)
            else:
                carried = [k for k in self._carry if k in scan]
                fresh = sorted(
                    k for k, e in scan.items()
                    if k not in carried and _changed(e, self._last_sent.get(k)))
                keys = carried + fresh
                if not keys:
                    return None
                self._carry = keys[self.max_entries:]
                keys = keys[: self.max_entries]
            return [scan[k] for k in keys]

    def _commit(self, entries: List[Dict], full: bool) -> Dict[str, Any]:
        """Stamp seq + node identity and mark the entries as sent."""
        with self._lock:
            self._seq += 1
            frame = {
                "v": FRAME_VERSION,
                "node": self.node,
                "job": self.job,
                "seq": self._seq,
                "ts": time.time(),
                "full": bool(full),
                "metrics": entries,
            }
            for e in entries:
                key = (e["name"],
                       tuple(sorted((e.get("labels") or {}).items())))
                self._last_sent[key] = e
            self._last_emit = time.time()
        self._m_frames.inc()
        self._h_bytes.observe(frame_nbytes(frame))
        return frame

    def _due_full(self) -> bool:
        return (self._seq + 1) % self.resync_every == 0

    # -- off-thread preparation -------------------------------------------
    def start(self) -> "MetricStreamer":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"metric-streamer-{self.node}",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                entries = self._build_entries(full=self._due_full())
                if entries is None:
                    continue
                if self._send_cb is not None:
                    # dedicated low-frequency frame: no round traffic to
                    # ride, so this thread delivers it itself
                    self._send_cb(self._commit(entries, self._due_full()))
                else:
                    with self._lock:
                        # never displace a prepared FULL frame (a
                        # flush_final waiting for the last message out)
                        # with a delta — the stream's final frame must
                        # stay full or lost-frame healing is forfeited;
                        # entries are only marked sent at commit, so
                        # anything this delta carried is re-collected
                        if not (self._prepared is not None
                                and self._prepared_full):
                            self._prepared = entries
                            self._prepared_full = self._due_full()
            except Exception:  # pragma: no cover - observability never kills
                import logging

                logging.getLogger(__name__).exception(
                    "metric streamer scan failed")

    # -- hot-path surface --------------------------------------------------
    def pop_frame(self, force: bool = False) -> Optional[Dict[str, Any]]:
        """The prepared frame, seq-stamped — or None when nothing is due.

        Rate-limited to one frame per ``interval_s`` so a chatty transport
        cannot amplify telemetry traffic; ``force`` builds inline (the
        loopback pump and the final flush use it).
        """
        with self._lock:
            due = force or (time.time() - self._last_emit >= self.interval_s)
            if not due:
                # leave the prepared frame in place — discarding it here
                # would push the registry scan onto the next due send
                return None
            prepared, full = self._prepared, self._prepared_full
            self._prepared = None
        if force:
            # a forced pop (per-round pump, final flush) must reflect the
            # registry NOW, not a snapshot the prep thread took earlier;
            # discarding the prepared entries is safe — they are only
            # marked sent at commit, so they stay "changed" and are
            # re-collected by this fresh build
            prepared = None
        if prepared is None:
            # no prepared frame (prep thread hasn't fired since the last
            # emit) — build inline; rate-limited above, host-only work
            full = self._due_full()
            prepared = self._build_entries(full=full)
            if prepared is None:
                return None
        return self._commit(prepared, full)

    def pump(self, collector, force: bool = True) -> bool:
        """Loopback: build a frame and ingest it into ``collector``."""
        frame = self.pop_frame(force=force)
        if frame is None:
            return False
        collector.ingest(frame)
        return True

    def flush_final(self) -> None:
        """Prepare a FULL frame for the next ``pop_frame`` (stream close
        piggybacked on the last message out)."""
        entries = self._build_entries(full=True)
        with self._lock:
            self._prepared = entries or []
            self._prepared_full = True
            self._last_emit = 0.0  # make the next pop unconditionally due

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def close(self) -> Optional[Dict[str, Any]]:
        """Stop the off-thread loop and return the final FULL frame (the
        collector's totals become exact the moment it lands)."""
        self.stop()
        entries = self._build_entries(full=True)
        if entries is None:
            entries = []
        frame = self._commit(entries, full=True)
        if self._send_cb is not None and frame["metrics"]:
            try:
                self._send_cb(frame)
            except Exception:  # pragma: no cover - transport already down
                pass
        return frame
