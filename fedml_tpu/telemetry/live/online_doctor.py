"""Online doctor — post-hoc triage rules evaluated on the live stream.

``telemetry doctor`` answers "what went wrong" after the run; the online
doctor answers it **while the run is still going**: it hangs off the
:class:`~fedml_tpu.telemetry.live.collector.LiveCollector` as an ingest
hook and re-evaluates the same rule set incrementally on every applied
frame — straggling clients, memory growth slope, a serving endpoint
stuck on a stale round, quorum-degraded rounds, evicted nodes that never
rejoined. A tripped rule emits ONE alert (edge-triggered, deduped per
subject) the round the condition becomes true, landed in all three
places an operator might be watching:

- a ``doctor_alert`` record appended to ``<run_dir>/telemetry.jsonl``
  (the post-hoc doctor surfaces these in its ``live`` section, proving
  the alert fired mid-run, not in the autopsy);
- the flight recorder ring (a crash dump shows the alerts that preceded
  death);
- the ``live/alerts`` counter (labeled by rule) on the scrape endpoint.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from fedml_tpu.telemetry import flight_recorder
from fedml_tpu.telemetry.registry import get_registry

__all__ = ["OnlineDoctor"]


class OnlineDoctor:
    """Incremental triage over a live collector's merged registry."""

    def __init__(self, collector, run_dir: Optional[str] = None,
                 straggler_threshold: float = 2.0,
                 anomaly_threshold: float = 4.0,
                 mem_growth_threshold: float = 1.5,
                 min_rounds: int = 3,
                 stale_round_gap: int = 2,
                 rejoin_grace_rounds: int = 2,
                 slo_burn_threshold: float = 10.0,
                 slo_burn_windows_s: Tuple[float, ...] = (60.0, 300.0)):
        self.collector = collector
        self.run_dir = run_dir
        self.straggler_threshold = float(straggler_threshold)
        self.anomaly_threshold = float(anomaly_threshold)
        self.mem_growth_threshold = float(mem_growth_threshold)
        self.min_rounds = int(min_rounds)
        self.stale_round_gap = int(stale_round_gap)
        self.rejoin_grace_rounds = int(rejoin_grace_rounds)
        self.slo_burn_threshold = float(slo_burn_threshold)
        self.slo_burn_windows_s = tuple(
            sorted(float(w) for w in slo_burn_windows_s))
        self.alerts: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        # serializes rule evaluation: collector hooks fire outside the
        # collector's merge lock, and ingests arrive concurrently (comm
        # receive threads + ThreadingHTTPServer /ingest handlers) — the
        # per-rule history dicts below are not safe to race on
        self._eval_lock = threading.Lock()
        self._fired: set = set()
        self._mem_hist: Dict[Tuple, List[Tuple[int, float]]] = {}
        # (node, endpoint, objective) -> [(ts, slo_total, slo_breaches)]:
        # the cumulative-counter history the multi-window burn rate is
        # differenced from
        self._slo_hist: Dict[Tuple, List[Tuple[float, float, float]]] = {}
        self._quorum_seen: Dict[Tuple, float] = {}
        self._evict_epoch: Dict[str, Tuple[float, Optional[int]]] = {}
        self._rounds: Dict[str, int] = {}
        collector.add_hook(self._on_frame)

    # -- plumbing ----------------------------------------------------------
    @staticmethod
    def _per_node(by_name: Dict[str, List[Dict]],
                  name: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for rec in by_name.get(name, ()):
            node = (rec.get("labels") or {}).get("node", "?")
            out[node] = out.get(node, 0.0) + float(
                rec.get("value", rec.get("count", 0)) or 0)
        return out

    def _round_of(self, node: str) -> Optional[int]:
        """The node's current round: rounds_scored counts completed
        scoring passes, so the round that just closed is value - 1.
        Computed once per ingested frame from the snapshot the hook
        already holds — never a fresh registry scan per record."""
        v = self._rounds.get(node)
        return int(v) - 1 if v else None

    def _emit(self, rule: str, verdict: str, node: str,
              round_idx: Optional[int], dedupe: Tuple, **fields) -> None:
        key = (rule,) + dedupe
        with self._lock:
            if key in self._fired:
                return
            self._fired.add(key)
        alert = {
            "ts": time.time(),
            "kind": "doctor_alert",
            "rule": rule,
            "node": node,
            "round": round_idx,
            "verdict": verdict,
            **fields,
        }
        self.alerts.append(alert)
        get_registry().counter("live/alerts", labels={"rule": rule}).inc()
        flight_recorder.record("doctor_alert", rule=rule, node=node,
                               round=round_idx, verdict=verdict)
        # alert-triggered deep trace: straggler / memory-slope / serving-
        # stall alerts request ONE bounded capture for the next round on
        # the implicated (in-process) node — the TraceController dedupes
        # per rule per run and enforces the count/byte budget, so a
        # second alert on the same rule never re-captures
        from fedml_tpu.telemetry.profiling import (
            AUTO_CAPTURE_RULES,
            get_trace_controller,
        )

        if rule in AUTO_CAPTURE_RULES:
            get_trace_controller().request_capture(
                rule=rule, reason=verdict, node=node, round_idx=round_idx)
        run_dir = self.run_dir
        if run_dir is None:
            from fedml_tpu.telemetry.spans import get_tracer

            run_dir = get_tracer().sink_dir
        if run_dir is not None:
            try:
                os.makedirs(run_dir, exist_ok=True)
                with open(os.path.join(run_dir, "telemetry.jsonl"), "a") as f:
                    f.write(json.dumps(alert, default=str) + "\n")
            except OSError:  # pragma: no cover - sink dir gone
                pass

    # -- the hook ----------------------------------------------------------
    # the metric namespaces any rule reads: a frame carrying none of them
    # (comm counters, live/* plane health, serving wire stats...) cannot
    # change any rule's verdict, so it skips the registry snapshot + full
    # re-evaluation entirely — most steady-state frames take this exit
    _RULE_PREFIXES = ("health/", "mem/", "serving/", "resilience/", "tier/")

    def _on_frame(self, frame: Dict, collector) -> None:
        if not any(str(e.get("name", "")).startswith(self._RULE_PREFIXES)
                   for e in frame.get("metrics") or ()):
            return
        node = str(frame.get("node"))
        with self._eval_lock:
            recs = collector.snapshot()
            by_name: Dict[str, List[Dict]] = {}
            for rec in recs:
                by_name.setdefault(rec["name"], []).append(rec)
            self._rounds = self._per_node(by_name, "health/rounds_scored")
            self._check_stragglers(by_name)
            self._check_memory(by_name)
            self._check_serving(by_name)
            self._check_slo_burn(by_name)
            self._check_quorum(by_name)
            self._check_never_rejoined(by_name, node, self._round_of(node))

    # -- rules -------------------------------------------------------------
    def _check_stragglers(self, by_name: Dict[str, List[Dict]]) -> None:
        for metric, threshold, rule, text in (
                ("health/straggler_score", self.straggler_threshold,
                 "straggler", "latency {v:.1f}x the cohort median"),
                ("health/anomaly_score", self.anomaly_threshold,
                 "anomaly", "median update-norm/loss |z| {v:.1f}")):
            for rec in by_name.get(metric, ()):
                labels = rec.get("labels") or {}
                client = labels.get("client")
                node = labels.get("node", "?")
                v = float(rec.get("value") or 0.0)
                if client is None or v < threshold:
                    continue
                rnd = self._round_of(node)
                # the tracker's gauge is a median over scored rounds, but
                # a flag still needs min_rounds of evidence — mirror the
                # post-hoc doctor so the two can never disagree
                if rnd is None or rnd + 1 < self.min_rounds:
                    continue
                self._emit(
                    rule,
                    f"client {client} is a {rule}: " + text.format(v=v),
                    node, rnd, dedupe=(node, str(client)),
                    client=str(client), score=round(v, 3))

    def _check_memory(self, by_name: Dict[str, List[Dict]]) -> None:
        from fedml_tpu.telemetry.doctor import _fit_slope

        for metric in ("mem/device_bytes_in_use", "mem/live_buffer_bytes"):
            for rec in by_name.get(metric, ()):
                labels = rec.get("labels") or {}
                node = labels.get("node", "?")
                phase = labels.get("phase", "")
                rnd = self._round_of(node)
                if rnd is None:
                    continue
                v = float(rec.get("value") or 0.0)
                if v <= 0:
                    continue
                key = (node, phase, metric)
                hist = self._mem_hist.setdefault(key, [])
                if not hist or hist[-1][0] != rnd:
                    hist.append((rnd, v))
                else:
                    hist[-1] = (rnd, v)
                if len(hist) < max(3, self.min_rounds):
                    continue
                first, last = hist[0][1], hist[-1][1]
                slope = _fit_slope([float(r) for r, _ in hist],
                                   [b for _, b in hist])
                if first > 0 and slope > 0 and (
                        last / first >= self.mem_growth_threshold):
                    self._emit(
                        "memory_growth",
                        f"memory grows in phase {phase!r} on {node}: "
                        f"{first:.0f} -> {last:.0f} bytes "
                        f"({slope:.0f} B/round)",
                        node, rnd, dedupe=(node, phase, metric),
                        phase=phase, metric=metric,
                        slope_bytes_per_round=round(slope, 1))

    def _check_serving(self, by_name: Dict[str, List[Dict]]) -> None:
        published = [float(r.get("value") or 0.0)
                     for r in by_name.get("serving/round_published", ())]
        if not published:
            return
        pub = max(published)
        for rec in by_name.get("serving/round_current", ()):
            labels = rec.get("labels") or {}
            node = labels.get("node", "?")
            cur = float(rec.get("value") or 0.0)
            if pub - cur >= self.stale_round_gap:
                # re-arming falls out of the dedupe key: a healed endpoint
                # advances cur, so a NEW stall dedupes on a new (node, cur)
                self._emit(
                    "stale_serving_round",
                    f"endpoint {node} serves round {cur:.0f} while training "
                    f"published round {pub:.0f} ({pub - cur:.0f} behind)",
                    node, int(pub), dedupe=(node, int(cur)),
                    round_current=int(cur), round_published=int(pub))

    def _check_slo_burn(self, by_name: Dict[str, List[Dict]]) -> None:
        """Multi-window error-budget burn rate (SRE-style) over the
        cumulative ``serving/slo_total`` / ``serving/slo_breaches``
        counter pairs each endpoint streams, labeled by objective.

        burn = (bad_delta / total_delta) / (1 - objective) over each
        window; the alert trips only when EVERY window has both spanned
        its full width (oldest history entry old enough) and burned at
        ``slo_burn_threshold`` or above — the short window makes the
        alert fast, the long window keeps a transient blip from paging.
        """
        def keyed(metric: str) -> Dict[Tuple, float]:
            out: Dict[Tuple, float] = {}
            for rec in by_name.get(metric, ()):
                labels = rec.get("labels") or {}
                key = (labels.get("node", "?"), labels.get("endpoint", "?"),
                       labels.get("objective", "?"))
                out[key] = float(rec.get("value", rec.get("count", 0)) or 0)
            return out

        totals = keyed("serving/slo_total")
        if not totals:
            return
        bads = keyed("serving/slo_breaches")
        objectives: Dict[Tuple, float] = {}
        for rec in by_name.get("serving/slo_objective", ()):
            labels = rec.get("labels") or {}
            objectives[(labels.get("node", "?"),
                        labels.get("endpoint", "?"))] = float(
                rec.get("value") or 0.0)
        now = time.time()
        long_w = self.slo_burn_windows_s[-1]
        for key, total in totals.items():
            node, endpoint, kind = key
            bad = bads.get(key, 0.0)
            hist = self._slo_hist.setdefault(key, [])
            hist.append((now, total, bad))
            # keep exactly one entry at/past the long-window boundary so
            # the difference stays well-defined without unbounded history
            while len(hist) >= 2 and hist[1][0] <= now - long_w:
                hist.pop(0)
            objective = objectives.get((node, endpoint), 0.99)
            budget = 1.0 - objective
            if budget <= 0:
                continue
            burns = []
            for w in self.slo_burn_windows_s:
                base = None
                for ts, t, b in hist:
                    if ts <= now - w:
                        base = (t, b)
                    else:
                        break
                if base is None:
                    burns = None  # window not spanned yet — can't judge
                    break
                d_total = total - base[0]
                d_bad = bad - base[1]
                bad_frac = d_bad / d_total if d_total > 0 else 0.0
                burns.append(bad_frac / budget)
            if burns is None or min(burns) < self.slo_burn_threshold:
                continue
            rnd = self._round_of(node)
            self._emit(
                "slo_burn",
                f"{endpoint} on {node} burns its {kind} error budget at "
                f"{burns[0]:.1f}x (long window {burns[-1]:.1f}x, "
                f"objective {objective:g})",
                node, rnd, dedupe=(node, endpoint, kind),
                endpoint=endpoint, objective=kind,
                burn=round(burns[0], 2), burn_long=round(burns[-1], 2),
                budget=round(budget, 4),
                windows_s=list(self.slo_burn_windows_s))

    def _check_quorum(self, by_name: Dict[str, List[Dict]]) -> None:
        for name, recs in by_name.items():
            is_quorum = (name == "resilience/quorum_rounds"
                         or (name.startswith("tier/")
                             and name.endswith("/quorum_failures")))
            if not is_quorum:
                continue
            for rec in recs:
                labels = rec.get("labels") or {}
                node = labels.get("node", "?")
                v = float(rec.get("value") or 0.0)
                key = (node, name)
                prev = self._quorum_seen.get(key, 0.0)
                if v > prev:
                    self._quorum_seen[key] = v
                    rnd = self._round_of(node)
                    what = ("round closed on quorum after its deadline"
                            if name == "resilience/quorum_rounds"
                            else f"cohort close fell below quorum ({name})")
                    self._emit(
                        "quorum", f"{node}: {what} (total {v:.0f})",
                        node, rnd, dedupe=(node, name, int(v)),
                        counter=name, total=v)

    def _check_never_rejoined(self, by_name: Dict[str, List[Dict]],
                              node: str, round_idx: Optional[int]) -> None:
        ev = self._per_node(by_name, "resilience/clients_evicted").get(node)
        rj = self._per_node(by_name, "resilience/clients_rejoined").get(node)
        deficit = (ev or 0.0) - (rj or 0.0)
        epoch = self._evict_epoch.get(node)
        if deficit <= 0:
            self._evict_epoch.pop(node, None)
            return
        if epoch is None or epoch[0] != deficit:
            # new deficit level: start (or restart) the rejoin grace clock
            self._evict_epoch[node] = (deficit, round_idx)
            return
        start_round = epoch[1]
        if (round_idx is not None and start_round is not None
                and round_idx - start_round >= self.rejoin_grace_rounds):
            self._emit(
                "never_rejoined",
                f"{node}: {deficit:.0f} evicted client(s) have not "
                f"rejoined after {round_idx - start_round} round(s)",
                node, round_idx, dedupe=(node, deficit, start_round),
                evicted=ev, rejoined=rj)

    # -- reads -------------------------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        return list(self.alerts)
