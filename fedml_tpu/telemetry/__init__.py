"""Unified telemetry: typed metrics registry + trace-propagating spans.

Quick tour::

    from fedml_tpu import telemetry

    reg = telemetry.get_registry()
    reg.counter("broker/bytes_in").inc(1024)
    reg.histogram("serving/request_ms").observe(12.5)

    tracer = telemetry.configure(".fedml_logs/run_0")
    with tracer.span("round/0/train"):
        ...  # child spans + remote contexts stitch automatically

    print(reg.export_prometheus())

See ``docs/observability.md`` for the span taxonomy and sink layout.
"""
from fedml_tpu.telemetry.registry import (
    BYTES_BUCKETS,
    DEFAULT_BUCKETS_MS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    reset_registry,
    set_registry,
)
from fedml_tpu.telemetry.spans import (
    CTX_KEY,
    TraceContext,
    Tracer,
    activate_context,
    add_span_listener,
    configure,
    configure_from_args,
    current_context,
    deactivate_context,
    extract_context,
    flush_run,
    get_tracer,
    inject_context,
    install_jax_compile_listener,
    remove_span_listener,
    reset_tracer,
    unwrap_frame_body,
    wrap_frame_body,
)
from fedml_tpu.telemetry.report import (
    RunData,
    build_report,
    format_report,
    load_spans,
)
from fedml_tpu.telemetry import flight_recorder
from fedml_tpu.telemetry.device_stats import (
    DeviceStatsSampler,
    install_compile_cache_counters,
    memory_snapshot,
    sample_now,
)
from fedml_tpu.telemetry.doctor import build_doctor, format_doctor
from fedml_tpu.telemetry.flight_recorder import (
    FlightRecorder,
    get_flight_recorder,
    reset_flight_recorder,
)
from fedml_tpu.telemetry.health import (
    ClientHealthTracker,
    log_health_event,
    update_norm,
)
from fedml_tpu.telemetry.live import (  # noqa: E402 - after flight_recorder
    LiveCollector,
    LivePlane,
    MetricStreamer,
    MetricsScrapeServer,
    OnlineDoctor,
    reset_live_plane,
)
from fedml_tpu.telemetry.profiling import (  # noqa: E402 - after spans
    ProgramCatalog,
    TraceController,
    get_catalog,
    get_trace_controller,
    reset_catalog,
    reset_trace_controller,
    wrap_jit,
)
from fedml_tpu.telemetry.tracing import (  # noqa: E402 - after report
    AssembledTrace,
    RoundCriticalPath,
    SpanStreamer,
    TraceCollector,
    assemble_trace,
    compute_critical_path,
    compute_critical_paths,
    export_perfetto,
    summarize_critical_paths,
    write_perfetto,
)

__all__ = [
    "BYTES_BUCKETS",
    "DEFAULT_BUCKETS_MS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "reset_registry",
    "set_registry",
    "CTX_KEY",
    "TraceContext",
    "Tracer",
    "activate_context",
    "add_span_listener",
    "configure",
    "configure_from_args",
    "current_context",
    "deactivate_context",
    "extract_context",
    "flush_run",
    "get_tracer",
    "inject_context",
    "install_jax_compile_listener",
    "remove_span_listener",
    "reset_tracer",
    "unwrap_frame_body",
    "wrap_frame_body",
    "RunData",
    "build_report",
    "format_report",
    "load_spans",
    "flight_recorder",
    "FlightRecorder",
    "get_flight_recorder",
    "reset_flight_recorder",
    "DeviceStatsSampler",
    "install_compile_cache_counters",
    "memory_snapshot",
    "sample_now",
    "build_doctor",
    "format_doctor",
    "ClientHealthTracker",
    "log_health_event",
    "update_norm",
    "LiveCollector",
    "LivePlane",
    "MetricStreamer",
    "MetricsScrapeServer",
    "OnlineDoctor",
    "reset_live_plane",
    "ProgramCatalog",
    "TraceController",
    "get_catalog",
    "get_trace_controller",
    "reset_catalog",
    "reset_trace_controller",
    "wrap_jit",
    "AssembledTrace",
    "RoundCriticalPath",
    "SpanStreamer",
    "TraceCollector",
    "assemble_trace",
    "compute_critical_path",
    "compute_critical_paths",
    "export_perfetto",
    "summarize_critical_paths",
    "write_perfetto",
]
