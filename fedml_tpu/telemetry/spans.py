"""Hierarchical spans with cross-process trace propagation.

Every span carries a ``(trace_id, span_id, parent_id)`` triple. Inside one
process the current span rides a ``contextvars.ContextVar``; across
processes the context travels as a header:

- comm messages (LOCAL/GRPC/TRPC/BROKER backends): a JSON-safe
  ``telemetry_ctx`` field injected into the message params by
  ``FedMLCommManager.send_message`` and re-activated around handler
  dispatch on the receiving rank;
- raw ``PubSubBroker`` frames: a binary envelope (magic + JSON header)
  prepended to the published body by ``BrokerClient`` and stripped on the
  subscriber side, so server-side and client-side spans of the same round
  stitch into one timeline.

Span naming follows the taxonomy ``round/<n>[/client/<id>]/<phase>`` for
round work and ``<subsystem>/<what>`` elsewhere; ``tools/
check_span_names.py`` lints the instrumented literals.

JAX compile-vs-execute split: a ``jax.monitoring`` duration listener
attributes backend-compile seconds to whatever span is open when XLA
compiles, so a span's ``compile_ms`` attr separates "first round pays the
bridge" from steady-state execution.
"""
from __future__ import annotations

import atexit
import contextlib
import contextvars
import json
import os
import struct
import threading
import time
import uuid
import weakref
from typing import Any, Dict, Iterator, List, Optional

from fedml_tpu.telemetry import flight_recorder
from fedml_tpu.telemetry.registry import get_registry

CTX_KEY = "telemetry_ctx"
_FRAME_MAGIC = b"\xf5TCX"


class TraceContext:
    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_dict(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d: Dict[str, str]) -> "TraceContext":
        return cls(str(d["trace_id"]), str(d["span_id"]))

    def __repr__(self) -> str:  # pragma: no cover
        return f"TraceContext({self.trace_id}/{self.span_id})"


_current: "contextvars.ContextVar[Optional[_ActiveSpan]]" = contextvars.ContextVar(
    "fedml_telemetry_span", default=None
)


class _ActiveSpan:
    """Mutable in-flight span; becomes an immutable record at end()."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "started",
                 "started_mono", "attrs", "remote_parent", "placeholder",
                 "compile_ms")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 remote_parent: bool, attrs: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        # wall clock for human-readable placement, monotonic for durations:
        # an NTP step mid-run shifts `started` but cannot corrupt the
        # measured length of the span
        self.started = time.time()
        self.started_mono = time.perf_counter()
        self.attrs = attrs
        self.remote_parent = remote_parent
        self.placeholder = False
        self.compile_ms = 0.0

    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)


def new_trace_id() -> str:
    return uuid.uuid4().hex


def current_context() -> Optional[TraceContext]:
    span = _current.get()
    return span.context() if span is not None else None


def activate_context(ctx: Optional[TraceContext]):
    """Adopt a remote context as the current parent; returns a reset token.

    The adopted context is represented as a zero-duration placeholder so
    child spans stitch to the remote span id without recording anything.
    """
    if ctx is None:
        return None
    holder = _ActiveSpan("remote", ctx.trace_id, None, True, {})
    holder.span_id = ctx.span_id
    holder.placeholder = True
    return _current.set(holder)


def deactivate_context(token) -> None:
    if token is not None:
        _current.reset(token)


# -- header propagation (comm-message params dict) ------------------------
def inject_context(params: Dict[str, Any]) -> None:
    ctx = current_context()
    if ctx is not None:
        params[CTX_KEY] = ctx.to_dict()


def extract_context(params: Dict[str, Any]) -> Optional[TraceContext]:
    raw = params.pop(CTX_KEY, None)
    if not isinstance(raw, dict) or "trace_id" not in raw:
        return None
    try:
        return TraceContext.from_dict(raw)
    except (KeyError, TypeError):
        return None


# -- frame propagation (raw broker bodies) ---------------------------------
def wrap_frame_body(body: bytes, ctx: Optional[TraceContext] = None) -> bytes:
    """Prepend the trace header to a pub/sub body (no-op without context).

    Layout: magic ‖ u16 header_len ‖ json(ctx) ‖ body. The broker routes
    bodies opaquely (Python and native C++ alike), so the envelope is
    invisible to it and to the wire protocol.
    """
    ctx = ctx or current_context()
    if ctx is None:
        return body
    header = json.dumps(ctx.to_dict()).encode()
    return _FRAME_MAGIC + struct.pack(">H", len(header)) + header + body


def unwrap_frame_body(body: bytes):
    """Split (ctx | None, original_body); bodies without the magic — or
    that merely start with the magic bytes by accident — pass through
    untouched, so un-instrumented publishers stay compatible."""
    if not body.startswith(_FRAME_MAGIC) or len(body) < 6:
        return None, body
    (hlen,) = struct.unpack(">H", body[4:6])
    if len(body) < 6 + hlen:
        return None, body
    try:
        ctx = TraceContext.from_dict(json.loads(body[6 : 6 + hlen]))
    except (ValueError, KeyError, UnicodeDecodeError):
        return None, body
    return ctx, body[6 + hlen :]


# -- jax compile attribution ----------------------------------------------
_jax_listener_installed = False
_jax_listener_lock = threading.Lock()


def install_jax_compile_listener() -> None:
    """Attribute XLA backend-compile time to the currently open span.

    Installed once per process, lazily on first Tracer construction; the
    listener is a few ns when no compile happens and writes into both the
    active span (``compile_ms`` attr) and the global ``jax/compile_ms``
    histogram.
    """
    global _jax_listener_installed
    with _jax_listener_lock:
        if _jax_listener_installed:
            return
        try:
            import jax.monitoring
        except ImportError:  # pragma: no cover - jax is a hard dep in-tree
            return

        def _on_duration(event: str, duration_secs: float, **kw) -> None:
            if "backend_compile" not in event:
                return
            ms = duration_secs * 1e3
            get_registry().histogram("jax/compile_ms").observe(ms)
            span = _current.get()
            if span is not None:
                span.compile_ms += ms

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _jax_listener_installed = True


# one atexit hook over weak refs: tracers stay collectable, and the exit
# flush covers however many instances are still alive
_live_tracers: "weakref.WeakSet[Tracer]" = weakref.WeakSet()


def _flush_live_tracers() -> None:
    for t in list(_live_tracers):
        try:
            t.flush()
        except OSError:  # pragma: no cover - sink dir gone at exit
            pass


atexit.register(_flush_live_tracers)


# -- span listeners --------------------------------------------------------
# Process-global observers of completed span/event records — the live
# tracing plane (SpanStreamer) taps here so remote nodes can ship their
# spans without the tracer knowing anything about transports. Listener
# exceptions are swallowed: observability must never break the traced code.
_span_listeners: List[Any] = []
_span_listeners_lock = threading.Lock()


def add_span_listener(fn) -> None:
    """Register ``fn(record: dict)`` to observe every completed span and
    every point event recorded by any tracer in this process."""
    with _span_listeners_lock:
        if fn not in _span_listeners:
            _span_listeners.append(fn)


def remove_span_listener(fn) -> None:
    with _span_listeners_lock:
        try:
            _span_listeners.remove(fn)
        except ValueError:
            pass


def _notify_span_listeners(rec: Dict) -> None:
    with _span_listeners_lock:
        listeners = list(_span_listeners)
    for fn in listeners:
        try:
            fn(rec)
        except Exception:  # noqa: BLE001 - listeners must never raise out
            pass


class Tracer:
    """Span factory + buffered JSONL sink.

    Completed spans buffer in memory and flush to ``<sink_dir>/<filename>``
    when the buffer passes ``buffer_limit``, on ``flush()``, and at
    interpreter exit — a crash loses at most one buffer, not the run.
    """

    def __init__(self, sink_dir: Optional[str] = None,
                 filename: str = "spans.jsonl", buffer_limit: int = 256,
                 service: str = ""):
        self._dir = sink_dir
        self._filename = filename
        self._limit = max(int(buffer_limit), 1)
        self.service = service
        self._lock = threading.Lock()
        self._records: List[Dict] = []
        install_jax_compile_listener()
        _live_tracers.add(self)

    @property
    def sink_dir(self) -> Optional[str]:
        return self._dir

    # -- span lifecycle ---------------------------------------------------
    def begin(self, name: str, **attrs: Any) -> _ActiveSpan:
        parent = _current.get()
        if parent is not None:
            # only the DIRECT child of an adopted remote context is marked
            # stitched; its own descendants are ordinary local spans
            span = _ActiveSpan(name, parent.trace_id, parent.span_id,
                               parent.placeholder, attrs)
        else:
            span = _ActiveSpan(name, new_trace_id(), None, False, attrs)
        return span

    def end(self, span: _ActiveSpan, ended: Optional[float] = None) -> Dict:
        if ended is None:
            # duration from the monotonic clock; `ended` derived so the
            # ended - started == duration invariant survives for readers
            duration_ms = (time.perf_counter() - span.started_mono) * 1e3
            ended = span.started + duration_ms / 1e3
        else:
            # explicit end times are wall-clock by contract (backfill,
            # tests) — keep the historical wall math for them
            duration_ms = (ended - span.started) * 1e3
        rec = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "started": span.started,
            "mono": span.started_mono,
            "ended": ended,
            "duration_ms": duration_ms,
        }
        if span.compile_ms:
            rec["compile_ms"] = span.compile_ms
            rec["execute_ms"] = max(rec["duration_ms"] - span.compile_ms, 0.0)
        if span.remote_parent:
            rec["remote_parent"] = True
        if self.service:
            rec["service"] = self.service
        if span.attrs:
            rec["attrs"] = span.attrs
        overflow = None
        with self._lock:
            self._records.append(rec)
            if len(self._records) >= self._limit:
                overflow = self._records
                self._records = []
        if overflow is not None:
            self._write(overflow)
        # a condensed copy rides the flight-recorder ring so a crash dump
        # shows the last spans even when the sink buffer died with them
        flight_recorder.on_span(rec)
        _notify_span_listeners(rec)
        return rec

    def event(self, name: str, **attrs: Any) -> Dict:
        """Record a zero-duration point event at the current instant.

        Point records land in the same JSONL sink as spans but carry
        ``point: true`` and no ``duration_ms``, so ``load_spans``-based
        consumers (report phases, stragglers) skip them while the trace
        assembler can use them as precise causal markers — e.g. the
        ``comm/send``/``comm/recv`` pairs that clock alignment matches.
        """
        rec: Dict[str, Any] = {
            "name": name,
            "point": True,
            "ts": time.time(),
            "mono": time.perf_counter(),
        }
        ctx = current_context()
        if ctx is not None:
            rec["trace_id"] = ctx.trace_id
            rec["span_id"] = ctx.span_id
        if self.service:
            rec["service"] = self.service
        if attrs:
            rec["attrs"] = attrs
        overflow = None
        with self._lock:
            self._records.append(rec)
            if len(self._records) >= self._limit:
                overflow = self._records
                self._records = []
        if overflow is not None:
            self._write(overflow)
        _notify_span_listeners(rec)
        return rec

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[_ActiveSpan]:
        s = self.begin(name, **attrs)
        token = _current.set(s)
        try:
            yield s
        finally:
            _current.reset(token)
            self.end(s)

    # -- sink -------------------------------------------------------------
    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._records)

    def _write(self, records: List[Dict]) -> Optional[str]:
        if self._dir is None or not records:
            return None
        os.makedirs(self._dir, exist_ok=True)
        path = os.path.join(self._dir, self._filename)
        with open(path, "a") as f:
            for rec in records:
                f.write(json.dumps(rec, default=str) + "\n")
        return path

    def flush(self) -> Optional[str]:
        with self._lock:
            records, self._records = self._records, []
        return self._write(records)


_default_tracer: Optional[Tracer] = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer (memory-only until configure() points it
    at a run dir)."""
    global _default_tracer
    with _default_lock:
        if _default_tracer is None:
            _default_tracer = Tracer()
        return _default_tracer


def configure(run_dir: str, service: str = "") -> Tracer:
    """Bind the global tracer to a run dir (idempotent per dir). Also
    points the flight recorder's crash dump at the same dir, so every
    engine that lands spans gets the black box for free."""
    global _default_tracer
    with _default_lock:
        t = _default_tracer
        if t is None or t._dir != run_dir:
            t = Tracer(sink_dir=run_dir, service=service)
            _default_tracer = t
    flight_recorder.bind(run_dir)
    return t


def configure_from_args(args: Any, service: str = "") -> Tracer:
    """Derive the sink dir from run args — same layout core/mlops uses:
    ``<log_file_dir>/run_<run_id>/``. Also applies the run's deep-trace
    budget knobs (``trace_max_captures`` / ``trace_byte_budget`` /
    ``trace_rounds``) to the process TraceController. ``service`` stamps
    this process's records with its node identity, which is what lets
    trace assembly tell nodes apart in a shared run dir."""
    run_id = str(getattr(args, "run_id", "0") or "0")
    base = str(getattr(args, "log_file_dir", "") or ".fedml_logs")
    tracer = configure(os.path.join(base, f"run_{run_id}"), service=service)
    from fedml_tpu.telemetry.profiling import trace as _trace

    _trace.configure_from_args(args)
    return tracer


def flush_run() -> Optional[str]:
    """Land the global tracer's spans, a registry snapshot, AND the
    program-catalog snapshot (``programs.jsonl``) in the run dir (no-op
    for an unconfigured, memory-only tracer). The one call a training
    loop needs at the end of ``train()``."""
    from fedml_tpu.telemetry.registry import get_registry as _reg

    tracer = get_tracer()
    tracer.flush()
    if tracer.sink_dir is None:
        return None
    from fedml_tpu.telemetry.profiling import get_catalog

    get_catalog().flush_jsonl(tracer.sink_dir)
    return _reg().flush_jsonl(tracer.sink_dir)


def reset_tracer() -> None:
    """Drop the global tracer (test isolation)."""
    global _default_tracer
    with _default_lock:
        _default_tracer = None
