"""Artifact storage — the ``fedml storage`` surface, TPU-repo edition.

Parity target: ``python/fedml/cli/modules/storage.py`` +
``python/fedml/api/__init__.py:181-204`` — upload / download / list /
delete / get-metadata of named data artifacts. The reference routes
these through the hosted Nexus backend (R2 object storage + a cloud
metadata DB); here the same verbs run over the in-tree object-store
seam with no hosted service:

- ``local`` (default) — content-addressed store on disk
  (:class:`LocalCASObjectStore`), root at ``$FEDML_TPU_STORAGE_DIR`` or
  ``~/.fedml_tpu/storage``;
- ``s3`` — real S3 REST + SigV4 (:class:`S3ObjectStore`), endpoint and
  credentials from env/kwargs;
- ``web3`` / ``theta`` — decentralized pinning services
  (:class:`Web3ObjectStore` / :class:`ThetaObjectStore`).

The name→handle index the reference keeps in its cloud DB lives in a
local JSON file per service (``<root>/index/<service>.json``): object
*bytes* go to the selected backend, the *catalog* stays with the user.
Directories are uploaded as tar.gz archives and unpacked on download.
"""
from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
import tarfile
import time
from typing import Any, Dict, List, Optional

__all__ = ["StorageMetadata", "StorageManager"]


def _default_root() -> str:
    return os.environ.get(
        "FEDML_TPU_STORAGE_DIR",
        os.path.join(os.path.expanduser("~"), ".fedml_tpu", "storage"),
    )


@dataclasses.dataclass
class StorageMetadata:
    """One stored artifact (reference: ``StorageMetadata`` rows shown by
    ``fedml storage list``: dataName/description/createdAt/updatedAt)."""

    name: str
    handle: str                 # backend handle: CID (CAS) or object key
    service: str
    size_bytes: int
    sha256: str
    is_dir: bool
    created_at: str
    updated_at: str
    description: str = ""
    user_metadata: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StorageMetadata":
        return cls(**{f.name: d.get(f.name) for f in dataclasses.fields(cls)})


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime())


def _make_store(service: str, **kw):
    service = (service or "local").lower()
    if service == "local":
        from fedml_tpu.core.distributed.communication.decentralized_storage import (
            LocalCASObjectStore,
        )

        return LocalCASObjectStore(
            root=kw.get("root") or os.path.join(_default_root(), "cas"),
            secret_key=kw.get("secret_key"),
        )
    if service == "s3":
        from fedml_tpu.core.distributed.communication.s3_store import S3ObjectStore

        missing = [k for k in ("endpoint", "bucket")
                   if not (kw.get(k) or os.environ.get(f"FEDML_S3_{k.upper()}"))]
        if missing:
            raise ValueError(
                f"s3 storage needs {missing} (kwargs or FEDML_S3_* env); "
                "credentials come from AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY")
        return S3ObjectStore(
            endpoint=kw.get("endpoint") or os.environ["FEDML_S3_ENDPOINT"],
            bucket=kw.get("bucket") or os.environ["FEDML_S3_BUCKET"],
            region=kw.get("region") or os.environ.get("FEDML_S3_REGION",
                                                      "us-east-1"),
            access_key=kw.get("access_key"),
            # for s3, secret_key is the AWS secret (this backend does no
            # payload sealing); falls back to AWS_SECRET_ACCESS_KEY env
            secret_key=kw.get("secret_key"),
        )
    if service == "web3":
        from fedml_tpu.core.distributed.communication.decentralized_storage import (
            Web3ObjectStore,
        )

        return Web3ObjectStore(
            upload_uri=kw.get("upload_uri") or os.environ["FEDML_WEB3_UPLOAD_URI"],
            download_uri=kw.get("download_uri")
            or os.environ["FEDML_WEB3_DOWNLOAD_URI"],
            api_token=kw.get("api_token") or os.environ.get("FEDML_WEB3_TOKEN"),
            secret_key=kw.get("secret_key"),
        )
    if service == "theta":
        from fedml_tpu.core.distributed.communication.decentralized_storage import (
            ThetaObjectStore,
        )

        return ThetaObjectStore(
            rpc_uri=kw.get("rpc_uri") or os.environ["FEDML_THETA_RPC_URI"],
            secret_key=kw.get("secret_key"),
        )
    raise ValueError(f"unknown storage service {service!r} "
                     "(expected local|s3|web3|theta)")


class StorageManager:
    """Named-artifact catalog over a pluggable object store."""

    def __init__(self, service: str = "local",
                 index_dir: Optional[str] = None, **backend_kw):
        self.service = (service or "local").lower()
        if self.service not in ("local", "s3", "web3", "theta"):
            raise ValueError(f"unknown storage service {self.service!r} "
                             "(expected local|s3|web3|theta)")
        self._backend_kw = backend_kw
        self._store = None
        self._index_path = os.path.join(
            index_dir or os.path.join(_default_root(), "index"),
            f"{self.service}.json",
        )

    @property
    def store(self):
        """Backend built lazily: list/metadata only read the local index
        and must work without s3/web3/theta env config."""
        if self._store is None:
            self._store = _make_store(self.service, **self._backend_kw)
        return self._store

    # -- index persistence -------------------------------------------------
    def _load_index(self) -> Dict[str, Dict]:
        try:
            with open(self._index_path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _save_index(self, idx: Dict[str, Dict]) -> None:
        os.makedirs(os.path.dirname(self._index_path), exist_ok=True)
        tmp = self._index_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(idx, f, indent=1, sort_keys=True)
        os.replace(tmp, self._index_path)

    # -- verbs -------------------------------------------------------------
    def upload(self, data_path: str, name: Optional[str] = None,
               description: str = "",
               metadata: Optional[Dict[str, Any]] = None) -> StorageMetadata:
        """Store a file or directory under ``name`` (defaults to its
        basename). Directories ship as in-memory tar.gz archives."""
        data_path = os.path.expanduser(data_path)
        if not os.path.exists(data_path):
            raise FileNotFoundError(data_path)
        name = name or os.path.basename(os.path.normpath(data_path))
        is_dir = os.path.isdir(data_path)
        if is_dir:
            buf = io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w:gz") as tar:
                tar.add(data_path, arcname=".")
            data = buf.getvalue()
        else:
            with open(data_path, "rb") as f:
                data = f.read()
        handle = self.store.put_object(f"storage/{name}", data)
        idx = self._load_index()
        prev = idx.get(name)
        meta = StorageMetadata(
            name=name, handle=handle, service=self.service,
            size_bytes=len(data), sha256=hashlib.sha256(data).hexdigest(),
            is_dir=is_dir,
            created_at=prev["created_at"] if prev else _now(),
            updated_at=_now(), description=description,
            user_metadata=metadata,
        )
        idx[name] = meta.to_dict()
        self._save_index(idx)
        if prev and prev["handle"] != handle:
            # don't leak the superseded blob (content-addressed stores give
            # new content a new handle) — unless another entry shares it
            self._unpin_if_unreferenced(idx, prev["handle"])
        return meta

    def _unpin_if_unreferenced(self, idx: Dict[str, Dict],
                               handle: str) -> None:
        if any(e["handle"] == handle for e in idx.values()):
            return  # CAS dedup: identical content shares one blob
        try:
            self.store.delete_object(handle)
        except Exception:  # unpin is best-effort on pinning services
            pass

    def get_metadata(self, name: str) -> StorageMetadata:
        idx = self._load_index()
        if name not in idx:
            raise KeyError(f"no stored artifact named {name!r}")
        return StorageMetadata.from_dict(idx[name])

    def list(self) -> List[StorageMetadata]:
        return [StorageMetadata.from_dict(d)
                for _, d in sorted(self._load_index().items())]

    def download(self, name: str, dest: Optional[str] = None) -> str:
        """Fetch an artifact to ``dest`` (default: ./<name>); returns the
        written path. Integrity-checked against the recorded sha256."""
        meta = self.get_metadata(name)
        data = self.store.get_object(meta.handle)
        if hashlib.sha256(data).hexdigest() != meta.sha256:
            raise IOError(
                f"artifact {name!r}: downloaded bytes fail the recorded "
                f"sha256 — store corrupted or tampered")
        dest = os.path.expanduser(dest or os.path.join(".", name))
        if meta.is_dir:
            os.makedirs(dest, exist_ok=True)
            with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
                try:
                    tar.extractall(dest, filter="data")
                except TypeError:
                    # filter= appeared mid-3.10/3.11; the archive is one
                    # we wrote ourselves and its bytes just passed the
                    # sha256 check, so plain extraction is acceptable
                    tar.extractall(dest)
        else:
            parent = os.path.dirname(os.path.abspath(dest))
            os.makedirs(parent, exist_ok=True)
            with open(dest, "wb") as f:
                f.write(data)
        return dest

    def delete(self, name: str) -> bool:
        idx = self._load_index()
        entry = idx.pop(name, None)
        if entry is None:
            return False
        self._save_index(idx)
        self._unpin_if_unreferenced(idx, entry["handle"])
        return True
