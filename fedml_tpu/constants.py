"""Framework-wide constants.

Mirrors the surface of the reference's ``python/fedml/constants.py:1-83``
(training types, backends, optimizer names) with TPU-native additions: the
``xla_ici`` comm backend and the parallel (mesh) simulation backend.
"""

# ---- training types (engine selector) --------------------------------------
FEDML_TRAINING_PLATFORM_SIMULATION = "simulation"
FEDML_TRAINING_PLATFORM_CROSS_SILO = "cross_silo"
FEDML_TRAINING_PLATFORM_CROSS_DEVICE = "cross_device"
FEDML_TRAINING_PLATFORM_CROSS_CLOUD = "cross_cloud"
FEDML_TRAINING_PLATFORM_SERVING = "serving"

# ---- simulation backends ----------------------------------------------------
FEDML_SIMULATION_TYPE_SP = "sp"  # single process, host round loop
# TPU-native replacement of the reference's NCCL backend
# (python/fedml/simulation/nccl/): clients ride a jax.sharding.Mesh axis.
FEDML_SIMULATION_TYPE_MESH = "mesh"
FEDML_SIMULATION_TYPE_NCCL = "NCCL"  # alias accepted, runs the mesh backend
FEDML_SIMULATION_TYPE_MPI = "MPI"  # alias accepted, runs the mesh backend

# ---- cross-silo scenarios ---------------------------------------------------
CROSS_SILO_SCENARIO_HORIZONTAL = "horizontal"
CROSS_SILO_SCENARIO_HIERARCHICAL = "hierarchical"

# ---- communication backends -------------------------------------------------
COMM_BACKEND_LOCAL = "LOCAL"      # deterministic in-process (tests, SP)
COMM_BACKEND_GRPC = "GRPC"
COMM_BACKEND_XLA_ICI = "XLA_ICI"  # intra-pod ranks == mesh axes, XLA collectives
COMM_BACKEND_MQTT_S3 = "MQTT_S3"  # gated: requires paho-mqtt + boto3
COMM_BACKEND_BROKER = "BROKER"    # in-tree pub/sub broker + object store
COMM_BACKEND_TRPC = "TRPC"        # torch.distributed.rpc (TensorPipe)
                                  # (the MQTT+S3 deployment shape, no deps)

# ---- federated optimizers ---------------------------------------------------
# Parity with the reference list (python/fedml/constants.py:40-63).
FEDML_FEDERATED_OPTIMIZER_FEDAVG = "FedAvg"
FEDML_FEDERATED_OPTIMIZER_FEDAVG_SEQ = "FedAvg_seq"
FEDML_FEDERATED_OPTIMIZER_FEDOPT = "FedOpt"
FEDML_FEDERATED_OPTIMIZER_FEDOPT_SEQ = "FedOpt_seq"
FEDML_FEDERATED_OPTIMIZER_FEDPROX = "FedProx"
FEDML_FEDERATED_OPTIMIZER_FEDNOVA = "FedNova"
FEDML_FEDERATED_OPTIMIZER_FEDDYN = "FedDyn"
FEDML_FEDERATED_OPTIMIZER_SCAFFOLD = "SCAFFOLD"
FEDML_FEDERATED_OPTIMIZER_MIME = "Mime"
FEDML_FEDERATED_OPTIMIZER_FEDSGD = "FedSGD"
FEDML_FEDERATED_OPTIMIZER_ASYNC_FEDAVG = "Async_FedAvg"
FEDML_FEDERATED_OPTIMIZER_FEDGAN = "FedGAN"
FEDML_FEDERATED_OPTIMIZER_HIERARCHICAL_FL = "HierarchicalFL"
FEDML_FEDERATED_OPTIMIZER_TURBO_AGGREGATE = "TurboAggregate"
FEDML_FEDERATED_OPTIMIZER_VERTICAL_FL = "VerticalFL"
FEDML_FEDERATED_OPTIMIZER_SPLIT_NN = "SplitNN"
FEDML_FEDERATED_OPTIMIZER_DECENTRALIZED_FL = "DecentralizedFL"

SUPPORTED_FEDERATED_OPTIMIZERS = [
    FEDML_FEDERATED_OPTIMIZER_FEDAVG,
    FEDML_FEDERATED_OPTIMIZER_FEDAVG_SEQ,
    FEDML_FEDERATED_OPTIMIZER_FEDOPT,
    FEDML_FEDERATED_OPTIMIZER_FEDOPT_SEQ,
    FEDML_FEDERATED_OPTIMIZER_FEDPROX,
    FEDML_FEDERATED_OPTIMIZER_FEDNOVA,
    FEDML_FEDERATED_OPTIMIZER_FEDDYN,
    FEDML_FEDERATED_OPTIMIZER_SCAFFOLD,
    FEDML_FEDERATED_OPTIMIZER_MIME,
    FEDML_FEDERATED_OPTIMIZER_FEDSGD,
    FEDML_FEDERATED_OPTIMIZER_ASYNC_FEDAVG,
    FEDML_FEDERATED_OPTIMIZER_HIERARCHICAL_FL,
    FEDML_FEDERATED_OPTIMIZER_TURBO_AGGREGATE,
    FEDML_FEDERATED_OPTIMIZER_VERTICAL_FL,
    FEDML_FEDERATED_OPTIMIZER_SPLIT_NN,
    FEDML_FEDERATED_OPTIMIZER_DECENTRALIZED_FL,
]

# ---- roles ------------------------------------------------------------------
ROLE_CLIENT = "client"
ROLE_SERVER = "server"

# ---- misc -------------------------------------------------------------------
FEDML_CROSS_SILO_CUSTOMIZED_HIERARCHICAL_KEY = "customized_hierarchical"
DEFAULT_SERVER_RANK = 0
