"""Run supervision policy — restart backoff + crash-loop containment.

The job plane's core judgment call lives here so every supervisor in the
repo makes it the same way: the :class:`~fedml_tpu.scheduler.agent.LocalAgent`
relaunching a dead run, and the kill-the-server recovery runner
(:mod:`fedml_tpu.resilience.durability.recover`) re-arming a crashed
federation server, both ask one :class:`RestartTracker` what to do with
an exit code.

Policy semantics:

* **restart** — any abnormal exit (nonzero rc, signal death) relaunches
  after an exponential backoff ``backoff_s * 2^k`` capped at
  ``max_backoff_s``. The schedule is deliberately UN-jittered: two
  supervisors with the same policy produce bit-identical delay
  sequences, which is what the crash-loop determinism test pins.
* **crash-loop containment** — ``crash_loop_threshold`` *consecutive*
  failures that are both *fast* (the process lived less than
  ``fast_fail_s``) and *identical* (same rc) stop the relaunching: the
  run is FAILED with a doctor-visible reason instead of flapping
  forever. A slow failure or a different rc resets the streak — that is
  a run making (different) progress, not a config error in a loop.
* **give-up** — ``max_restarts`` total relaunches bound the budget even
  for slow/varied failures.
* **resume** — durable jobs relaunch with ``FEDML_RESUME=1`` exported,
  so a federation server re-enters via the PR 12 write-ahead journal
  (mid-round, uploads salvaged) rather than from round 0.
"""
from __future__ import annotations

import json
import logging
import os
import signal
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = ["RestartPolicy", "RestartTracker", "describe_rc",
           "sched_event", "peak_hbm_from_programs"]


def describe_rc(rc: Optional[int]) -> str:
    """Human-readable exit code (``rc=-15 (SIGTERM)`` / ``rc=7``)."""
    if rc is None:
        return "rc=unknown"
    if rc < 0:
        try:
            name = signal.Signals(-rc).name
        except ValueError:
            name = f"signal {-rc}"
        return f"rc={rc} ({name})"
    return f"rc={rc}"


class RestartPolicy:
    """The per-run supervision knobs (job yaml ``restart:`` block)."""

    def __init__(self, max_restarts: int = 0, backoff_s: float = 0.5,
                 max_backoff_s: float = 30.0, crash_loop_threshold: int = 3,
                 fast_fail_s: float = 5.0, resume: bool = True):
        self.max_restarts = max(0, int(max_restarts))
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.crash_loop_threshold = max(1, int(crash_loop_threshold))
        self.fast_fail_s = float(fast_fail_s)
        self.resume = bool(resume)

    @classmethod
    def from_spec(cls, raw: Any) -> Optional["RestartPolicy"]:
        """``None`` (no supervision) unless the spec asks for it.
        Accepts a dict, a JSON string, or a bare int (= max_restarts)."""
        if raw in (None, "", False, 0):
            return None
        if isinstance(raw, str):
            raw = json.loads(raw)
        if isinstance(raw, bool):
            raw = {"max_restarts": 3}
        if isinstance(raw, int):
            raw = {"max_restarts": raw}
        if not isinstance(raw, dict):
            raise ValueError(
                f"restart policy must be a dict/int/bool, got {type(raw).__name__}")
        allowed = {"max_restarts", "backoff_s", "max_backoff_s",
                   "crash_loop_threshold", "fast_fail_s", "resume"}
        bad = set(raw) - allowed
        if bad:
            raise ValueError(f"unknown restart policy keys: {sorted(bad)}")
        pol = cls(**raw)
        return pol if pol.max_restarts > 0 else None

    def to_dict(self) -> Dict:
        return {"max_restarts": self.max_restarts,
                "backoff_s": self.backoff_s,
                "max_backoff_s": self.max_backoff_s,
                "crash_loop_threshold": self.crash_loop_threshold,
                "fast_fail_s": self.fast_fail_s,
                "resume": self.resume}


class RestartTracker:
    """One run's supervision state; ask :meth:`on_exit` after each death.

    Not thread-safe by itself — callers serialize (the agent's monitor
    loop is the only writer per record; the recovery supervisor is
    single-threaded).
    """

    def __init__(self, policy: RestartPolicy):
        self.policy = policy
        self.restarts = 0            # relaunches performed
        self.fast_streak = 0         # consecutive fast identical failures
        self.last_rc: Optional[int] = None
        self.delays_s: List[float] = []  # the backoff schedule actually used

    def on_exit(self, rc: Optional[int], uptime_s: float
                ) -> Tuple[str, Any]:
        """Judge one abnormal exit.

        Returns ``("restart", delay_s)``, ``("crash_loop", reason)`` or
        ``("give_up", reason)``. Callers only consult this for abnormal
        exits (rc != 0); a clean exit is FINISHED, not a supervision
        decision.
        """
        fast = uptime_s < self.policy.fast_fail_s
        if fast and rc == self.last_rc:
            self.fast_streak += 1
        else:
            self.fast_streak = 1 if fast else 0
        self.last_rc = rc
        if self.fast_streak >= self.policy.crash_loop_threshold:
            return ("crash_loop",
                    f"crash-loop contained: {self.fast_streak} consecutive "
                    f"fast (<{self.policy.fast_fail_s:g}s) identical "
                    f"failures ({describe_rc(rc)}) after backoff "
                    f"{[round(d, 3) for d in self.delays_s]}")
        if self.restarts >= self.policy.max_restarts:
            return ("give_up",
                    f"restart budget exhausted: {self.restarts} relaunch(es) "
                    f"already spent, last exit {describe_rc(rc)}")
        delay = min(self.policy.backoff_s * (2.0 ** self.restarts),
                    self.policy.max_backoff_s)
        self.restarts += 1
        self.delays_s.append(delay)
        return ("restart", delay)


def sched_event(event: str, **fields: Any) -> None:
    """Land one job-plane event everywhere the doctor looks (mirror of
    the secagg protocol's event helper): ``health.jsonl`` + the flight
    recorder, both best-effort."""
    from fedml_tpu.telemetry import flight_recorder
    from fedml_tpu.telemetry.health import log_health_event

    try:
        log_health_event({"kind": "sched_event", "event": event, **fields})
    except Exception:  # pragma: no cover - observability must not kill
        logger.exception("sched event logging failed")
    flight_recorder.record("sched_event", event=event, **fields)


def peak_hbm_from_programs(run_dir: str) -> Optional[float]:
    """Max ``peak_hbm_bytes`` over a run's PR 10 program catalog
    (``programs.jsonl``) — the admission figure a master gates
    rescheduling on. None when the file is missing/empty/unreadable
    (admission then treats the job's demand as unknown)."""
    path = (run_dir if run_dir.endswith(".jsonl")
            else os.path.join(run_dir, "programs.jsonl"))
    try:
        peak = 0.0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                peak = max(peak, float(rec.get("peak_hbm_bytes", 0) or 0))
        return peak or None
    except (OSError, ValueError):
        return None
