"""JobMonitor — the periodic liveness sweeper, as a process singleton.

Parity target: ``computing/scheduler/comm_utils/job_monitor.py:37`` —
the reference's ``JobMonitor`` singleton whose timer loop
(``monitor_slave_run_process_status`` :63) sweeps run processes whose
pids died without reporting, resets their status, and checks deployed
endpoint containers' liveness (``:230``), re-marking dead replicas so
the gateway stops routing to them.

This build sweeps two planes with one loop:
  * runs — RUNNING rows in the ComputeStore whose pid is gone become
    FAILED (status reconciliation the agents can't do if they crashed
    with the run);
  * endpoints — DEPLOYED replicas in the deploy EndpointCache whose
    ``/ready`` probe fails become OFFLINE (and flip back to DEPLOYED
    when the probe recovers — self-healing, which the reference's
    monitor does by restarting containers).
"""
from __future__ import annotations

import logging
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from fedml_tpu.core.mlops.status import RunStatus
from fedml_tpu.deploy.cache import EndpointCache, EndpointStatus
from fedml_tpu.scheduler.agent import _pid_alive
from fedml_tpu.scheduler.compute_store import ComputeStore

logger = logging.getLogger(__name__)

_singleton_lock = threading.Lock()
_singleton: Optional["JobMonitor"] = None


def _pid_reused(pid: int, run_started_at) -> bool:
    """True when the live pid demonstrably belongs to a *newer* process
    than the run row — i.e. the run died and the kernel recycled its pid.
    /proc-only (Linux); anywhere it can't be read, assume not reused."""
    if not run_started_at:
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
        # field 22 (1-based) = starttime in ticks since boot; fields can
        # contain spaces only inside the comm "(...)" — split after it
        starttime_ticks = int(stat.rsplit(")", 1)[1].split()[19])
        with open("/proc/uptime") as f:
            uptime_s = float(f.read().split()[0])
        hz = os.sysconf("SC_CLK_TCK")
        proc_started_at = time.time() - uptime_s + starttime_ticks / hz
        # Generous 120s slack: proc_started_at is derived from the current
        # wall clock, so an NTP step/VM-pause between the run being stamped
        # and this sweep shifts the comparison — a tight slack would FAIL
        # live runs on a clock jump. Real pid recycling is visible at any
        # slack once the row outlives it (rows live minutes-to-hours).
        return proc_started_at > float(run_started_at) + 120.0
    except (OSError, ValueError, IndexError):
        return False


def _probe_ready(url: str, timeout: float) -> bool:
    try:
        with urllib.request.urlopen(f"{url.rstrip('/')}/ready",
                                    timeout=timeout) as resp:
            return resp.status == 200
    except (urllib.error.URLError, OSError, ValueError):
        return False


class JobMonitor:
    """Sweeps run + endpoint liveness; use ``JobMonitor.get_instance()``."""

    def __init__(self, compute_store: Optional[ComputeStore] = None,
                 endpoint_cache: Optional[EndpointCache] = None,
                 interval_s: float = 5.0, probe_timeout_s: float = 2.0,
                 node_id: Optional[str] = None, live: Optional[Any] = None):
        self.compute_store = compute_store
        self.endpoint_cache = endpoint_cache
        self.interval_s = interval_s
        self.probe_timeout_s = probe_timeout_s
        # live telemetry plane (optional LivePlane): each sweep loops the
        # scheduler/* gauges into the collector, so a multi-tenant job
        # plane's packing signals are scrapeable while jobs run
        self.live = live
        # Pid liveness is only meaningful on the node that spawned the
        # run. With a shared store (NFS workdir, multi-node sqlite) a
        # monitor must never judge another node's rows: host A would mark
        # host B's live runs FAILED. None = single-node store, sweep all.
        self.node_id = node_id
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.sweeps = 0
        # sweep_once() is public API and the loop-thread body: serialize
        # whole sweeps so a caller-driven sweep racing the timer can't
        # double-probe endpoints or lose a `sweeps` increment
        self._sweep_lock = threading.Lock()
        # job/endpoint health rides the telemetry registry (not private
        # attrs), so `telemetry report` and the Prometheus exposition see
        # the scheduler plane without polling this object
        from fedml_tpu.telemetry import get_registry

        reg = get_registry()
        self._m_sweeps = reg.counter("scheduler/sweeps")
        self._m_runs_fixed = reg.counter("scheduler/runs_fixed")
        self._m_endpoint_flips = reg.counter("scheduler/endpoint_flips")
        self._g_runs_running = reg.gauge("scheduler/runs_running")
        self._g_endpoints_offline = reg.gauge("scheduler/endpoints_offline")
        # job-plane visibility: a RESTARTING row is a run in supervision
        # backoff — its pid is legitimately dead, which is exactly why the
        # pid sweep only judges RUNNING rows (the agent owns the relaunch)
        self._g_runs_restarting = reg.gauge("sched/runs_restarting")

    # -- singleton (reference keeps one monitor per agent process) -----
    @classmethod
    def get_instance(cls, **kwargs) -> "JobMonitor":
        global _singleton
        with _singleton_lock:
            if _singleton is None:
                _singleton = cls(**kwargs)
            return _singleton

    @classmethod
    def reset_instance(cls) -> None:
        global _singleton
        with _singleton_lock:
            if _singleton is not None:
                _singleton.stop()
            _singleton = None

    # -- sweeps --------------------------------------------------------
    def sweep_runs(self) -> List[str]:
        """RUNNING rows whose pid died → FAILED. Returns fixed run ids."""
        if self.compute_store is None:
            return []
        fixed = []
        for row in self.compute_store.runs(status=RunStatus.RUNNING):
            if self.node_id is not None and row.get("node_id") not in (
                    "", None, self.node_id):
                continue
            pid = row.get("pid")
            if pid and (not _pid_alive(int(pid))
                        or _pid_reused(int(pid), row.get("started_at"))):
                self.compute_store.finish_run(
                    row["run_id"], RunStatus.FAILED, returncode=None)
                fixed.append(row["run_id"])
                logger.warning("job_monitor: run %s pid %s died; -> FAILED",
                               row["run_id"], pid)
        return fixed

    def sweep_endpoints(self) -> Dict[str, Dict[str, str]]:
        """Probe every replica URL; flip DEPLOYED<->OFFLINE on evidence.

        Returns {endpoint_id: {worker_id: new_status}} for flips only.
        """
        if self.endpoint_cache is None:
            return {}
        flips: Dict[str, Dict[str, str]] = {}
        for ep in self.endpoint_cache.list_endpoints():
            eid = ep["endpoint_id"]
            for wid, rep in (ep.get("replicas") or {}).items():
                url, status = rep.get("url"), rep.get("status")
                if not url or status not in (EndpointStatus.DEPLOYED,
                                             EndpointStatus.OFFLINE):
                    continue
                alive = _probe_ready(url, self.probe_timeout_s)
                new = EndpointStatus.DEPLOYED if alive else EndpointStatus.OFFLINE
                if new != status:
                    self.endpoint_cache.set_replica(
                        eid, wid, url=url, status=new)
                    flips.setdefault(eid, {})[wid] = new
                    logger.warning("job_monitor: endpoint %s replica %s %s -> %s",
                                   eid, wid, status, new)
        return flips

    def sweep_once(self) -> Dict:
        with self._sweep_lock:
            return self._sweep_once_locked()

    def _sweep_once_locked(self) -> Dict:
        result = {"runs_fixed": self.sweep_runs(),
                  "endpoint_flips": self.sweep_endpoints()}
        self.sweeps += 1
        self._m_sweeps.inc()
        if result["runs_fixed"]:
            self._m_runs_fixed.inc(len(result["runs_fixed"]))
        n_flips = sum(len(v) for v in result["endpoint_flips"].values())
        if n_flips:
            self._m_endpoint_flips.inc(n_flips)
        if self.compute_store is not None:
            self._g_runs_running.set(
                len(self.compute_store.runs(status=RunStatus.RUNNING)))
            self._g_runs_restarting.set(
                len(self.compute_store.runs(status=RunStatus.RESTARTING)))
        if self.endpoint_cache is not None:
            offline = sum(
                1
                for ep in self.endpoint_cache.list_endpoints()
                for rep in (ep.get("replicas") or {}).values()
                if rep.get("status") == EndpointStatus.OFFLINE)
            self._g_endpoints_offline.set(offline)
        if self.live is not None:
            try:
                self.live.pump()
            except Exception:  # pragma: no cover - observability only
                logger.exception("job_monitor live pump failed")
        return result

    # -- loop ----------------------------------------------------------
    def start(self) -> "JobMonitor":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="fedml-job-monitor")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sweep_once()
            except Exception:
                logger.exception("job_monitor sweep failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
