"""Local agent — spawns, supervises, preempts, and kills job subprocesses.

Parity target: the reference slave agent (``slave/client_runner.py:60`` —
``run`` :378 spawns the job process, ``callback_start_train`` :893,
``callback_stop_train`` :982; the daemon loop ``slave/client_daemon.py:34``
cleans zombies and relaunches). TPU-build re-design: one `LocalAgent`
owns a run table; each run is a subprocess started from a JobSpec
(bootstrap → job), its stdout/stderr tailed into a per-run log file, its
status tracked by the validated FSM and mirrored into the JSONL metrics
sink. A monitor thread reaps exits; `kill` terminates the whole process
group (the reference's cleanup_all_fedml_client_* equivalent).

Job-plane additions (the parts the reference's daemon loop only gestures
at):

* **supervision** — a run whose spec carries a ``restart`` policy is
  relaunched on ANY abnormal exit with exponential backoff; N fast
  identical failures trip crash-loop containment (FAILED with a
  doctor-visible reason instead of flapping). Durable jobs relaunch with
  ``FEDML_RESUME=1`` so a federation server re-enters via the write-ahead
  journal instead of round 0. ``sched/restarts`` / ``sched/crash_loops``.
* **preemption** — :meth:`preempt` is the graceful quiesce verb for
  preemptible capacity: SIGTERM to the process group, wait for the WHOLE
  group to drain within the grace window (the flight recorder's SIGTERM
  dump + the fdatasync'd journal make the kill-point safe anywhere),
  escalate to SIGKILL only past the deadline. Terminal status PREEMPTED,
  which a master treats as "reschedule me", not "I failed".
  ``sched/preemptions``.
* **re-adoption** — an agent restarted over live runs re-adopts the runs
  the store says it owns (pid still alive + the ``_pid_reused`` check)
  instead of abandoning them to the JobMonitor's FAILED sweep; each run's
  shell writes its exit code to a ``<run_id>.rc`` file so even a run that
  finished while no agent was watching lands on its true terminal status.
  ``sched/adopted``.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from fedml_tpu.core.mlops.metrics import MLOpsMetrics
from fedml_tpu.core.mlops.status import RunStatus, RunStatusMachine
from fedml_tpu.scheduler.job_yaml import JobSpec
from fedml_tpu.scheduler.supervision import (
    RestartPolicy,
    RestartTracker,
    describe_rc,
    sched_event,
)

logger = logging.getLogger(__name__)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(int(pid), 0)
        return True
    except (ProcessLookupError, PermissionError, ValueError):
        return False


def _pgid_alive(pgid: int) -> bool:
    """True while ANY member of the process group survives — the quiesce
    wait must cover the whole group, not just the shell leader (the job's
    python child keeps flushing its journal after sh dies)."""
    try:
        os.killpg(int(pgid), 0)
        return True
    except (ProcessLookupError, ValueError):
        return False
    except PermissionError:  # pragma: no cover - foreign uid member
        return True


class RunRecord:
    def __init__(self, run_id: str, spec: JobSpec, log_path: str, sink):
        self.run_id = run_id
        self.spec = spec
        self.log_path = log_path
        self.rc_path = log_path[:-4] + ".rc" if log_path.endswith(".log") \
            else log_path + ".rc"
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None  # survives across agent processes
        self.fsm = RunStatusMachine(run_id, sink=sink)
        self.returncode: Optional[int] = None
        self.started = time.time()
        self.spawned_at = self.started  # last (re)spawn, for fast-fail judge
        # supervision state
        policy = RestartPolicy.from_spec(spec.restart)
        self.tracker: Optional[RestartTracker] = (
            RestartTracker(policy) if policy else None)
        self.next_restart_at: Optional[float] = None
        self.reason = ""                # last supervision verdict
        self.extra_env: Dict[str, str] = {}
        # intent (persisted: the exit verdict must say PREEMPTED even if
        # a different agent process ends up judging it) vs in-flight
        # (process-local: this preempt() call owns the quiesce, monitor
        # hands off)
        self.preempt_requested = False
        self.preempt_inflight = False
        self.adopted = False


class LocalAgent:
    """Single-host agent daemon; the scheduler plane's execution leaf."""

    def __init__(self, workdir: str = ".fedml_runs", args: Any = None,
                 poll_interval: float = 0.2):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self._runs: Dict[str, RunRecord] = {}
        self._lock = threading.Lock()
        self._metrics = MLOpsMetrics(args, sink_dir=os.path.join(self.workdir, "mlops"))
        self._poll_interval = poll_interval
        self._monitor: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._table_path = os.path.join(self.workdir, "runs.json")
        # cross-run cache (scheduler_core parity): run history + device
        # inventory survive this agent process and are queryable by the
        # CLI / JobMonitor from other processes
        from fedml_tpu.scheduler.compute_store import ComputeStore

        self.compute_store = ComputeStore(self.workdir)
        self.node_id = getattr(args, "node_id", None) or "local"
        self._persist_lock = threading.Lock()
        from fedml_tpu.telemetry import get_registry

        reg = get_registry()
        self._m_restarts = reg.counter("sched/restarts")
        self._m_crash_loops = reg.counter("sched/crash_loops")
        self._m_preemptions = reg.counter("sched/preemptions")
        self._m_adopted = reg.counter("sched/adopted")
        # inventory probe runs out-of-process (jax.devices() in this daemon
        # would grab the TPU the spawned jobs need) and off-thread (so agent
        # construction stays fast); the row lands when the probe returns
        self._inventory_thread = threading.Thread(
            target=self._record_inventory, daemon=True)
        self._inventory_thread.start()
        self._load_table()

    def _record_inventory(self) -> None:
        from fedml_tpu.scheduler.env_collect import collect_resources_probe

        try:
            self.compute_store.record_inventory(
                self.node_id, collect_resources_probe())
        except Exception:
            logger.exception("inventory probe failed")

    # -- cross-process run table -----------------------------------------
    # the reference's agents persist run state in sqlite
    # (slave/client_data_interface.py) so `fedml stop` works from any
    # process; here a json table in the workdir serves the same purpose.
    # The full spec rides along so a RESTARTED AGENT can keep supervising
    # (relaunch a run in backoff, re-arm an adopted run's policy).
    def _persist_table(self) -> None:
        rows = {}
        with self._lock:
            for rid, rec in self._runs.items():
                rows[rid] = {
                    "job_name": rec.spec.job_name,
                    "log_path": rec.log_path,
                    "pid": rec.pid,
                    "status": rec.fsm.status,
                    "returncode": rec.returncode,
                    "started": rec.started,
                    "spawned": rec.spawned_at,
                    "preempted_intent": rec.preempt_requested,
                    "spec": rec.spec.wire(),
                    "extra_env": rec.extra_env,
                    "restarts": rec.tracker.restarts if rec.tracker else 0,
                    "reason": rec.reason,
                }
        # the monitor thread and a wait()ing caller can persist concurrently —
        # serialize, and write via mkstemp so a torn write can't be promoted
        with self._persist_lock:
            fd, tmp = tempfile.mkstemp(dir=self.workdir, suffix=".runs.tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(rows, f)
            os.replace(tmp, self._table_path)
            # mirror into the cross-run sqlite cache
            for rid, row in rows.items():
                self.compute_store.upsert_run(
                    rid, job_name=row["job_name"], node_id=self.node_id,
                    status=row["status"], pid=row["pid"],
                    returncode=row["returncode"], log_path=row["log_path"],
                    restarts=row["restarts"], reason=row["reason"],
                )
                if row["status"] in RunStatus.TERMINAL:
                    prev = self.compute_store.get_run(rid)
                    if prev and prev.get("finished_at") is None:
                        self.compute_store.upsert_run(
                            rid, finished_at=time.time())

    def _load_table(self) -> None:
        if not os.path.exists(self._table_path):
            return
        try:
            with open(self._table_path) as f:
                rows = json.load(f)
        except (OSError, ValueError):
            return
        from fedml_tpu.scheduler.job_monitor import _pid_reused

        for rid, row in rows.items():
            spec = JobSpec.from_wire(row.get("spec") or
                                     {"job_name": row.get("job_name", rid)},
                                     default_name=rid)
            rec = RunRecord(rid, spec, row.get("log_path", ""),
                            self._status_sink)
            rec.pid = row.get("pid")
            rec.returncode = row.get("returncode")
            rec.started = float(row.get("started") or rec.started)
            # the pid-reuse judgment must key off the LAST respawn, not
            # the first launch — a supervised relaunch >120s in would
            # otherwise look "reused", and a live run would be doubled
            rec.spawned_at = float(row.get("spawned") or rec.started)
            rec.extra_env = {k: str(v) for k, v in
                             (row.get("extra_env") or {}).items()}
            rec.reason = str(row.get("reason") or "")
            # intent survives the agent: a run SIGTERM'd by a preempt
            # whose agent died mid-grace must still land PREEMPTED (the
            # reschedulable verdict), not KILLED; in-flight does NOT
            # survive — no preempt() owns the quiesce here, the monitor
            # judges the exit
            rec.preempt_requested = bool(row.get("preempted_intent"))
            if rec.tracker is not None:
                rec.tracker.restarts = int(row.get("restarts") or 0)
            rec.fsm.status = row.get("status", RunStatus.IDLE)
            if rec.fsm.status in (RunStatus.RUNNING, RunStatus.STOPPING):
                # STOPPING rows too: a kill/preempt grace window can be
                # persisted mid-flight by a concurrent _persist_table —
                # left alone the row would sit non-terminal forever
                # (neither the monitor's branches nor the JobMonitor's
                # RUNNING-only sweep would ever judge it)
                alive = (rec.pid and _pid_alive(rec.pid)
                         and not _pid_reused(rec.pid, rec.spawned_at))
                rc = self._read_rc(rec)
                if alive and rc is None:
                    # re-adopt: the previous agent process died over this
                    # live run; keep supervising it (pid polls + rc file)
                    # instead of abandoning it to the JobMonitor sweep
                    rec.adopted = True
                    self._m_adopted.inc()
                    sched_event("run_adopted", run_id=rid, pid=rec.pid,
                                node=self.node_id)
                    self._start_log_daemon(rec, from_beginning=False)
                else:
                    # died while no agent was watching: the rc file (if
                    # the shell got far enough to write it) gives the
                    # true terminal status; otherwise judge it like any
                    # abnormal exit — which lets a supervised run
                    # RESTART instead of rotting as FAILED
                    self._judge_exit(rec, rc, persist=False)
            elif (rec.fsm.status == RunStatus.RESTARTING
                  and rec.tracker is not None):
                # relaunch owed from the previous agent life: re-arm at
                # the policy's base backoff (exact remaining delay died
                # with the old process; the budget in `restarts` didn't)
                rec.next_restart_at = time.time() + rec.tracker.policy.backoff_s
            self._runs[rid] = rec
        if self._runs:
            # land the load-time judgments (died-unwatched runs just
            # landed FINISHED/FAILED/RESTARTING in memory) back in the
            # table + store NOW: a stale RUNNING row with a dead pid is
            # exactly what the JobMonitor sweep flips to FAILED — it
            # would overwrite a true rc-file FINISHED verdict
            self._persist_table()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "LocalAgent":
        if self._monitor is None:
            self._stopping.clear()
            self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
            self._monitor.start()
        return self

    def shutdown(self, kill_running: bool = True) -> None:
        self._stopping.set()
        if kill_running:
            for rid in list(self._runs):
                self.kill(rid)
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None

    # -- run control ------------------------------------------------------
    def start_run(self, spec: JobSpec, run_id: Optional[str] = None,
                  extra_env: Optional[Dict[str, str]] = None) -> str:
        run_id = run_id or f"run-{int(time.time()*1000)}-{len(self._runs)}"
        log_path = os.path.join(self.workdir, f"{run_id}.log")
        rec = RunRecord(run_id, spec, log_path, self._status_sink)
        rec.extra_env = dict(extra_env or {})
        rec.fsm.transition(RunStatus.PROVISIONING, "agent accepted job")
        try:
            self._spawn(rec)
        except Exception as e:
            rec.fsm.transition(RunStatus.FAILED, f"spawn error: {e}")
            with self._lock:
                self._runs[run_id] = rec
            raise
        rec.fsm.transition(RunStatus.RUNNING, f"pid {rec.proc.pid}")
        self._start_log_daemon(rec)
        with self._lock:
            self._runs[run_id] = rec
        self._persist_table()
        self.start()
        return run_id

    def _spawn(self, rec: RunRecord, resume: bool = False) -> None:
        """(Re)spawn a record's process; the caller owns the status
        transition. The shell writes its exit code to ``<run_id>.rc`` so
        an adopted run's true rc survives the agent that spawned it."""
        script = ""
        if rec.spec.bootstrap:
            script += rec.spec.bootstrap.rstrip() + "\n"
        script += rec.spec.job
        # rc file: written atomically (tmp + mv) so a reader never sees a
        # torn value; staleness handled by deleting it pre-spawn. The user
        # script runs in a SUBSHELL so its own `exit N` cannot skip the
        # rc capture, and the wrapper shell ignores TERM so a group-wide
        # preempt/kill signal still lets it record the job's true rc
        # (the job itself — the subshell and its children — still gets
        # the signal and may trap it for a graceful quiesce).
        script = ("trap : TERM\n"
                  "(\n" + script.rstrip() + "\n)\n"
                  '__fedml_rc=$?\n'
                  'printf %s "$__fedml_rc" > "$FEDML_RC_FILE.tmp" && '
                  'mv "$FEDML_RC_FILE.tmp" "$FEDML_RC_FILE"\n'
                  'exit "$__fedml_rc"\n')
        env = dict(os.environ)
        env.update(rec.spec.env)
        env.update(rec.extra_env)
        env["FEDML_RUN_ID"] = rec.run_id
        env["FEDML_RC_FILE"] = rec.rc_path
        if resume:
            # durable jobs re-enter via their journal/checkpoints, not
            # round 0 — the job's config reads resume: true; this env var
            # is the plane's signal for jobs that gate resume on it
            env["FEDML_RESUME"] = "1"
        try:
            os.remove(rec.rc_path)
        except OSError:
            pass
        log_f = open(rec.log_path, "ab")
        try:
            rec.proc = subprocess.Popen(
                ["/bin/sh", "-c", script],
                cwd=rec.spec.workspace,
                env=env,
                stdout=log_f,
                stderr=subprocess.STDOUT,
                start_new_session=True,  # own pgid → group kill works
            )
        finally:
            log_f.close()  # child holds its own fd
        rec.pid = rec.proc.pid
        rec.spawned_at = time.time()
        rec.returncode = None

    def _start_log_daemon(self, rec: RunRecord,
                          from_beginning: bool = True) -> None:
        # ship the run's log lines into the same sink as its status
        # events; an ADOPTING agent tails from the current end — the
        # previous agent's daemon already shipped the history
        from fedml_tpu.core.mlops.log_daemon import MLOpsRuntimeLogDaemon

        rec.log_daemon = MLOpsRuntimeLogDaemon(
            rec.run_id, rec.log_path,
            sink_dir=os.path.join(self.workdir, "mlops")
        ).start(from_beginning=from_beginning)

    def _read_rc(self, rec: RunRecord) -> Optional[int]:
        try:
            with open(rec.rc_path) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _drain_group(self, pgid: int, grace_s: float, leader_done) -> bool:
        """SIGTERM the process group and wait for the WHOLE group to
        drain within the grace window, re-sending SIGTERM every 0.5 s —
        a child exec'd in the window between a signal's delivery and its
        own birth never saw it (group signals don't reach future
        members); without the re-send that race escalates ~20% of
        graceful quiesces to SIGKILL. Past the deadline, SIGKILL the
        group. Returns True when escalation fired."""
        try:
            os.killpg(pgid, signal.SIGTERM)
            deadline = time.time() + grace_s
            last_term = time.time()
            while time.time() < deadline:
                if leader_done() and not _pgid_alive(pgid):
                    return False  # quiesced: every group member drained
                if time.time() - last_term > 0.5:
                    last_term = time.time()
                    os.killpg(pgid, signal.SIGTERM)
                time.sleep(0.05)
            os.killpg(pgid, signal.SIGKILL)
            return True
        except ProcessLookupError:
            return False  # group already gone — the drain we wanted

    def kill(self, run_id: str, grace_s: float = 3.0) -> bool:
        rec = self._runs.get(run_id)
        if rec is None:
            return False
        if rec.fsm.status == RunStatus.RESTARTING:
            # no live process — cancel the pending relaunch
            rec.next_restart_at = None
            rec.fsm.transition(RunStatus.KILLED, "restart cancelled by kill")
            self._persist_table()
            return True
        if rec.proc is None:
            # adopted from the persisted table (other-process launch):
            # the child got its own session, so its pgid == its pid
            if rec.pid is None or not _pid_alive(rec.pid):
                return False
            rec.fsm.transition(RunStatus.STOPPING, "kill requested (adopted)")
            self._drain_group(rec.pid, grace_s,
                              lambda: not _pid_alive(rec.pid))
            rec.fsm.transition(RunStatus.KILLED, "adopted pgid killed")
            self._persist_table()
            return True
        if rec.proc.poll() is not None:
            return False
        rec.fsm.transition(RunStatus.STOPPING, "kill requested")
        self._drain_group(os.getpgid(rec.proc.pid), grace_s,
                          lambda: rec.proc.poll() is not None)
        try:
            rec.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:  # pragma: no cover
            pass
        rec.returncode = rec.proc.returncode
        rec.fsm.transition(RunStatus.KILLED, f"rc={rec.returncode}")
        self._persist_table()
        return True

    def preempt(self, run_id: str, grace_s: float = 10.0) -> bool:
        """Gracefully quiesce a run for rescheduling: SIGTERM the process
        group, wait for the WHOLE group to drain (flight-recorder dump +
        journal fdatasync make any point of death safe), escalate to
        SIGKILL only past the grace deadline. Terminal status PREEMPTED —
        the job plane's "resume me elsewhere" verdict, distinct from
        KILLED ("operator said stop")."""
        rec = self._runs.get(run_id)
        if rec is None or rec.fsm.is_terminal:
            return False
        if rec.proc is not None and rec.proc.poll() is not None:
            # the run already exited on its own inside the last poll
            # window — land its TRUE verdict first: a clean FINISH must
            # not be re-labeled PREEMPTED, but a supervised crash heading
            # into RESTARTING is still preemptable (the backoff branch
            # below cancels the relaunch — it must not land on a node
            # that is being drained)
            self._judge_exit(rec, rec.proc.returncode)
            if rec.fsm.status != RunStatus.RESTARTING:
                return False
        if rec.fsm.status == RunStatus.RESTARTING:
            self._m_preemptions.inc()
            rec.preempt_requested = True
            rec.next_restart_at = None
            rec.fsm.transition(RunStatus.STOPPING, "preempt (in backoff)")
            rec.fsm.transition(RunStatus.PREEMPTED, "preempted during backoff")
            sched_event("run_preempted", run_id=run_id, node=self.node_id,
                        rc=None, escalated=False)
            self._persist_table()
            return True
        if rec.proc is None:
            # adopted run: it may already be done (rc file written, pid a
            # lingering zombie) — land its TRUE terminal status rather
            # than claiming a preemption of a finished process
            rc = self._read_rc(rec)
            if rc is not None:
                self._judge_exit(rec, rc)
                return False
        pgid = rec.proc.pid if rec.proc is not None else rec.pid
        if pgid is None or (rec.proc is None and not _pid_alive(pgid)):
            return False
        # only now — past every no-process early-return — may the flags
        # be set: the monitor skips in-flight preemptions, so a flag with
        # no preemption in flight would strand the run un-judged forever
        self._m_preemptions.inc()
        rec.preempt_requested = True
        rec.preempt_inflight = True
        rec.fsm.transition(RunStatus.STOPPING, f"preempt grace={grace_s:g}s")
        escalated = self._drain_group(
            pgid, grace_s,
            (lambda: rec.proc.poll() is not None) if rec.proc is not None
            else (lambda: not _pid_alive(pgid)))
        if rec.proc is not None:
            try:
                rec.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
            rec.returncode = rec.proc.returncode
        else:
            rec.returncode = self._read_rc(rec)
        rec.fsm.transition(
            RunStatus.PREEMPTED,
            f"{describe_rc(rec.returncode)}"
            + (" after SIGKILL escalation" if escalated else " within grace"))
        sched_event("run_preempted", run_id=run_id, node=self.node_id,
                    rc=rec.returncode, escalated=escalated)
        self._persist_table()
        return True

    def status(self, run_id: str) -> Optional[str]:
        rec = self._runs.get(run_id)
        return rec.fsm.status if rec else None

    def wait(self, run_id: str, timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            rec = self._runs.get(run_id)
            if rec is not None and rec.fsm.is_terminal:
                # the caller may exit the process right after this returns,
                # killing the daemon monitor thread mid-persist — make the
                # terminal state durable before handing back control
                self._persist_table()
                return rec.fsm.status
            time.sleep(self._poll_interval / 2)
        raise TimeoutError(f"run {run_id} not terminal after {timeout}s")

    def logs(self, run_id: str, tail: Optional[int] = None) -> str:
        rec = self._runs.get(run_id)
        if rec is None or not os.path.exists(rec.log_path):
            return ""
        with open(rec.log_path, "rb") as f:
            data = f.read().decode(errors="replace")
        if tail is not None:
            data = "\n".join(data.splitlines()[-tail:])
        return data

    def list_runs(self) -> List[Dict]:
        with self._lock:
            return [
                {
                    "run_id": rid,
                    "job_name": rec.spec.job_name,
                    "status": rec.fsm.status,
                    "returncode": rec.returncode,
                    "log_path": rec.log_path,
                    "restarts": rec.tracker.restarts if rec.tracker else 0,
                    "reason": rec.reason,
                }
                for rid, rec in self._runs.items()
            ]

    def cleanup(self) -> int:
        """Drop terminal runs from the table (daemon zombie-cleanup twin)."""
        with self._lock:
            dead = [rid for rid, rec in self._runs.items() if rec.fsm.is_terminal]
            for rid in dead:
                del self._runs[rid]
        self._persist_table()
        return len(dead)

    # -- internals --------------------------------------------------------
    def _status_sink(self, entry: Dict) -> None:
        self._metrics.report_training_status(entry["to"], run_id=entry["run_id"])

    def _judge_exit(self, rec: RunRecord, rc: Optional[int],
                    persist: bool = True) -> None:
        """Route one observed exit through the supervision policy."""
        rec.returncode = rc
        if rec.fsm.status == RunStatus.STOPPING:
            # kill() and preempt() both pass through STOPPING; the monitor
            # may observe the exit first — land on the verdict the caller
            # asked for, not a generic KILLED
            rec.fsm.transition(
                RunStatus.PREEMPTED if rec.preempt_requested
                else RunStatus.KILLED, describe_rc(rc))
        elif rc == 0:
            rec.fsm.transition(RunStatus.FINISHED, "rc=0")
        elif rec.tracker is None:
            rec.fsm.transition(RunStatus.FAILED, describe_rc(rc))
        else:
            uptime = time.time() - rec.spawned_at
            action, detail = rec.tracker.on_exit(rc, uptime)
            if action == "restart":
                rec.reason = (f"{describe_rc(rc)} after {uptime:.1f}s; "
                              f"relaunch #{rec.tracker.restarts} in "
                              f"{detail:g}s")
                rec.proc = None
                rec.next_restart_at = time.time() + detail
                rec.fsm.transition(RunStatus.RESTARTING, rec.reason)
            else:
                rec.reason = detail
                if action == "crash_loop":
                    self._m_crash_loops.inc()
                    sched_event("crash_loop", run_id=rec.run_id,
                                node=self.node_id, rc=rc,
                                attempts=rec.tracker.restarts + 1,
                                reason=detail)
                rec.fsm.transition(RunStatus.FAILED, detail)
        if rec.fsm.is_terminal:
            daemon = getattr(rec, "log_daemon", None)
            if daemon is not None:
                daemon.stop()  # final flush of the tail
        if persist:
            self._persist_table()

    def _relaunch(self, rec: RunRecord) -> None:
        rec.next_restart_at = None
        # another process may have judged this run while we were in
        # backoff: `fedml_tpu stop`/`preempt` from a CLI adopts the run
        # via the shared table and lands KILLED/PREEMPTED there — honor
        # that verdict instead of relaunching a run an operator (or a
        # reclaim notice) just quiesced
        row = self.compute_store.get_run(rec.run_id)
        foreign = (row or {}).get("status")
        if foreign in (RunStatus.KILLED, RunStatus.PREEMPTED):
            rec.returncode = row.get("returncode", rec.returncode)
            rec.fsm.transition(RunStatus.STOPPING,
                               f"{foreign} by another process")
            rec.fsm.transition(foreign, "restart cancelled: judged "
                               "terminal out-of-process")
            self._persist_table()
            return
        resume = bool(rec.tracker and rec.tracker.policy.resume
                      and rec.spec.durable)
        try:
            self._spawn(rec, resume=resume)
        except Exception as e:
            rec.reason = f"relaunch spawn error: {e}"
            rec.fsm.transition(RunStatus.FAILED, rec.reason)
            self._persist_table()
            return
        self._m_restarts.inc()
        sched_event("run_restarted", run_id=rec.run_id, node=self.node_id,
                    attempt=rec.tracker.restarts if rec.tracker else 0,
                    resume=resume)
        rec.fsm.transition(
            RunStatus.RUNNING,
            f"relaunched pid {rec.proc.pid}"
            + (" (resume)" if resume else ""))
        self._persist_table()

    def _monitor_loop(self) -> None:
        while not self._stopping.is_set():
            for rec in list(self._runs.values()):
                if rec.fsm.is_terminal:
                    continue
                if rec.preempt_inflight:
                    # an in-process preempt() owns the quiesce verdict:
                    # the LEADER may exit while other group members are
                    # still draining — judging that early exit here would
                    # mis-time the escalation decision
                    continue
                if rec.fsm.status == RunStatus.RESTARTING:
                    if (rec.next_restart_at is not None
                            and time.time() >= rec.next_restart_at):
                        self._relaunch(rec)
                    continue
                if rec.proc is not None:
                    rc = rec.proc.poll()
                    if rc is not None:
                        self._judge_exit(rec, rc)
                    continue
                if rec.adopted and rec.pid is not None:
                    # adopted run: no Popen handle — the rc file is the
                    # truth (it also outlives a zombie pid); a dead pid
                    # with no rc file is an abnormal, rc-unknown exit
                    rc = self._read_rc(rec)
                    if rc is not None:
                        self._judge_exit(rec, rc)
                    elif not _pid_alive(rec.pid):
                        self._judge_exit(rec, None)
            time.sleep(self._poll_interval)
