"""Local agent — spawns, monitors, and kills job subprocesses.

Parity target: the reference slave agent (``slave/client_runner.py:60`` —
``run`` :378 spawns the job process, ``callback_start_train`` :893,
``callback_stop_train`` :982; the daemon loop ``slave/client_daemon.py:34``
cleans zombies and relaunches). TPU-build re-design: one `LocalAgent`
owns a run table; each run is a subprocess started from a JobSpec
(bootstrap → job), its stdout/stderr tailed into a per-run log file, its
status tracked by the validated FSM and mirrored into the JSONL metrics
sink. A monitor thread reaps exits; `kill` terminates the whole process
group (the reference's cleanup_all_fedml_client_* equivalent).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from fedml_tpu.core.mlops.metrics import MLOpsMetrics
from fedml_tpu.core.mlops.status import RunStatus, RunStatusMachine
from fedml_tpu.scheduler.job_yaml import JobSpec


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(int(pid), 0)
        return True
    except (ProcessLookupError, PermissionError, ValueError):
        return False


class RunRecord:
    def __init__(self, run_id: str, spec: JobSpec, log_path: str, sink):
        self.run_id = run_id
        self.spec = spec
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None  # survives across agent processes
        self.fsm = RunStatusMachine(run_id, sink=sink)
        self.returncode: Optional[int] = None
        self.started = time.time()


class LocalAgent:
    """Single-host agent daemon; the scheduler plane's execution leaf."""

    def __init__(self, workdir: str = ".fedml_runs", args: Any = None,
                 poll_interval: float = 0.2):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self._runs: Dict[str, RunRecord] = {}
        self._lock = threading.Lock()
        self._metrics = MLOpsMetrics(args, sink_dir=os.path.join(self.workdir, "mlops"))
        self._poll_interval = poll_interval
        self._monitor: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._table_path = os.path.join(self.workdir, "runs.json")
        # cross-run cache (scheduler_core parity): run history + device
        # inventory survive this agent process and are queryable by the
        # CLI / JobMonitor from other processes
        from fedml_tpu.scheduler.compute_store import ComputeStore

        self.compute_store = ComputeStore(self.workdir)
        self.node_id = getattr(args, "node_id", None) or "local"
        self._persist_lock = threading.Lock()
        # inventory probe runs out-of-process (jax.devices() in this daemon
        # would grab the TPU the spawned jobs need) and off-thread (so agent
        # construction stays fast); the row lands when the probe returns
        self._inventory_thread = threading.Thread(
            target=self._record_inventory, daemon=True)
        self._inventory_thread.start()
        self._load_table()

    def _record_inventory(self) -> None:
        from fedml_tpu.scheduler.env_collect import collect_resources_probe

        try:
            self.compute_store.record_inventory(
                self.node_id, collect_resources_probe())
        except Exception:
            logger.exception("inventory probe failed")

    # -- cross-process run table -----------------------------------------
    # the reference's agents persist run state in sqlite
    # (slave/client_data_interface.py) so `fedml stop` works from any
    # process; here a json table in the workdir serves the same purpose
    def _persist_table(self) -> None:
        rows = {}
        with self._lock:
            for rid, rec in self._runs.items():
                rows[rid] = {
                    "job_name": rec.spec.job_name,
                    "log_path": rec.log_path,
                    "pid": rec.pid,
                    "status": rec.fsm.status,
                    "returncode": rec.returncode,
                }
        # the monitor thread and a wait()ing caller can persist concurrently —
        # serialize, and write via mkstemp so a torn write can't be promoted
        with self._persist_lock:
            fd, tmp = tempfile.mkstemp(dir=self.workdir, suffix=".runs.tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(rows, f)
            os.replace(tmp, self._table_path)
            # mirror into the cross-run sqlite cache
            for rid, row in rows.items():
                self.compute_store.upsert_run(
                    rid, job_name=row["job_name"], node_id=self.node_id,
                    status=row["status"], pid=row["pid"],
                    returncode=row["returncode"], log_path=row["log_path"],
                )
                if row["status"] in RunStatus.TERMINAL:
                    prev = self.compute_store.get_run(rid)
                    if prev and prev.get("finished_at") is None:
                        self.compute_store.upsert_run(
                            rid, finished_at=time.time())

    def _load_table(self) -> None:
        if not os.path.exists(self._table_path):
            return
        try:
            with open(self._table_path) as f:
                rows = json.load(f)
        except (OSError, ValueError):
            return
        for rid, row in rows.items():
            rec = RunRecord(
                rid, JobSpec(job_name=row.get("job_name", rid), job="",
                             workspace="."),
                row.get("log_path", ""), self._status_sink,
            )
            rec.pid = row.get("pid")
            rec.returncode = row.get("returncode")
            rec.fsm.status = row.get("status", RunStatus.IDLE)
            if (rec.fsm.status == RunStatus.RUNNING and rec.pid
                    and not _pid_alive(rec.pid)):
                # process died while no agent was watching; exact rc unknown.
                # FAILED, matching JobMonitor.sweep_runs for the same
                # condition — terminal status must not depend on which
                # component notices first.
                rec.fsm.status = RunStatus.FAILED
            self._runs[rid] = rec

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "LocalAgent":
        if self._monitor is None:
            self._stopping.clear()
            self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
            self._monitor.start()
        return self

    def shutdown(self, kill_running: bool = True) -> None:
        self._stopping.set()
        if kill_running:
            for rid in list(self._runs):
                self.kill(rid)
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None

    # -- run control ------------------------------------------------------
    def start_run(self, spec: JobSpec, run_id: Optional[str] = None,
                  extra_env: Optional[Dict[str, str]] = None) -> str:
        run_id = run_id or f"run-{int(time.time()*1000)}-{len(self._runs)}"
        log_path = os.path.join(self.workdir, f"{run_id}.log")
        rec = RunRecord(run_id, spec, log_path, self._status_sink)
        rec.fsm.transition(RunStatus.PROVISIONING, "agent accepted job")

        script = ""
        if spec.bootstrap:
            script += spec.bootstrap.rstrip() + "\n"
        script += spec.job
        env = dict(os.environ)
        env.update(spec.env)
        env.update(extra_env or {})
        env["FEDML_RUN_ID"] = run_id
        log_f = open(log_path, "ab")
        try:
            rec.proc = subprocess.Popen(
                ["/bin/sh", "-c", script],
                cwd=spec.workspace,
                env=env,
                stdout=log_f,
                stderr=subprocess.STDOUT,
                start_new_session=True,  # own pgid → group kill works
            )
        except Exception as e:
            log_f.close()
            rec.fsm.transition(RunStatus.FAILED, f"spawn error: {e}")
            with self._lock:
                self._runs[run_id] = rec
            raise
        finally:
            if rec.proc is not None:
                log_f.close()  # child holds its own fd
        rec.pid = rec.proc.pid
        rec.fsm.transition(RunStatus.RUNNING, f"pid {rec.proc.pid}")
        # ship the run's log lines into the same sink as its status events
        from fedml_tpu.core.mlops.log_daemon import MLOpsRuntimeLogDaemon

        rec.log_daemon = MLOpsRuntimeLogDaemon(
            run_id, log_path, sink_dir=os.path.join(self.workdir, "mlops")
        ).start()
        with self._lock:
            self._runs[run_id] = rec
        self._persist_table()
        self.start()
        return run_id

    def kill(self, run_id: str, grace_s: float = 3.0) -> bool:
        rec = self._runs.get(run_id)
        if rec is None:
            return False
        if rec.proc is None:
            # adopted from the persisted table (other-process launch):
            # the child got its own session, so its pgid == its pid
            if rec.pid is None or not _pid_alive(rec.pid):
                return False
            rec.fsm.transition(RunStatus.STOPPING, "kill requested (adopted)")
            try:
                os.killpg(rec.pid, signal.SIGTERM)
                deadline = time.time() + grace_s
                while time.time() < deadline and _pid_alive(rec.pid):
                    time.sleep(0.05)
                if _pid_alive(rec.pid):
                    os.killpg(rec.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            rec.fsm.transition(RunStatus.KILLED, "adopted pgid killed")
            self._persist_table()
            return True
        if rec.proc.poll() is not None:
            return False
        rec.fsm.transition(RunStatus.STOPPING, "kill requested")
        pgid = os.getpgid(rec.proc.pid)
        os.killpg(pgid, signal.SIGTERM)
        deadline = time.time() + grace_s
        while time.time() < deadline and rec.proc.poll() is None:
            time.sleep(0.05)
        if rec.proc.poll() is None:
            os.killpg(pgid, signal.SIGKILL)
            rec.proc.wait(timeout=5)
        rec.returncode = rec.proc.returncode
        rec.fsm.transition(RunStatus.KILLED, f"rc={rec.returncode}")
        self._persist_table()
        return True

    def status(self, run_id: str) -> Optional[str]:
        rec = self._runs.get(run_id)
        return rec.fsm.status if rec else None

    def wait(self, run_id: str, timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            rec = self._runs.get(run_id)
            if rec is not None and rec.fsm.is_terminal:
                # the caller may exit the process right after this returns,
                # killing the daemon monitor thread mid-persist — make the
                # terminal state durable before handing back control
                self._persist_table()
                return rec.fsm.status
            time.sleep(self._poll_interval / 2)
        raise TimeoutError(f"run {run_id} not terminal after {timeout}s")

    def logs(self, run_id: str, tail: Optional[int] = None) -> str:
        rec = self._runs.get(run_id)
        if rec is None or not os.path.exists(rec.log_path):
            return ""
        with open(rec.log_path, "rb") as f:
            data = f.read().decode(errors="replace")
        if tail is not None:
            data = "\n".join(data.splitlines()[-tail:])
        return data

    def list_runs(self) -> List[Dict]:
        with self._lock:
            return [
                {
                    "run_id": rid,
                    "job_name": rec.spec.job_name,
                    "status": rec.fsm.status,
                    "returncode": rec.returncode,
                    "log_path": rec.log_path,
                }
                for rid, rec in self._runs.items()
            ]

    def cleanup(self) -> int:
        """Drop terminal runs from the table (daemon zombie-cleanup twin)."""
        with self._lock:
            dead = [rid for rid, rec in self._runs.items() if rec.fsm.is_terminal]
            for rid in dead:
                del self._runs[rid]
        self._persist_table()
        return len(dead)

    # -- internals --------------------------------------------------------
    def _status_sink(self, entry: Dict) -> None:
        self._metrics.report_training_status(entry["to"], run_id=entry["run_id"])

    def _monitor_loop(self) -> None:
        while not self._stopping.is_set():
            for rec in list(self._runs.values()):
                if rec.proc is None or rec.fsm.is_terminal:
                    continue
                rc = rec.proc.poll()
                if rc is None:
                    continue
                rec.returncode = rc
                if rec.fsm.status == RunStatus.STOPPING:
                    rec.fsm.transition(RunStatus.KILLED, f"rc={rc}")
                elif rc == 0:
                    rec.fsm.transition(RunStatus.FINISHED, "rc=0")
                else:
                    rec.fsm.transition(RunStatus.FAILED, f"rc={rc}")
                daemon = getattr(rec, "log_daemon", None)
                if daemon is not None:
                    daemon.stop()  # final flush of the tail
                self._persist_table()
            time.sleep(self._poll_interval)
