"""Connectivity diagnosis — `fedml_tpu diagnosis`.

Parity target: ``computing/scheduler/slave/client_diagnosis.py:24`` (the
reference checks MQTT/S3/backend reachability before a run). TPU-build
checks: the broker control plane (TCP connect + a pub/sub echo through
the real frame protocol), the object store (write/read/delete round
trip), and the JAX accelerator runtime.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Dict


def check_broker(host: str, port: int, timeout: float = 5.0) -> Dict:
    """Full pub/sub echo through the broker — not just a TCP connect."""
    t0 = time.time()
    try:
        from fedml_tpu.core.distributed.communication.broker import (
            BrokerClient,
        )

        client = BrokerClient(host, port, timeout=timeout)
        topic = f"diagnosis/{uuid.uuid4().hex}"
        got = threading.Event()
        client.subscribe(topic, lambda body: got.set())
        deadline = time.time() + timeout
        while not got.is_set() and time.time() < deadline:
            client.publish(topic, b"ping")  # resend: subscribe may race
            got.wait(0.1)
        client.close()
        if not got.is_set():
            return {"ok": False, "error": "echo timed out (connected, but "
                                          "no message came back)"}
        return {"ok": True, "rtt_ms": round((time.time() - t0) * 1000, 1)}
    except OSError as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def check_object_store(store_dir=None) -> Dict:
    try:
        from fedml_tpu.core.distributed.communication.object_store import (
            LocalDirObjectStore,
        )

        store = LocalDirObjectStore(store_dir)
        key = store.new_key("diagnosis")
        store.put_object(key, b"ping")
        ok = store.get_object(key) == b"ping"
        store.delete_object(key)
        return {"ok": ok, "root": store.root}
    except OSError as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def check_accelerator() -> Dict:
    try:
        import jax

        devs = jax.devices()
        return {"ok": True, "backend": jax.default_backend(),
                "devices": len(devs),
                "kind": devs[0].device_kind if devs else ""}
    except Exception as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def run_diagnosis(broker: str = None, store_dir=None) -> Dict:
    report: Dict = {}
    if broker:
        host, _, port = broker.rpartition(":")
        if not host or not port.isdigit():
            report["broker"] = {
                "ok": False,
                "error": f"expected host:port, got {broker!r}"}
        else:
            report["broker"] = check_broker(host, int(port))
    report["object_store"] = check_object_store(store_dir)
    report["accelerator"] = check_accelerator()
    report["ok"] = all(v.get("ok") for v in report.values()
                       if isinstance(v, dict))
    return report
