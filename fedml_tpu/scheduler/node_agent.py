"""Node agent — a LocalAgent exposed to the cluster over the broker.

Parity target: the reference slave agent's remote-control surface
(``slave/client_runner.py`` — MQTT callbacks ``callback_start_train``
:893 / ``callback_stop_train`` :982, status reporting back to the master,
log shipping via the log daemon). Re-design: the in-process LocalAgent
keeps doing the process supervision; this wrapper speaks the scheduler
wire protocol so a MasterAgent on another machine can start/stop runs
here and see their status and logs.

Wire protocol (JSON over broker topics):

  node → ``sched/{cluster}/master``:
      node_online {node_id, slots}
      heartbeat   {node_id, runs: {run_id: status}}
      run_status  {node_id, run_id, status, returncode}
      run_logs    {node_id, run_id, data}
  master → ``sched/{cluster}/node/{node_id}``:
      start_run {run_id, spec {job_name, job, workspace, bootstrap, env},
                 env {..extra per-rank env..}}
      stop_run  {run_id}
      get_logs  {run_id, tail}
"""
from __future__ import annotations

import logging
import os
import time
from typing import Dict

from fedml_tpu.core.distributed.communication.broker_agent import BrokerJsonAgent
from fedml_tpu.scheduler.agent import LocalAgent
from fedml_tpu.scheduler.job_yaml import JobSpec

logger = logging.getLogger(__name__)


class NodeAgent(BrokerJsonAgent):
    def __init__(self, node_id: str, broker_host: str, broker_port: int,
                 workdir: str = ".fedml_runs", cluster: str = "default",
                 slots: int = 1, heartbeat_s: float = 1.0, store=None):
        super().__init__(broker_host, broker_port)
        self.node_id = node_id
        self.cluster = cluster
        self.slots = slots
        self.workdir = os.path.join(workdir, node_id)
        self.agent = LocalAgent(workdir=self.workdir)
        self._heartbeat_s = heartbeat_s
        self._reported: Dict[str, str] = {}  # run_id → last status sent
        if store is None:
            from fedml_tpu.core.distributed.communication.object_store import (
                create_object_store,
            )

            store = create_object_store()
        self.store = store
        self.subscribe_json(
            f"sched/{cluster}/node/{node_id}", self._on_message)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "NodeAgent":
        from fedml_tpu.scheduler.env_collect import (
            collect_resources_probe as collect_resources,
        )

        self.agent.start()
        self._publish({"type": "node_online", "node_id": self.node_id,
                       "slots": self.slots,
                       "resources": collect_resources()})
        self.spawn_loop(self._heartbeat_loop)
        return self

    def shutdown(self, kill_running: bool = True) -> None:
        self.agent.shutdown(kill_running=kill_running)
        self.stop_agent()

    def serve_forever(self) -> None:
        self.start()
        try:
            while not self._stopping.is_set():
                time.sleep(0.5)
        except KeyboardInterrupt:
            self.shutdown()

    # -- handlers ---------------------------------------------------------
    def _on_message(self, msg: Dict) -> None:
        mtype = msg.get("type")
        if mtype == "start_run":
            self._handle_start(msg)
        elif mtype == "stop_run":
            self.agent.kill(str(msg["run_id"]))
        elif mtype == "get_logs":
            rid = str(msg["run_id"])
            self._publish({"type": "run_logs", "node_id": self.node_id,
                           "run_id": rid,
                           "data": self.agent.logs(rid, tail=msg.get("tail"))})
        elif mtype == "ota_upgrade":
            self._handle_ota(msg)

    def _handle_ota(self, msg: Dict) -> None:
        """Stage a code upgrade (slave daemon_ota_upgrade parity): unpack
        the shipped package, record it, report; applied on next restart."""
        from fedml_tpu.scheduler import ota

        version = str(msg.get("version", "unknown"))
        try:
            record = ota.stage_upgrade(
                self.store, str(msg["package_key"]), version, self.workdir)
            self._publish({"type": "ota_staged", "node_id": self.node_id,
                           "version": record["version"], "ok": True})
        except Exception as e:
            logger.exception("node %s: OTA staging failed", self.node_id)
            self._publish({"type": "ota_staged", "node_id": self.node_id,
                           "version": version, "ok": False,
                           "error": str(e)})

    def _handle_start(self, msg: Dict) -> None:
        rid = str(msg["run_id"])
        raw = msg.get("spec") or {}
        spec = JobSpec(
            job_name=str(raw.get("job_name", rid)),
            job=str(raw.get("job", "")),
            workspace=str(raw.get("workspace", ".")),
            bootstrap=raw.get("bootstrap"),
            env={k: str(v) for k, v in (raw.get("env") or {}).items()},
        )
        from fedml_tpu.scheduler import ota

        try:
            # staged OTA code (if any) leads PYTHONPATH for the job process
            self.agent.start_run(
                spec, run_id=rid,
                extra_env=ota.apply_env(self.workdir, msg.get("env") or {}))
        except Exception as e:
            logger.exception("node %s failed to start %s", self.node_id, rid)
            self._publish({"type": "run_status", "node_id": self.node_id,
                           "run_id": rid, "status": "FAILED",
                           "returncode": None, "error": str(e)})

    # -- status shipping --------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stopping.is_set():
            runs = {}
            for row in self.agent.list_runs():
                rid, status = row["run_id"], row["status"]
                runs[rid] = status
                if self._reported.get(rid) != status:
                    self._reported[rid] = status
                    self._publish({
                        "type": "run_status", "node_id": self.node_id,
                        "run_id": rid, "status": status,
                        "returncode": row.get("returncode"),
                    })
            self._publish({"type": "heartbeat", "node_id": self.node_id,
                           "runs": runs})
            time.sleep(self._heartbeat_s)

    def _publish(self, msg: Dict) -> None:
        # daemon side: raising in a heartbeat/handler thread would kill
        # the loop; master timeouts + heartbeat reconciliation cover losses
        self.publish_json(f"sched/{self.cluster}/master", msg, best_effort=True)
