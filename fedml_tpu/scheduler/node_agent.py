"""Node agent — a LocalAgent exposed to the cluster over the broker.

Parity target: the reference slave agent's remote-control surface
(``slave/client_runner.py`` — MQTT callbacks ``callback_start_train``
:893 / ``callback_stop_train`` :982, status reporting back to the master,
log shipping via the log daemon). Re-design: the in-process LocalAgent
keeps doing the process supervision; this wrapper speaks the scheduler
wire protocol so a MasterAgent on another machine can start/stop runs
here and see their status and logs.

Wire protocol (JSON over broker topics):

  node → ``sched/{cluster}/master``:
      node_online {node_id, slots}
      heartbeat   {node_id, runs: {run_id: status}}
      run_status  {node_id, run_id, status, returncode}
      run_logs    {node_id, run_id, data}
  master → ``sched/{cluster}/node/{node_id}``:
      start_run   {run_id, spec {job_name, job, workspace, bootstrap, env,
                   computing, restart, durable}, env {..per-rank env..}}
      stop_run    {run_id}
      preempt_run {run_id, grace_s}      # graceful quiesce → PREEMPTED
      drain_node  {grace_s}              # reclaim notice: preempt ALL runs
      get_logs    {run_id, tail}

The ``drain_node`` verb is how preemptible capacity plugs in: whatever
delivers the provider's "this node is being reclaimed in N seconds"
notice publishes it here; the local agent quiesces every run (SIGTERM +
grace, journals already fdatasync'd) and the master reacts to the
PREEMPTED status reports by rescheduling durable jobs onto survivors.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional

from fedml_tpu.core.distributed.communication.broker_agent import BrokerJsonAgent
from fedml_tpu.core.mlops.status import RunStatus
from fedml_tpu.scheduler.agent import LocalAgent
from fedml_tpu.scheduler.job_yaml import JobSpec

logger = logging.getLogger(__name__)


class NodeAgent(BrokerJsonAgent):
    def __init__(self, node_id: str, broker_host: str, broker_port: int,
                 workdir: str = ".fedml_runs", cluster: str = "default",
                 slots: int = 1, heartbeat_s: float = 1.0, store=None):
        super().__init__(broker_host, broker_port)
        self.node_id = node_id
        self.cluster = cluster
        self.slots = slots
        self.workdir = os.path.join(workdir, node_id)
        self.agent = LocalAgent(workdir=self.workdir)
        self._heartbeat_s = heartbeat_s
        self._reported: Dict[str, str] = {}  # run_id → last status sent
        self._resources: Optional[Dict] = None  # last known probe snapshot
        self._res_lock = threading.Lock()  # start() vs refresh thread
        if store is None:
            from fedml_tpu.core.distributed.communication.object_store import (
                create_object_store,
            )

            store = create_object_store()
        self.store = store
        self.subscribe_json(
            f"sched/{cluster}/node/{node_id}", self._on_message)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "NodeAgent":
        from fedml_tpu.scheduler.env_collect import (
            collect_resources_probe as collect_resources,
        )

        self.agent.start()
        res = collect_resources()
        with self._res_lock:
            self._resources = res
        self._publish({"type": "node_online", "node_id": self.node_id,
                       "slots": self.slots, "resources": res})
        self.spawn_loop(self._heartbeat_loop)
        return self

    def _refresh_resources(self) -> None:
        from fedml_tpu.scheduler.env_collect import collect_resources_probe

        try:
            res = collect_resources_probe()
            with self._res_lock:
                self._resources = res
        except Exception:  # pragma: no cover - probe is best-effort
            logger.exception("node %s: resource probe failed", self.node_id)

    def shutdown(self, kill_running: bool = True) -> None:
        self.agent.shutdown(kill_running=kill_running)
        self.stop_agent()

    def serve_forever(self) -> None:
        self.start()
        try:
            while not self._stopping.is_set():
                time.sleep(0.5)
        except KeyboardInterrupt:
            self.shutdown()

    # -- handlers ---------------------------------------------------------
    def _on_message(self, msg: Dict) -> None:
        mtype = msg.get("type")
        if mtype == "start_run":
            self._handle_start(msg)
        elif mtype == "stop_run":
            self.agent.kill(str(msg["run_id"]))
        elif mtype == "preempt_run":
            self._preempt_async(str(msg["run_id"]),
                                float(msg.get("grace_s", 10.0)))
        elif mtype == "drain_node":
            self._handle_drain(msg)
        elif mtype == "get_logs":
            rid = str(msg["run_id"])
            self._publish({"type": "run_logs", "node_id": self.node_id,
                           "run_id": rid,
                           "data": self.agent.logs(rid, tail=msg.get("tail"))})
        elif mtype == "ota_upgrade":
            self._handle_ota(msg)

    def _handle_ota(self, msg: Dict) -> None:
        """Stage a code upgrade (slave daemon_ota_upgrade parity): unpack
        the shipped package, record it, report; applied on next restart."""
        from fedml_tpu.scheduler import ota

        version = str(msg.get("version", "unknown"))
        try:
            record = ota.stage_upgrade(
                self.store, str(msg["package_key"]), version, self.workdir)
            self._publish({"type": "ota_staged", "node_id": self.node_id,
                           "version": record["version"], "ok": True})
        except Exception as e:
            logger.exception("node %s: OTA staging failed", self.node_id)
            self._publish({"type": "ota_staged", "node_id": self.node_id,
                           "version": version, "ok": False,
                           "error": str(e)})

    def _preempt_async(self, run_id: str, grace_s: float) -> None:
        """Quiesce off the broker reader thread: a preempt blocks for up
        to its grace window, and handlers dispatch inline on the single
        read loop — a serial drain of N runs would take N×grace and
        freeze every other control verb (stop_run, get_logs) meanwhile.
        Preempts of distinct runs are independent SIGTERM+wait loops;
        concurrent calls for the SAME run converge on idempotent FSM
        transitions."""
        threading.Thread(target=self.agent.preempt, args=(run_id,),
                         kwargs={"grace_s": grace_s}, daemon=True,
                         name=f"preempt-{run_id}").start()

    def _handle_drain(self, msg: Dict) -> None:
        """Reclaim notice landed at the node: quiesce everything local,
        concurrently. The master never hears a special message — the
        PREEMPTED status reports ARE the signal it reschedules durable
        jobs from."""
        grace_s = float(msg.get("grace_s", 10.0))
        logger.warning("node %s: drain notice (grace %gs)", self.node_id,
                       grace_s)
        for row in self.agent.list_runs():
            if row["status"] not in RunStatus.TERMINAL:
                self._preempt_async(row["run_id"], grace_s)

    def _handle_start(self, msg: Dict) -> None:
        rid = str(msg["run_id"])
        spec = JobSpec.from_wire(msg.get("spec") or {}, default_name=rid)
        from fedml_tpu.scheduler import ota

        try:
            # staged OTA code (if any) leads PYTHONPATH for the job process
            self.agent.start_run(
                spec, run_id=rid,
                extra_env=ota.apply_env(self.workdir, msg.get("env") or {}))
        except Exception as e:
            logger.exception("node %s failed to start %s", self.node_id, rid)
            self._publish({"type": "run_status", "node_id": self.node_id,
                           "run_id": rid, "status": "FAILED",
                           "returncode": None, "error": str(e)})

    # -- status shipping --------------------------------------------------
    def _heartbeat_loop(self) -> None:
        beats = 0
        while not self._stopping.is_set():
            runs = {}
            for row in self.agent.list_runs():
                rid, status = row["run_id"], row["status"]
                runs[rid] = status
                if self._reported.get(rid) != status:
                    self._reported[rid] = status
                    self._publish({
                        "type": "run_status", "node_id": self.node_id,
                        "run_id": rid, "status": status,
                        "returncode": row.get("returncode"),
                    })
            # slots ride every heartbeat (a master that missed the
            # one-shot node_online — e.g. it restarted, or came up after
            # this node — must still learn the placement capacity);
            # resources re-advertise periodically from the last known
            # snapshot, refreshed OFF this thread — a hanging probe
            # (unmemoized on failure, up to its 60s timeout) on the
            # heartbeat path would silence us past node_loss_deadline_s
            # and get a healthy node's jobs rescheduled out from under it
            hb = {"type": "heartbeat", "node_id": self.node_id,
                  "runs": runs, "slots": self.slots}
            if beats % 30 == 0 and self._resources is not None:
                hb["resources"] = self._resources
                threading.Thread(target=self._refresh_resources,
                                 daemon=True).start()
            beats += 1
            self._publish(hb)
            time.sleep(self._heartbeat_s)

    def _publish(self, msg: Dict) -> None:
        # daemon side: raising in a heartbeat/handler thread would kill
        # the loop; master timeouts + heartbeat reconciliation cover losses
        self.publish_json(f"sched/{self.cluster}/master", msg, best_effort=True)
