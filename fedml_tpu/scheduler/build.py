"""`fedml_tpu build` — package a job for distribution.

Parity target: ``fedml build`` (``cli/modules/build.py`` →
``api.fedml_build``): zip a source folder + entry point + optional
config folder into a self-describing package that `launch`/OTA/deploy
can ship through the object store.
"""
from __future__ import annotations

import json
import os
import time
import zipfile
from typing import Dict, Optional

MANIFEST = "fedml_package.json"


def build_package(source_folder: str, entry_point: str,
                  dest_folder: str, config_folder: Optional[str] = None,
                  package_name: Optional[str] = None) -> str:
    """Returns the path of the built zip."""
    source_folder = os.path.abspath(source_folder)
    if not os.path.isdir(source_folder):
        raise FileNotFoundError(f"no such source folder: {source_folder}")
    entry_path = os.path.join(source_folder, entry_point)
    if not os.path.isfile(entry_path):
        raise FileNotFoundError(
            f"entry point {entry_point!r} not found in {source_folder}")
    os.makedirs(dest_folder, exist_ok=True)
    name = package_name or os.path.basename(source_folder.rstrip(os.sep))
    zip_path = os.path.join(os.path.abspath(dest_folder), f"{name}.zip")

    manifest: Dict = {
        "package_name": name,
        "entry_point": entry_point,
        "built_at": time.time(),
    }
    with zipfile.ZipFile(zip_path, "w", zipfile.ZIP_DEFLATED) as z:
        for base, dirs, files in os.walk(source_folder):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", ".fedml_runs")]
            for fn in files:
                full = os.path.join(base, fn)
                z.write(full, os.path.relpath(full, source_folder))
        if config_folder:
            cfg = os.path.abspath(config_folder)
            for base, _, files in os.walk(cfg):
                for fn in files:
                    full = os.path.join(base, fn)
                    z.write(full, os.path.join(
                        "config", os.path.relpath(full, cfg)))
            manifest["config_folder"] = "config"
        z.writestr(MANIFEST, json.dumps(manifest))
    return zip_path


def read_manifest(zip_path: str) -> Dict:
    with zipfile.ZipFile(zip_path) as z:
        return json.loads(z.read(MANIFEST))
