"""Preempt-and-resume scenario — the job plane's chaos acceptance.

The PR 12 recovery runner proves the journal survives a SIGKILL; this
scenario proves the *scheduler* turns preemptible capacity into a
non-event: a durable cross-silo federation runs under REAL node-agent
subprocesses, the server's node receives a drain (simulated reclaim
notice) mid-round, the run is SIGTERM-quiesced within a grace window
(flight-recorder dump + fdatasync'd journal make any kill point safe),
the master reschedules it onto a surviving node, and it resumes
MID-ROUND from the journal — salvaged uploads never retrained, and under
the identity codec the final params are bit-identical to an undisturbed
run.

Measured: **MTTR** = wall seconds from the reclaim notice to the
rescheduled server announcing its journal replay (the ``RESUMED`` marker
in its run log). Exposed as ``fedml_tpu chaos --drain`` and gated by
``tools/preempt_bench.py`` / ``bench.py --preempt``.

Optionally an :class:`~fedml_tpu.resilience.chaos.AgentKillWindow`
SIGKILLs the *surviving node's agent* after the resume and restarts it
over the same workdir — the restarted agent must re-adopt the live
resumed server (pid + rc-file supervision) for the federation to finish,
which is the cross-process proof of the re-adoption satellite.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from fedml_tpu.resilience.chaos import AgentKillWindow, NodeDrain

logger = logging.getLogger(__name__)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

__all__ = ["run_preempt_scenario", "read_journal_records"]


def read_journal_records(path: str) -> List[Dict]:
    """READ-ONLY journal scan for the drain trigger — unlike
    ``RoundJournal.records()`` it never truncates a (possibly mid-append)
    tail, because the journal belongs to a LIVE server we are only
    spying on. Frame parsing is the journal module's own
    :func:`~fedml_tpu.resilience.durability.journal.parse_frames`, so a
    format change can't silently break the trigger."""
    from fedml_tpu.resilience.durability.journal import parse_frames

    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return []
    records, _ = parse_frames(data)
    return records


def _spawn_node_agent(node_id: str, broker: Tuple[str, int], workdir: str,
                      slots: int) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (REPO, env.get("PYTHONPATH")) if p)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "fedml_tpu.cli", "cluster", "node",
         "--id", node_id, "--broker", f"{broker[0]}:{broker[1]}",
         "--workdir", workdir, "--slots", str(slots)],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        env=env, start_new_session=True)


def _find_marker(log_path: str, prefix: str) -> Optional[str]:
    try:
        with open(log_path, "rb") as f:
            for raw in f.read().decode(errors="replace").splitlines():
                if raw.startswith(prefix):
                    return raw[len(prefix):]
    except OSError:
        pass
    return None


def run_preempt_scenario(
    seed: int = 0,
    rounds: int = 5,
    clients: int = 2,
    drain_round: int = 2,
    after_uploads: int = 1,
    grace_s: float = 10.0,
    compression: str = "identity",
    via: str = "master",
    agent_kill: bool = False,
    timeout: float = 600.0,
    tmp_dir: Optional[str] = None,
    extra_train: Optional[Dict] = None,
) -> Dict:
    """One drained federation on a two-node cluster; JSON-safe summary.

    ``via='master'`` drives :meth:`MasterAgent.drain_node`;
    ``via='reclaim'`` delivers the drain notice to the NODE agent (wire
    verb), and the master reschedules purely from the PREEMPTED status
    reports. ``agent_kill=True`` additionally SIGKILLs + restarts the
    surviving node's agent after the resume (re-adoption proof).
    """
    import shutil
    import tempfile

    from fedml_tpu.core.distributed.communication.broker import PubSubBroker
    from fedml_tpu.resilience.durability.recover import scenario_config
    from fedml_tpu.scheduler.job_yaml import JobSpec
    from fedml_tpu.scheduler.master_agent import MasterAgent

    drain = NodeDrain("n1", round=drain_round, after_uploads=after_uploads,
                      grace_s=grace_s, via=via)
    kill_spec = AgentKillWindow("n2") if agent_kill else None
    tmp = tmp_dir or tempfile.mkdtemp(prefix="fedml_preempt_")
    owns_tmp = tmp_dir is None
    os.makedirs(tmp, exist_ok=True)
    agents_dir = os.path.join(tmp, "agents")
    broker = PubSubBroker(port=0).start()
    host, port = broker.address
    run_id = f"preempt_{seed}"
    cfg = scenario_config(run_id, seed, rounds, clients, host, port, tmp,
                          compression, extra_train=extra_train)
    cfg_path = os.path.join(tmp, f"{run_id}.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    journal_path = os.path.join(tmp, "ckpts", "server_round.journal")

    py = sys.executable
    rank_cmd = (f'"{py}" -m fedml_tpu.resilience.durability.'
                f'recover --cf "{cfg_path}"')
    common_env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    server_spec = JobSpec(
        job_name="fed-server", workspace=REPO, env=dict(common_env),
        durable=True,
        job=f'{rank_cmd} --rank 0 --role server\n')
    client_spec = JobSpec(
        job_name="fed-clients", workspace=REPO, env=dict(common_env),
        job=f'{rank_cmd} --rank "$((FEDML_RANK+1))" --role client\n')

    t0 = time.time()
    master = None
    agents: Dict[str, subprocess.Popen] = {}
    result: Dict = {
        "seed": int(seed), "rounds": int(rounds), "clients": int(clients),
        "drain_round": int(drain_round), "grace_s": float(grace_s),
        "via": via, "compression": compression,
        "agent_kill": bool(agent_kill),
    }
    try:
        # node n1 hosts only the server; n2 hosts the clients AND must
        # have a spare slot for the rescheduled server
        agents["n1"] = _spawn_node_agent("n1", (host, port), agents_dir, 1)
        agents["n2"] = _spawn_node_agent("n2", (host, port), agents_dir,
                                         clients + 1)
        master = MasterAgent(host, port, node_timeout_s=5.0,
                             node_loss_deadline_s=30.0).start()
        master.wait_for_nodes(2, timeout=60)
        client_job = master.submit_job(client_spec, n_ranks=clients,
                                       nodes=["n2"])
        server_job = master.submit_job(server_spec, n_ranks=1, nodes=["n1"])
        server_rid = f"{server_job}-r0"

        # deterministic mid-round trigger: the journal says round
        # `drain_round` is open with >= after_uploads uploads durable
        deadline = time.time() + timeout
        while time.time() < deadline:
            recs = read_journal_records(journal_path)
            opened = [r for r in recs if r.get("kind") == "round_open"]
            if opened and int(opened[-1].get("round", -1)) >= drain.round:
                rnd = int(opened[-1]["round"])
                got = sum(1 for r in recs
                          if r.get("kind") == "upload_received"
                          and int(r.get("round", -1)) == rnd)
                if got >= drain.after_uploads:
                    result["drained_at_round"] = rnd
                    result["uploads_journaled_at_drain"] = got
                    break
            st = master.job_status(server_job)["status"]
            if st in ("FAILED", "KILLED"):
                raise RuntimeError(f"server job died pre-drain: {st}")
            time.sleep(0.02)
        else:
            raise TimeoutError("journal never showed the drain window")

        # the reclaim notice
        t_drain = time.time()
        if drain.via == "master":
            drain_out = master.drain_node("n1", grace_s=drain.grace_s,
                                          timeout=timeout)
        else:
            # provider notice lands at the NODE; master only sees the
            # PREEMPTED status report and must reschedule from that
            master._send("n1", {"type": "drain_node",
                                "grace_s": drain.grace_s})
            view = master.jobs[server_job]
            while time.time() < deadline and server_rid not in view.resched_map:
                time.sleep(0.05)
            drain_out = {"node": "n1", "preempted": [server_rid],
                         "rescheduled": dict(view.resched_map), "failed": []}
        result["drain"] = drain_out
        new_rid = drain_out["rescheduled"].get(server_rid)
        if new_rid is None:
            raise RuntimeError(f"server run was not rescheduled: {drain_out}")
        view = master.jobs[server_job]
        new_node = view.ranks[new_rid]
        result["rescheduled_to"] = new_node
        new_log = os.path.join(agents_dir, new_node, f"{new_rid}.log")

        # MTTR clock stops at the resumed server's journal-replay marker
        resumed_raw = None
        while time.time() < deadline:
            resumed_raw = _find_marker(new_log, "RESUMED ")
            if resumed_raw is not None:
                result["mttr_s"] = round(time.time() - t_drain, 3)
                break
            time.sleep(0.05)
        if resumed_raw is None:
            raise TimeoutError("rescheduled server never announced RESUMED")
        resumed = json.loads(resumed_raw)
        result["resumed_round"] = resumed.get("round")
        result["salvaged_uploads"] = int(resumed.get("salvaged", 0))
        result["salvaged_clients"] = resumed.get("clients", [])

        if kill_spec is not None:
            # scheduler-tier chaos: kill the surviving node's AGENT over
            # the live resumed run; the restart must re-adopt it
            time.sleep(kill_spec.after_s)
            victim = agents[kill_spec.node]
            os.kill(victim.pid, signal.SIGKILL)
            victim.wait(timeout=30)
            time.sleep(kill_spec.restart_after_s)
            agents[kill_spec.node] = _spawn_node_agent(
                kill_spec.node, (host, port), agents_dir, clients + 1)
            result["agent_killed"] = kill_spec.node

        out = master.wait_job(server_job,
                              timeout=max(5.0, deadline - time.time()))
        result["job_status"] = out["status"]
        master.wait_job(client_job,
                        timeout=max(5.0, deadline - time.time()))
        result["completed"] = out["status"] == "FINISHED"

        digest = _find_marker(new_log, "DIGEST ")
        res_line = _find_marker(new_log, "RESULT ")
        result["digest"] = digest
        result["result"] = json.loads(res_line) if res_line else None
        trained: Dict[str, List[int]] = {}
        for k in range(clients):
            clog = os.path.join(agents_dir, "n2", f"{client_job}-r{k}.log")
            try:
                with open(clog, "rb") as f:
                    lines = f.read().decode(errors="replace").splitlines()
            except OSError:
                lines = []
            trained[str(k + 1)] = [int(ln.split()[1]) for ln in lines
                                   if ln.startswith("TRAINED ")]
        result["trained"] = trained
        from fedml_tpu.telemetry import get_registry

        reg = get_registry()
        counters = {}
        for rec in reg.snapshot():
            name = rec.get("name", "")
            if name.startswith("sched/"):
                key = name.split("/", 1)[1]
                counters[key] = counters.get(key, 0.0) + float(
                    rec.get("value", rec.get("count", 0)) or 0)
        result["counters"] = counters
        result["wall_s"] = round(time.time() - t0, 3)
        return result
    finally:
        if master is not None:
            master.shutdown()
        for p in agents.values():
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        # the runs live in their OWN sessions (start_new_session), so the
        # agent group-kill above does not reach them — reap any stragglers
        # off the persisted run tables
        for node in ("n1", "n2"):
            table = os.path.join(agents_dir, node, "runs.json")
            try:
                with open(table) as f:
                    rows = json.load(f)
            except (OSError, ValueError):
                continue
            for row in rows.values():
                pid = row.get("pid")
                if pid:
                    try:
                        os.killpg(int(pid), signal.SIGKILL)
                    except (ProcessLookupError, PermissionError, ValueError):
                        pass
        broker.stop()
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)
