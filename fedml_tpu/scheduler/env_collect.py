"""Environment collector — `fedml_tpu env`.

Parity target: ``computing/scheduler/env/collect_env.py`` (prints
fedml/torch/GPU environment at init). TPU edition reports the JAX stack
and visible accelerators instead of torch/CUDA.
"""
from __future__ import annotations

import platform
import sys
from typing import Dict


def collect_env() -> Dict:
    info: Dict = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
    }
    try:
        import fedml_tpu

        info["fedml_tpu"] = getattr(fedml_tpu, "__version__", "dev")
    except Exception as e:
        info["fedml_tpu"] = f"import error: {e}"
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = __import__(mod)
            info[mod] = getattr(m, "__version__", "?")
        except Exception:
            info[mod] = "absent"
    try:
        import jax

        devs = jax.devices()
        info["devices"] = [f"{d.device_kind}:{d.id}" for d in devs]
        info["default_backend"] = jax.default_backend()
    except Exception as e:
        info["devices"] = f"unavailable: {e}"
    return info


def collect_resources() -> Dict:
    """Compact accelerator inventory for scheduler heartbeats (parity:
    the reference agents report GPU inventory into the compute cache,
    ``scheduler_core/compute_gpu_db.py``)."""
    out: Dict = {"platform": "cpu", "device_count": 0, "device_kind": ""}
    try:
        import jax

        devs = jax.devices()
        out["platform"] = jax.default_backend()
        out["device_count"] = len(devs)
        out["device_kind"] = devs[0].device_kind if devs else ""
    except Exception as e:
        out["error"] = str(e)
    return out


def print_env() -> None:
    for k, v in collect_env().items():
        print(f"{k:>18}: {v}")
