"""Environment collector — `fedml_tpu env`.

Parity target: ``computing/scheduler/env/collect_env.py`` (prints
fedml/torch/GPU environment at init). TPU edition reports the JAX stack
and visible accelerators instead of torch/CUDA.
"""
from __future__ import annotations

import json
import os
import platform
import stat
import subprocess
import sys
import tempfile
import time
from typing import Dict, Optional


def collect_env() -> Dict:
    info: Dict = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
    }
    try:
        import fedml_tpu

        info["fedml_tpu"] = getattr(fedml_tpu, "__version__", "dev")
    except Exception as e:
        info["fedml_tpu"] = f"import error: {e}"
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = __import__(mod)
            info[mod] = getattr(m, "__version__", "?")
        except Exception:
            info[mod] = "absent"
    try:
        import jax

        devs = jax.devices()
        info["devices"] = [f"{d.device_kind}:{d.id}" for d in devs]
        info["default_backend"] = jax.default_backend()
    except Exception as e:
        info["devices"] = f"unavailable: {e}"
    return info


def collect_resources() -> Dict:
    """Compact accelerator inventory for scheduler heartbeats (parity:
    the reference agents report GPU inventory into the compute cache,
    ``scheduler_core/compute_gpu_db.py``)."""
    out: Dict = {"platform": "cpu", "device_count": 0, "device_kind": ""}
    try:
        import jax

        devs = jax.devices()
        out["platform"] = jax.default_backend()
        out["device_count"] = len(devs)
        out["device_kind"] = devs[0].device_kind if devs else ""
        try:
            # per-device HBM ceiling — the job plane's admission figure
            # (PR 10 programs.jsonl peak-HBM is judged against this);
            # absent on backends without memory_stats (CPU) → admission
            # treats the node as unconstrained
            limit = devs[0].memory_stats().get("bytes_limit") if devs else None
            if limit:
                out["hbm_bytes_limit"] = float(limit)
        except Exception:
            pass
    except Exception as e:
        out["error"] = str(e)
    return out


_probe_cache: Optional[Dict] = None


def _probe_cache_path() -> Optional[str]:
    """Path for the probe disk cache inside a per-uid 0700 dir, or None
    (in-memory only) when the dir can't be trusted — e.g. pre-created by
    another user, a symlink, or group/world-accessible."""
    uid = os.getuid() if hasattr(os, "getuid") else 0
    d = os.path.join(tempfile.gettempdir(), f"fedml_tpu_probe_{uid}")
    try:
        os.mkdir(d, 0o700)
    except FileExistsError:
        pass
    except OSError:
        return None
    try:
        st = os.lstat(d)
        if (not stat.S_ISDIR(st.st_mode) or st.st_uid != uid
                or (st.st_mode & 0o077)):
            return None
    except OSError:
        return None
    return os.path.join(d, "resource_probe.json")


def collect_resources_probe(timeout_s: float = 60.0) -> Dict:
    """``collect_resources()`` in a short-lived subprocess, memoized.

    Agent daemons must NOT call ``jax.devices()`` in-process: on TPU
    hosts it acquires libtpu exclusively, so the training job the agent
    spawns next would fail device init (the reference has the same
    split — agents shell out to nvidia-smi rather than importing torch).
    """
    global _probe_cache
    if _probe_cache is not None:
        return dict(_probe_cache)
    # explicit override (tests, constrained deploys): skip the probe
    override = os.environ.get("FEDML_TPU_RESOURCES")
    if override:
        try:
            _probe_cache = json.loads(override)
            return dict(_probe_cache)
        except ValueError:
            pass
    # cross-process disk cache: one probe per machine per TTL, not one
    # per agent construction. The cache lives in a per-uid 0700 directory:
    # the shared tempdir is world-writable, so a flat fixed name could be
    # pre-created (poisoning or silently breaking os.replace under the
    # sticky bit) or planted as a symlink by another user.
    cache_path = _probe_cache_path()
    try:
        if cache_path and time.time() - os.path.getmtime(cache_path) < 600:
            with open(cache_path) as f:
                _probe_cache = json.load(f)
            return dict(_probe_cache)
    except (OSError, ValueError):
        pass
    code = (
        "import json; from fedml_tpu.scheduler.env_collect import "
        "collect_resources; print(json.dumps(collect_resources()))"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, check=True,
        )
        _probe_cache = json.loads(out.stdout.strip().splitlines()[-1])
        if cache_path:
            try:
                fd, tmp = tempfile.mkstemp(dir=os.path.dirname(cache_path))
                with os.fdopen(fd, "w") as f:
                    json.dump(_probe_cache, f)
                os.replace(tmp, cache_path)
            except OSError:
                pass
    except Exception as e:
        # do NOT memoize a transient failure: a long-lived agent must not
        # report zero accelerators forever because one probe timed out
        return {"platform": "unknown", "device_count": 0,
                "device_kind": "", "error": str(e)}
    return dict(_probe_cache)


def print_env() -> None:
    for k, v in collect_env().items():
        print(f"{k:>18}: {v}")
