"""Launch manager — job yaml → running job on the local agent.

Parity target: ``scheduler_entry/launch_manager.py`` (package app → match
resources → dispatch). With no hosted backend, "matching" is a local
capacity check against visible accelerators, and dispatch goes straight to
the in-process LocalAgent; the module-level agent keeps `fedml_tpu launch`
/ `fedml_tpu stop` CLI invocations coherent within one process.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from fedml_tpu.scheduler.agent import LocalAgent
from fedml_tpu.scheduler.job_yaml import JobSpec

logger = logging.getLogger(__name__)

_agents: Dict[str, LocalAgent] = {}


def get_agent(workdir: str = ".fedml_runs") -> LocalAgent:
    import os

    key = os.path.abspath(workdir)
    if key not in _agents:
        _agents[key] = LocalAgent(workdir=workdir).start()
    return _agents[key]


def check_resources(spec: JobSpec) -> None:
    """Local capacity check (the reference's resource matcher, degenerated
    to one host): fail fast when the job demands more chips than visible."""
    want = int(spec.computing.get("minimum_num_chips", 0) or 0)
    if want <= 0:
        return
    try:
        import jax

        have = jax.device_count()
    except Exception:
        have = 0
    if have < want:
        raise RuntimeError(
            f"job '{spec.job_name}' wants {want} chips; host has {have}"
        )


def launch_job(yaml_path: str, workdir: str = ".fedml_runs",
               run_id: Optional[str] = None,
               extra_env: Optional[Dict[str, str]] = None) -> str:
    spec = JobSpec.load(yaml_path)
    check_resources(spec)
    agent = get_agent(workdir)
    rid = agent.start_run(spec, run_id=run_id, extra_env=extra_env)
    logger.info("launched job '%s' as %s", spec.job_name, rid)
    return rid


def run_stop(run_id: str, workdir: str = ".fedml_runs") -> bool:
    return get_agent(workdir).kill(run_id)


def run_status(run_id: str, workdir: str = ".fedml_runs") -> Optional[str]:
    return get_agent(workdir).status(run_id)


def run_logs(run_id: str, tail: Optional[int] = None,
             workdir: str = ".fedml_runs") -> str:
    return get_agent(workdir).logs(run_id, tail=tail)


def list_jobs(workdir: str = ".fedml_runs") -> List[Dict]:
    return get_agent(workdir).list_runs()
