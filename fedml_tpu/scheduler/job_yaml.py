"""Job YAML spec — what `fedml_tpu launch <job.yaml>` consumes.

Parity target: the reference's launch job yaml handled by
``scheduler_entry/launch_manager.py`` (job/bootstrap shell blocks,
workspace, computing resources). The TPU build keeps the same shape:

    job_name: my-experiment
    workspace: .                 # cwd for the job process
    bootstrap: |                 # optional one-time setup shell
      echo preparing
    job: |                       # the job shell (required)
      python my_train.py --cf fedml_config.yaml
    computing:
      minimum_num_chips: 0       # informational on a single host
    env:                         # extra environment for the job
      MY_FLAG: "1"
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

import yaml


@dataclasses.dataclass
class JobSpec:
    job_name: str
    job: str
    workspace: str = "."
    bootstrap: Optional[str] = None
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    computing: Dict = dataclasses.field(default_factory=dict)

    @staticmethod
    def load(path: str) -> "JobSpec":
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        if not raw.get("job"):
            raise ValueError(f"{path}: job yaml must define a 'job' shell block")
        workspace = raw.get("workspace", ".")
        if not os.path.isabs(workspace):
            workspace = os.path.normpath(
                os.path.join(os.path.dirname(os.path.abspath(path)), workspace)
            )
        return JobSpec(
            job_name=str(raw.get("job_name", os.path.basename(path))),
            job=str(raw["job"]),
            workspace=workspace,
            bootstrap=raw.get("bootstrap"),
            env={k: str(v) for k, v in (raw.get("env") or {}).items()},
            computing=raw.get("computing") or {},
        )
