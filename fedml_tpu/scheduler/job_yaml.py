"""Job YAML spec — what `fedml_tpu launch <job.yaml>` consumes.

Parity target: the reference's launch job yaml handled by
``scheduler_entry/launch_manager.py`` (job/bootstrap shell blocks,
workspace, computing resources). The TPU build keeps the same shape:

    job_name: my-experiment
    workspace: .                 # cwd for the job process
    bootstrap: |                 # optional one-time setup shell
      echo preparing
    job: |                       # the job shell (required)
      python my_train.py --cf fedml_config.yaml
    computing:
      minimum_num_chips: 0       # informational on a single host
      peak_hbm_bytes: 0          # admission figure (or programs_jsonl:
                                 # a PR 10 programs.jsonl to read it from)
    env:                         # extra environment for the job
      MY_FLAG: "1"
    durable: true                # job checkpoints/journals its state:
                                 # preempt/node-loss reschedule + resume
    restart:                     # supervision policy (see supervision.py)
      max_restarts: 3
      backoff_s: 0.5
      crash_loop_threshold: 3
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

import yaml


@dataclasses.dataclass
class JobSpec:
    job_name: str
    job: str
    workspace: str = "."
    bootstrap: Optional[str] = None
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    computing: Dict = dataclasses.field(default_factory=dict)
    # job plane: restart supervision policy (dict, see RestartPolicy) and
    # the durable flag — a durable job owns checkpoint/journal state, so
    # preemption and node loss reschedule-and-resume it instead of
    # failing the job
    restart: Optional[Dict] = None
    durable: bool = False

    def wire(self) -> Dict:
        """The JSON shape shipped over the scheduler control plane."""
        return {"job_name": self.job_name, "job": self.job,
                "workspace": self.workspace, "bootstrap": self.bootstrap,
                "env": self.env, "computing": self.computing,
                "restart": self.restart, "durable": self.durable}

    @classmethod
    def from_wire(cls, raw: Dict, default_name: str = "job") -> "JobSpec":
        raw = raw or {}
        return cls(
            job_name=str(raw.get("job_name", default_name)),
            job=str(raw.get("job", "")),
            workspace=str(raw.get("workspace", ".")),
            bootstrap=raw.get("bootstrap"),
            env={k: str(v) for k, v in (raw.get("env") or {}).items()},
            computing=raw.get("computing") or {},
            restart=raw.get("restart") or None,
            durable=bool(raw.get("durable", False)),
        )

    @staticmethod
    def load(path: str) -> "JobSpec":
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        if not raw.get("job"):
            raise ValueError(f"{path}: job yaml must define a 'job' shell block")
        workspace = raw.get("workspace", ".")
        if not os.path.isabs(workspace):
            workspace = os.path.normpath(
                os.path.join(os.path.dirname(os.path.abspath(path)), workspace)
            )
        # one field list: the yaml path and the control-plane wire path
        # construct through the same coercions, so a new spec field can't
        # silently exist on only one of them
        return JobSpec.from_wire({**raw, "workspace": workspace},
                                 default_name=os.path.basename(path))
