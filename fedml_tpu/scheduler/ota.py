"""OTA upgrade — staged code updates for node agents.

Parity target: ``slave/client_daemon.py:48`` ``daemon_ota_upgrade`` (the
reference's agents pull a newer fedml package and restart themselves).
Re-design for this build: the master ships a code package (zip) through
the object store, each node agent STAGES it — unpack to a versioned
directory, record ``pending_upgrade.json`` — and applies it on its next
restart by prepending the staged directory to PYTHONPATH. Staging and
applying are split on purpose: an agent mid-run must not yank its own
code, and a bad package must be inspectable rather than half-installed.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

from fedml_tpu.deploy.model_cards import FedMLModelCards

PENDING_FILE = "pending_upgrade.json"


def stage_upgrade(store, package_key: str, version: str,
                  workdir: str) -> Dict:
    """Fetch + unpack the package; record it as the pending upgrade."""
    ota_root = os.path.join(os.path.abspath(workdir), "ota")
    target = os.path.join(ota_root, str(version))
    os.makedirs(ota_root, exist_ok=True)
    zip_path = target + ".zip"
    with open(zip_path, "wb") as f:
        f.write(store.get_object(package_key))
    FedMLModelCards.unpack(zip_path, target)  # zip-slip-guarded extract
    os.unlink(zip_path)
    record = {"version": str(version), "path": target,
              "staged_at": time.time()}
    tmp = os.path.join(ota_root, PENDING_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(record, f)
    os.replace(tmp, os.path.join(ota_root, PENDING_FILE))
    return record


def pending_upgrade(workdir: str) -> Optional[Dict]:
    path = os.path.join(os.path.abspath(workdir), "ota", PENDING_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def apply_env(workdir: str, env: Dict[str, str]) -> Dict[str, str]:
    """Apply a staged upgrade to a child-process environment: the staged
    code dir leads PYTHONPATH (how the agent's next restart — and every
    job process it spawns — picks the new code up)."""
    staged = pending_upgrade(workdir)
    if staged and os.path.isdir(staged["path"]):
        env = dict(env)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (staged["path"], env.get("PYTHONPATH")) if p)
        env["FEDML_OTA_VERSION"] = staged["version"]
    return env
