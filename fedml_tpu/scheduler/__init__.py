"""Compute plane — local agent daemon, launch manager, job yaml, env.

Parity: reference ``computing/scheduler/`` (slave/master agents,
scheduler_entry launch path) in the thin single-host shape SURVEY §7.8
plans: job-yaml runner + agent daemon + local metrics sink.
"""
from fedml_tpu.scheduler.agent import LocalAgent
from fedml_tpu.scheduler.env_collect import collect_env
from fedml_tpu.scheduler.job_yaml import JobSpec
from fedml_tpu.scheduler.launch import (
    launch_job,
    list_jobs,
    run_logs,
    run_status,
    run_stop,
)

__all__ = [
    "LocalAgent",
    "JobSpec",
    "collect_env",
    "launch_job",
    "list_jobs",
    "run_logs",
    "run_status",
    "run_stop",
]
