"""Job plane — supervising agents, preemption, launch manager, job yaml.

Parity: reference ``computing/scheduler/`` (slave/master agents,
scheduler_entry launch path), grown past observation into supervision:
agents restart crashed runs (exponential backoff, crash-loop
containment), `preempt` quiesces a run for preemptible-capacity
reclaims, masters reschedule preempted/lost durable jobs onto surviving
nodes (peak-HBM-gated admission) where they resume from their PR 12
write-ahead journals. See docs/scheduler.md.
"""
from fedml_tpu.scheduler.agent import LocalAgent
from fedml_tpu.scheduler.env_collect import collect_env
from fedml_tpu.scheduler.job_yaml import JobSpec
from fedml_tpu.scheduler.launch import (
    launch_job,
    list_jobs,
    run_logs,
    run_status,
    run_stop,
)
from fedml_tpu.scheduler.preempt import run_preempt_scenario
from fedml_tpu.scheduler.supervision import (
    RestartPolicy,
    RestartTracker,
    peak_hbm_from_programs,
)

__all__ = [
    "LocalAgent",
    "JobSpec",
    "RestartPolicy",
    "RestartTracker",
    "collect_env",
    "launch_job",
    "list_jobs",
    "peak_hbm_from_programs",
    "run_logs",
    "run_preempt_scenario",
    "run_status",
    "run_stop",
]
