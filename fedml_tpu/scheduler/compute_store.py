"""Cross-run compute cache — sqlite-backed scheduler state.

Parity targets (reference ``computing/scheduler/scheduler_core/``):
  ``compute_cache_manager.py`` — redis+sqlite cross-run caches of run
  info, GPU availability, logs and metrics;
  ``compute_gpu_db.py``       — per-device inventory DB;
  ``log_manager.py`` / ``metrics_manager.py`` — query surfaces over the
  stored logs/metrics.

TPU-era redesign: one sqlite file in the scheduler workdir (WAL mode, so
agents, CLI and monitors in different processes read/write concurrently —
the reference also leans on sqlite for exactly this, redis being optional
infra we don't assume). Inventory rows come from ``collect_resources()``
(jax device census: TPU chips on real hardware, virtual CPU devices in
tests) instead of nvidia-smi.
"""
from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from fedml_tpu.scheduler.env_collect import collect_resources

_SCHEMA = """
CREATE TABLE IF NOT EXISTS devices (
    node_id     TEXT NOT NULL,
    platform    TEXT NOT NULL,
    device_kind TEXT NOT NULL DEFAULT '',
    device_count INTEGER NOT NULL DEFAULT 0,
    extra       TEXT NOT NULL DEFAULT '{}',
    updated_at  REAL NOT NULL,
    PRIMARY KEY (node_id)
);
CREATE TABLE IF NOT EXISTS runs (
    run_id      TEXT PRIMARY KEY,
    job_name    TEXT NOT NULL DEFAULT '',
    node_id     TEXT NOT NULL DEFAULT '',
    status      TEXT NOT NULL DEFAULT 'IDLE',
    pid         INTEGER,
    returncode  INTEGER,
    log_path    TEXT NOT NULL DEFAULT '',
    started_at  REAL,
    finished_at REAL,
    restarts    INTEGER NOT NULL DEFAULT 0,
    reason      TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS metrics (
    run_id  TEXT NOT NULL,
    ts      REAL NOT NULL,
    name    TEXT NOT NULL,
    value   REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS metrics_by_run ON metrics (run_id, name, ts);
"""


class ComputeStore:
    """One sqlite handle per process; safe for many processes via WAL."""

    def __init__(self, workdir: str = ".fedml_runs",
                 filename: str = "compute_cache.sqlite"):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.path = os.path.join(self.workdir, filename)
        self._local = threading.local()
        with self._conn() as c:
            c.executescript(_SCHEMA)
            # pre-job-plane stores lack the supervision columns; ALTER is
            # idempotent-by-catch (sqlite has no ADD COLUMN IF NOT EXISTS)
            for ddl in (
                "ALTER TABLE runs ADD COLUMN restarts INTEGER NOT NULL DEFAULT 0",
                "ALTER TABLE runs ADD COLUMN reason TEXT NOT NULL DEFAULT ''",
            ):
                try:
                    c.execute(ddl)
                except sqlite3.OperationalError:
                    pass  # duplicate column: schema already current

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path, timeout=10.0)
            conn.row_factory = sqlite3.Row
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
        return conn

    # -- inventory (compute_gpu_db parity) -----------------------------
    def record_inventory(self, node_id: str,
                         resources: Optional[Dict] = None) -> Dict:
        res = dict(resources if resources is not None else collect_resources())
        known = {k: res.pop(k, d) for k, d in
                 (("platform", "cpu"), ("device_kind", ""), ("device_count", 0))}
        with self._conn() as c:
            c.execute(
                "INSERT OR REPLACE INTO devices VALUES (?,?,?,?,?,?)",
                (node_id, known["platform"], known["device_kind"],
                 int(known["device_count"]), json.dumps(res), time.time()),
            )
        return {**known, **res}

    def inventory(self, max_age_s: Optional[float] = None) -> List[Dict]:
        q = "SELECT * FROM devices"
        params: tuple = ()
        if max_age_s is not None:
            q += " WHERE updated_at >= ?"
            params = (time.time() - max_age_s,)
        rows = self._conn().execute(q + " ORDER BY node_id", params).fetchall()
        return [
            {**dict(r), "extra": json.loads(r["extra"])} for r in rows
        ]

    def total_devices(self, platform: Optional[str] = None) -> int:
        rows = self.inventory()
        return sum(r["device_count"] for r in rows
                   if platform is None or r["platform"] == platform)

    # -- run history (compute_cache_manager parity) --------------------
    def upsert_run(self, run_id: str, **fields: Any) -> None:
        allowed = {"job_name", "node_id", "status", "pid", "returncode",
                   "log_path", "started_at", "finished_at", "restarts",
                   "reason"}
        bad = set(fields) - allowed
        if bad:
            raise ValueError(f"unknown run fields: {sorted(bad)}")
        with self._conn() as c:
            c.execute("INSERT OR IGNORE INTO runs (run_id, started_at) VALUES (?,?)",
                      (run_id, time.time()))
            if fields:
                sets = ", ".join(f"{k}=?" for k in fields)
                c.execute(f"UPDATE runs SET {sets} WHERE run_id=?",
                          (*fields.values(), run_id))

    def finish_run(self, run_id: str, status: str,
                   returncode: Optional[int] = None) -> None:
        self.upsert_run(run_id, status=status, returncode=returncode,
                        finished_at=time.time())

    def get_run(self, run_id: str) -> Optional[Dict]:
        row = self._conn().execute(
            "SELECT * FROM runs WHERE run_id=?", (run_id,)).fetchone()
        return dict(row) if row else None

    def runs(self, status: Optional[str] = None,
             limit: Optional[int] = None) -> List[Dict]:
        # sqlite: LIMIT -1 = unlimited — the sweeper and `jobs --history`
        # must see every row, not a silently-truncated window
        lim = -1 if limit is None else limit
        if status is None:
            rows = self._conn().execute(
                "SELECT * FROM runs ORDER BY started_at DESC LIMIT ?",
                (lim,)).fetchall()
        else:
            rows = self._conn().execute(
                "SELECT * FROM runs WHERE status=? "
                "ORDER BY started_at DESC LIMIT ?", (status, lim)).fetchall()
        return [dict(r) for r in rows]

    # -- metrics (metrics_manager parity) ------------------------------
    def log_metric(self, run_id: str, name: str, value: float,
                   ts: Optional[float] = None) -> None:
        with self._conn() as c:
            c.execute("INSERT INTO metrics VALUES (?,?,?,?)",
                      (run_id, ts if ts is not None else time.time(),
                       name, float(value)))

    def metrics(self, run_id: str, name: Optional[str] = None) -> List[Dict]:
        if name is None:
            rows = self._conn().execute(
                "SELECT * FROM metrics WHERE run_id=? ORDER BY ts",
                (run_id,)).fetchall()
        else:
            rows = self._conn().execute(
                "SELECT * FROM metrics WHERE run_id=? AND name=? ORDER BY ts",
                (run_id, name)).fetchall()
        return [dict(r) for r in rows]

    def latest_metric(self, run_id: str, name: str) -> Optional[float]:
        row = self._conn().execute(
            "SELECT value FROM metrics WHERE run_id=? AND name=? "
            "ORDER BY ts DESC LIMIT 1", (run_id, name)).fetchone()
        return None if row is None else row["value"]

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None
