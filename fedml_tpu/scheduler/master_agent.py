"""Master agent — multi-node run orchestration.

Parity target: ``master/server_runner.py`` (``FedMLServerRunner`` :68 —
``run`` :427 drives a run across edges, ``callback_start_train`` :1462;
status aggregation back from the slaves). Re-design: the master keeps a
node registry fed by broker heartbeats, fans a multi-rank job out as one
run per node (each rank gets FEDML_RANK/FEDML_NUM_RANKS env — the
TPU-era replacement for the reference's MQTT-dispatched train configs),
aggregates per-rank status FSMs into one job status, detects dead nodes
by heartbeat loss, and pulls every rank's logs into one run view.

Job status semantics:
  RUNNING  while any rank is non-terminal and no rank has failed
  FINISHED when ALL ranks finished
  FAILED   as soon as any rank FAILED/EXCEPTION, or its node went dark
  KILLED   after stop_job()
"""
from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from typing import Dict, List, Optional

from fedml_tpu.core.distributed.communication.broker import BrokerClient
from fedml_tpu.core.mlops.status import RunStatus
from fedml_tpu.scheduler.job_yaml import JobSpec

logger = logging.getLogger(__name__)


class JobView:
    """Aggregated state of one multi-rank job."""

    def __init__(self, job_id: str, ranks: Dict[str, str]):
        self.job_id = job_id
        self.ranks = ranks  # run_id → node_id
        self.rank_status: Dict[str, str] = {r: RunStatus.QUEUED for r in ranks}
        self.rank_rc: Dict[str, Optional[int]] = {r: None for r in ranks}
        self.logs: Dict[str, str] = {}
        self.stopped = False

    @property
    def status(self) -> str:
        statuses = set(self.rank_status.values())
        if self.stopped:
            return RunStatus.KILLED
        if statuses & {RunStatus.FAILED, RunStatus.EXCEPTION}:
            return RunStatus.FAILED
        if RunStatus.KILLED in statuses:
            return RunStatus.KILLED
        if statuses == {RunStatus.FINISHED}:
            return RunStatus.FINISHED
        return RunStatus.RUNNING

    @property
    def is_terminal(self) -> bool:
        return self.status in RunStatus.TERMINAL

    def describe(self) -> Dict:
        return {
            "job_id": self.job_id,
            "status": self.status,
            "ranks": [
                {"run_id": rid, "node_id": self.ranks[rid],
                 "status": self.rank_status[rid],
                 "returncode": self.rank_rc[rid]}
                for rid in sorted(self.ranks)
            ],
        }


class MasterAgent:
    def __init__(self, broker_host: str, broker_port: int,
                 cluster: str = "default", node_timeout_s: float = 5.0):
        self.cluster = cluster
        self.node_timeout_s = node_timeout_s
        self.nodes: Dict[str, Dict] = {}  # node_id → {last_seen, slots}
        self.jobs: Dict[str, JobView] = {}
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._log_events: Dict[str, threading.Event] = {}
        self._client = BrokerClient(broker_host, broker_port)
        self._client.subscribe(f"sched/{cluster}/master", self._on_message)
        self._watch: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "MasterAgent":
        if self._watch is None:
            self._watch = threading.Thread(target=self._watch_loop, daemon=True)
            self._watch.start()
        return self

    def shutdown(self) -> None:
        self._stopping.set()
        self._client.close()

    # -- node registry ----------------------------------------------------
    def live_nodes(self) -> List[str]:
        now = time.time()
        with self._lock:
            return sorted(n for n, info in self.nodes.items()
                          if now - info["last_seen"] < self.node_timeout_s)

    def wait_for_nodes(self, n: int, timeout: float = 30.0) -> List[str]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            live = self.live_nodes()
            if len(live) >= n:
                return live
            time.sleep(0.1)
        raise TimeoutError(
            f"only {len(self.live_nodes())}/{n} nodes online")

    # -- job control ------------------------------------------------------
    def submit_job(self, spec: JobSpec, n_ranks: int = 1,
                   nodes: Optional[List[str]] = None,
                   extra_env: Optional[Dict[str, Dict[str, str]]] = None,
                   ) -> str:
        """Fan ``spec`` out as ``n_ranks`` runs over the given (or all
        live) nodes, round-robin. Each rank's process sees FEDML_RANK /
        FEDML_NUM_RANKS / FEDML_JOB_ID; ``extra_env`` maps rank (as str)
        to additional env overrides."""
        live = self.live_nodes()
        if nodes:
            missing = sorted(set(nodes) - set(live))
            if missing:
                raise RuntimeError(
                    f"requested nodes not online: {missing} (live: {live})")
        targets = nodes or live
        if not targets:
            raise RuntimeError("no live nodes to schedule on")
        job_id = uuid.uuid4().hex[:10]
        ranks: Dict[str, str] = {}
        assignments = []
        for rank in range(n_ranks):
            node_id = targets[rank % len(targets)]
            run_id = f"{job_id}-r{rank}"
            ranks[run_id] = node_id
            env = {
                "FEDML_JOB_ID": job_id,
                "FEDML_RANK": str(rank),
                "FEDML_NUM_RANKS": str(n_ranks),
            }
            env.update((extra_env or {}).get(str(rank), {}))
            assignments.append((node_id, run_id, env))
        view = JobView(job_id, ranks)
        with self._lock:
            self.jobs[job_id] = view
        for node_id, run_id, env in assignments:
            self._send(node_id, {
                "type": "start_run", "run_id": run_id,
                "spec": {
                    "job_name": spec.job_name, "job": spec.job,
                    "workspace": spec.workspace,
                    "bootstrap": spec.bootstrap, "env": spec.env,
                },
                "env": env,
            })
        return job_id

    def stop_job(self, job_id: str) -> bool:
        view = self.jobs.get(job_id)
        if view is None:
            return False
        view.stopped = True
        for run_id, node_id in view.ranks.items():
            self._send(node_id, {"type": "stop_run", "run_id": run_id})
        return True

    def job_status(self, job_id: str) -> Optional[Dict]:
        view = self.jobs.get(job_id)
        return view.describe() if view else None

    def wait_job(self, job_id: str, timeout: float = 600.0) -> Dict:
        deadline = time.time() + timeout
        while time.time() < deadline:
            view = self.jobs.get(job_id)
            if view is not None and view.is_terminal:
                return view.describe()
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} not terminal after {timeout}s")

    def job_logs(self, job_id: str, tail: Optional[int] = 200,
                 timeout: float = 10.0) -> Dict[str, str]:
        """One run view: pull each rank's log from its node."""
        view = self.jobs.get(job_id)
        if view is None:
            return {}
        pending = []
        for run_id, node_id in view.ranks.items():
            event = threading.Event()
            self._log_events[run_id] = event
            pending.append((run_id, event))
            self._send(node_id, {"type": "get_logs", "run_id": run_id,
                                 "tail": tail})
        deadline = time.time() + timeout
        for run_id, event in pending:
            event.wait(timeout=max(0.0, deadline - time.time()))
            self._log_events.pop(run_id, None)
        return dict(view.logs)

    # -- internals --------------------------------------------------------
    def _send(self, node_id: str, msg: Dict) -> None:
        self._client.publish(f"sched/{self.cluster}/node/{node_id}",
                             json.dumps(msg).encode())

    def _on_message(self, body: bytes) -> None:
        try:
            msg = json.loads(body)
        except ValueError:
            return
        mtype = msg.get("type")
        nid = str(msg.get("node_id", ""))
        if mtype in ("node_online", "heartbeat"):
            with self._lock:
                info = self.nodes.setdefault(nid, {"slots": 1})
                info["last_seen"] = time.time()
                if "slots" in msg:
                    info["slots"] = int(msg["slots"])
        elif mtype == "run_status":
            rid = str(msg["run_id"])
            for view in self.jobs.values():
                if rid in view.rank_status:
                    view.rank_status[rid] = str(msg["status"])
                    view.rank_rc[rid] = msg.get("returncode")
                    break
        elif mtype == "run_logs":
            rid = str(msg["run_id"])
            for view in self.jobs.values():
                if rid in view.ranks:
                    view.logs[rid] = str(msg.get("data", ""))
                    break
            event = self._log_events.get(rid)
            if event is not None:
                event.set()

    def _watch_loop(self) -> None:
        """Dead-node detection: a node that stops heartbeating takes its
        non-terminal ranks to FAILED (the reference master's edge-offline
        handling)."""
        while not self._stopping.is_set():
            now = time.time()
            with self._lock:
                dark = {n for n, info in self.nodes.items()
                        if now - info["last_seen"] >= self.node_timeout_s}
                views = list(self.jobs.values())
            for view in views:
                for rid, node_id in view.ranks.items():
                    if (node_id in dark
                            and view.rank_status[rid] not in RunStatus.TERMINAL):
                        logger.warning("job %s rank %s lost: node %s dark",
                                       view.job_id, rid, node_id)
                        view.rank_status[rid] = RunStatus.FAILED
            time.sleep(0.5)
