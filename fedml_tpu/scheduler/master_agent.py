"""Master agent — multi-node run orchestration + the supervising job plane.

Parity target: ``master/server_runner.py`` (``FedMLServerRunner`` :68 —
``run`` :427 drives a run across edges, ``callback_start_train`` :1462;
status aggregation back from the slaves). Re-design: the master keeps a
node registry fed by broker heartbeats, fans a multi-rank job out as one
run per node (each rank gets FEDML_RANK/FEDML_NUM_RANKS env — the
TPU-era replacement for the reference's MQTT-dispatched train configs),
aggregates per-rank status FSMs into one job status, detects dead nodes
by heartbeat loss, and pulls every rank's logs into one run view.

Job-plane semantics (preemptible capacity):

* **preemption** — :meth:`drain_node` SIGTERM-quiesces every run on a
  node (``preempt_run`` verb to the node agent) and, for *durable* jobs,
  reschedules each preempted rank onto a surviving node where it resumes
  from its journal/checkpoints. A node agent may also preempt locally on
  a reclaim notice (``drain_node`` wire message): the master reacts to
  the PREEMPTED status report the same way, so reschedule-and-resume
  works whichever side noticed the reclaim first.
* **node loss** — a node silent past ``node_loss_deadline_s`` (tracked by
  the PR 5 :class:`~fedml_tpu.resilience.liveness.PeerLiveness`) has its
  RUNNING durable ranks declared lost and rescheduled onto survivors;
  non-durable ranks go FAILED at the (shorter) heartbeat timeout exactly
  as before. A lost node that comes back is readmitted, and any
  superseded run it still reports RUNNING is told to stop.
* **admission** — rescheduling (and initial placement) is gated on the
  job's peak-HBM figure (``computing.peak_hbm_bytes``, or read from a
  PR 10 ``programs.jsonl`` via ``computing.programs_jsonl``) against the
  target node's advertised ``hbm_bytes_limit``, so a resumed job can't
  land on a node without headroom.

Job status semantics:
  RUNNING  while any active rank is non-terminal and no rank has failed
           (a PREEMPTED rank awaiting reschedule counts as in-flight)
  FINISHED when ALL active ranks finished
  FAILED   as soon as any active rank FAILED/EXCEPTION, or a rank could
           not be rescheduled
  KILLED   after stop_job()
"""
from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Dict, List, Optional, Set

from fedml_tpu.core.distributed.communication.broker_agent import (
    BrokerJsonAgent,
    PeerRegistry,
)
from fedml_tpu.core.mlops.status import RunStatus
from fedml_tpu.resilience.liveness import PeerLiveness
from fedml_tpu.scheduler.job_yaml import JobSpec
from fedml_tpu.scheduler.supervision import peak_hbm_from_programs, sched_event

logger = logging.getLogger(__name__)


def job_hbm_demand(spec: JobSpec) -> float:
    """Per-rank peak-HBM admission figure for a job: the explicit
    ``computing.peak_hbm_bytes``, else the max over a referenced PR 10
    ``programs.jsonl`` catalog, else 0 (unknown → unconstrained)."""
    comp = spec.computing or {}
    explicit = float(comp.get("peak_hbm_bytes", 0) or 0)
    if explicit:
        return explicit
    ref = comp.get("programs_jsonl")
    if ref:
        return float(peak_hbm_from_programs(str(ref)) or 0.0)
    return 0.0


class JobView:
    """Aggregated state of one multi-rank job."""

    def __init__(self, job_id: str, ranks: Dict[str, str],
                 spec: Optional[JobSpec] = None,
                 rank_env: Optional[Dict[str, Dict[str, str]]] = None):
        self.job_id = job_id
        self.ranks = ranks  # run_id → node_id
        self.rank_status: Dict[str, str] = {r: RunStatus.QUEUED for r in ranks}
        self.rank_rc: Dict[str, Optional[int]] = {r: None for r in ranks}
        self.rank_env: Dict[str, Dict[str, str]] = dict(rank_env or {})
        self.logs: Dict[str, str] = {}
        self.stopped = False
        self.spec = spec
        self.durable = bool(spec.durable) if spec is not None else False
        self.hbm_demand = job_hbm_demand(spec) if spec is not None else 0.0
        # runs replaced by a rescheduled successor: excluded from the job
        # status aggregation, remembered so a returning node's stale
        # RUNNING report can be told to stop
        self.superseded: Set[str] = set()
        self.resched_map: Dict[str, str] = {}   # old run_id → new run_id
        self.resched_count: Dict[str, int] = {}  # base run_id → attempts
        self.resched_refused: Set[str] = set()   # no admissible node
        self.lost_pending: Dict[str, float] = {}  # run_id → declared-lost ts

    def active_statuses(self) -> Dict[str, str]:
        return {r: s for r, s in self.rank_status.items()
                if r not in self.superseded}

    @property
    def status(self) -> str:
        active = self.active_statuses()
        statuses = set(active.values())
        if self.stopped:
            return RunStatus.KILLED
        # PREEMPTED is in-flight ONLY while a reschedule can still
        # supersede it; a preempted rank that can never resume — the job
        # is not durable (nothing to resume), or its reschedule was
        # refused (no admissible node / budget exhausted) — is a failure,
        # or wait_job would report RUNNING forever
        unresumable = any(
            s == RunStatus.PREEMPTED
            and (not self.durable
                 or (r in self.resched_refused
                     and r not in self.lost_pending))
            for r, s in active.items())
        if statuses & {RunStatus.FAILED, RunStatus.EXCEPTION} or unresumable:
            return RunStatus.FAILED
        if RunStatus.KILLED in statuses:
            return RunStatus.KILLED
        if statuses == {RunStatus.FINISHED}:
            return RunStatus.FINISHED
        # RESTARTING (agent-local backoff) is likewise in-flight
        return RunStatus.RUNNING

    @property
    def is_terminal(self) -> bool:
        return self.status in RunStatus.TERMINAL

    def describe(self) -> Dict:
        return {
            "job_id": self.job_id,
            "status": self.status,
            "durable": self.durable,
            "ranks": [
                {"run_id": rid, "node_id": self.ranks[rid],
                 "status": self.rank_status[rid],
                 "returncode": self.rank_rc[rid],
                 "superseded": rid in self.superseded}
                for rid in sorted(self.ranks)
            ],
            "rescheduled": dict(self.resched_map),
        }


class MasterAgent(BrokerJsonAgent):
    def __init__(self, broker_host: str, broker_port: int,
                 cluster: str = "default", node_timeout_s: float = 5.0,
                 node_loss_deadline_s: Optional[float] = None,
                 max_reschedules: int = 3,
                 reschedule_patience_s: float = 30.0, store=None):
        super().__init__(broker_host, broker_port)
        self.cluster = cluster
        self._store = store  # lazily created for OTA pushes
        self.registry = PeerRegistry(node_timeout_s)
        # node-loss deadline: dark (heartbeat timeout) fails non-durable
        # ranks fast; LOST (silent this much longer) reschedules durable
        # ones — the longer window rides out broker hiccups and GC pauses
        # that are not a reclaimed node
        self.node_loss_deadline_s = float(
            node_loss_deadline_s if node_loss_deadline_s is not None
            else 3.0 * node_timeout_s)
        self.liveness = PeerLiveness(silent_after_s=self.node_loss_deadline_s)
        self.max_reschedules = int(max_reschedules)
        # a LOST rank with momentarily no admissible survivor (every node
        # busy, dark, or without HBM headroom) retries each sweep for this
        # long before the rank permanently fails — a transient capacity
        # dip must not permafail a resumable job
        self.reschedule_patience_s = float(reschedule_patience_s)
        self.jobs: Dict[str, JobView] = {}
        self._lock = threading.Lock()
        self._draining: Set[str] = set()
        self._awaiting_resume: Set[str] = set()
        self._log_events: Dict[str, threading.Event] = {}
        from fedml_tpu.telemetry import get_registry

        reg = get_registry()
        self._m_reschedules = reg.counter("sched/reschedules")
        self._m_jobs_lost = reg.counter("sched/jobs_lost")
        self._m_jobs_resumed = reg.counter("sched/jobs_resumed")
        self.subscribe_json(f"sched/{cluster}/master", self._on_message)
        self._watch_started = False

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "MasterAgent":
        if not self._watch_started:
            self._watch_started = True
            self.spawn_loop(self._watch_loop)
        return self

    def shutdown(self) -> None:
        self.stop_agent()

    # -- node registry ----------------------------------------------------
    def live_nodes(self) -> List[str]:
        return self.registry.live()

    def wait_for_nodes(self, n: int, timeout: float = 30.0) -> List[str]:
        return self.registry.wait_for(n, timeout, what="nodes")

    # -- placement helpers -------------------------------------------------
    def _ranks_in_use(self) -> Dict[str, int]:
        in_use: Dict[str, int] = {}
        for view in self.jobs.values():
            for rid, node_id in view.ranks.items():
                if (rid not in view.superseded
                        and view.rank_status[rid] not in RunStatus.TERMINAL):
                    in_use[node_id] = in_use.get(node_id, 0) + 1
        return in_use

    def _hbm_in_use(self) -> Dict[str, float]:
        used: Dict[str, float] = {}
        for view in self.jobs.values():
            if not view.hbm_demand:
                continue
            for rid, node_id in view.ranks.items():
                if (rid not in view.superseded
                        and view.rank_status[rid] not in RunStatus.TERMINAL):
                    used[node_id] = used.get(node_id, 0.0) + view.hbm_demand
        return used

    def _hbm_capacity(self, node_id: str) -> Optional[float]:
        res = self.registry.get(node_id).get("resources") or {}
        limit = res.get("hbm_bytes_limit")
        return float(limit) if limit else None

    def _admits(self, node_id: str, demand: float,
                hbm_used: Dict[str, float]) -> bool:
        """PR 10 peak-HBM admission: a job with a known demand may not
        land on a node advertising a smaller free HBM figure. Unknown
        demand or an un-instrumented node admits (CPU dev clusters)."""
        if demand <= 0:
            return True
        cap = self._hbm_capacity(node_id)
        if cap is None:
            return True
        return cap - hbm_used.get(node_id, 0.0) >= demand

    # -- job control ------------------------------------------------------
    def submit_job(self, spec: JobSpec, n_ranks: int = 1,
                   nodes: Optional[List[str]] = None,
                   extra_env: Optional[Dict[str, Dict[str, str]]] = None,
                   ) -> str:
        """Fan ``spec`` out as ``n_ranks`` runs over the given (or all
        live) nodes, respecting each node's advertised slots and HBM
        headroom. Each rank's process sees FEDML_RANK / FEDML_NUM_RANKS /
        FEDML_JOB_ID; ``extra_env`` maps rank (as str) to additional env
        overrides."""
        with self._lock:
            draining = set(self._draining)
        live = [n for n in self.live_nodes() if n not in draining]
        if nodes:
            missing = sorted(set(nodes) - set(live))
            if missing:
                raise RuntimeError(
                    f"requested nodes not online: {missing} (live: {live})")
        targets = nodes or live
        if not targets:
            raise RuntimeError("no live nodes to schedule on")
        # resource matcher (reference: scheduler_core/scheduler_matcher.py
        # against the GPU inventory): the job yaml's `computing` block
        # filters candidate nodes by their advertised inventory
        req = spec.computing or {}
        min_chips = int(req.get("minimum_num_chips", 0) or 0)
        want_platform = str(req.get("platform", "") or "").lower()
        if min_chips or want_platform:
            matched = []
            for n in targets:
                res = self.registry.get(n).get("resources") or {}
                if min_chips and int(res.get("device_count", 0)) < min_chips:
                    continue
                if (want_platform
                        and str(res.get("platform", "")).lower()
                        != want_platform):
                    continue
                matched.append(n)
            if not matched:
                raise RuntimeError(
                    f"no node satisfies computing requirements {req}; "
                    f"inventories: "
                    f"{ {n: self.registry.get(n).get('resources') for n in targets} }")
            targets = matched
        # expand nodes by their advertised slots (a slot = one rank; each
        # rank is its own JAX/XLA process, so slots bound oversubscription
        # the way the deploy plane's --capacity does), deducting ranks
        # still running from OTHER jobs, interleaved so ranks spread
        # across nodes before doubling up. HBM admission caps each node's
        # usable slots at what its advertised headroom can hold.
        demand = job_hbm_demand(spec)
        with self._lock:
            in_use = self._ranks_in_use()
            hbm_used = self._hbm_in_use()
        remaining = {}
        for n in targets:
            slots = max(0, max(1, int(self.registry.get(n).get("slots", 1)))
                        - in_use.get(n, 0))
            if demand > 0:
                cap = self._hbm_capacity(n)
                if cap is not None:
                    free = cap - hbm_used.get(n, 0.0)
                    slots = min(slots, max(0, int(free // demand)))
            remaining[n] = slots
        slot_list: List[str] = []
        while any(remaining.values()):
            for node_id in targets:
                if remaining[node_id] > 0:
                    remaining[node_id] -= 1
                    slot_list.append(node_id)
        if n_ranks > len(slot_list):
            raise RuntimeError(
                f"job needs {n_ranks} slots, cluster offers {len(slot_list)} "
                f"across {targets}"
                + (f" (peak-HBM admission: {demand:.0f} B/rank)"
                   if demand else ""))
        job_id = uuid.uuid4().hex[:10]
        ranks: Dict[str, str] = {}
        rank_env: Dict[str, Dict[str, str]] = {}
        assignments = []
        for rank in range(n_ranks):
            node_id = slot_list[rank]
            run_id = f"{job_id}-r{rank}"
            ranks[run_id] = node_id
            env = {
                "FEDML_JOB_ID": job_id,
                "FEDML_RANK": str(rank),
                "FEDML_NUM_RANKS": str(n_ranks),
            }
            env.update((extra_env or {}).get(str(rank), {}))
            rank_env[run_id] = env
            assignments.append((node_id, run_id, env))
        view = JobView(job_id, ranks, spec=spec, rank_env=rank_env)
        with self._lock:
            self.jobs[job_id] = view
        for node_id, run_id, env in assignments:
            self._send_start(node_id, run_id, spec, env)
        return job_id

    def _send_start(self, node_id: str, run_id: str, spec: JobSpec,
                    env: Dict[str, str]) -> None:
        self._send(node_id, {"type": "start_run", "run_id": run_id,
                             "spec": spec.wire(), "env": env})

    def stop_job(self, job_id: str) -> bool:
        view = self.jobs.get(job_id)
        if view is None:
            return False
        view.stopped = True
        for run_id, node_id in view.ranks.items():
            if run_id in view.superseded:
                continue
            self._send(node_id, {"type": "stop_run", "run_id": run_id})
        return True

    def job_status(self, job_id: str) -> Optional[Dict]:
        view = self.jobs.get(job_id)
        return view.describe() if view else None

    def wait_job(self, job_id: str, timeout: float = 600.0) -> Dict:
        deadline = time.time() + timeout
        while time.time() < deadline:
            view = self.jobs.get(job_id)
            if view is not None and view.is_terminal:
                return view.describe()
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} not terminal after {timeout}s")

    def job_logs(self, job_id: str, tail: Optional[int] = 200,
                 timeout: float = 10.0) -> Dict[str, str]:
        """One run view: pull each rank's log from its node."""
        view = self.jobs.get(job_id)
        if view is None:
            return {}
        pending = []
        for run_id, node_id in view.ranks.items():
            event = threading.Event()
            self._log_events[run_id] = event
            pending.append((run_id, event))
            self._send(node_id, {"type": "get_logs", "run_id": run_id,
                                 "tail": tail})
        deadline = time.time() + timeout
        for run_id, event in pending:
            event.wait(timeout=max(0.0, deadline - time.time()))
            self._log_events.pop(run_id, None)
        return dict(view.logs)

    # -- preemption / drain ------------------------------------------------
    def preempt_run(self, run_id: str, grace_s: float = 10.0) -> bool:
        """First-class preempt verb: quiesce ONE run wherever it lives.
        Durable jobs are rescheduled automatically once the node reports
        PREEMPTED."""
        for view in self.jobs.values():
            node_id = view.ranks.get(run_id)
            if node_id is None or run_id in view.superseded:
                continue
            if view.rank_status[run_id] in RunStatus.TERMINAL:
                return False
            self._send(node_id, {"type": "preempt_run", "run_id": run_id,
                                 "grace_s": float(grace_s)})
            return True
        return False

    def drain_node(self, node_id: str, grace_s: float = 10.0,
                   timeout: float = 120.0, reason: str = "drain") -> Dict:
        """Quiesce-and-reschedule everything on a node — the response to
        "this node is being reclaimed in N seconds". Preempts every
        active rank there (SIGTERM + grace via the node agent), waits for
        the quiesce, and lets the PREEMPTED reports drive rescheduling of
        durable jobs onto surviving nodes (non-durable ranks fail: there
        is nothing to resume). The node stays out of placement until
        :meth:`undrain`."""
        with self._lock:
            self._draining.add(node_id)
            victims = [
                (view, rid)
                for view in self.jobs.values()
                for rid, nid in view.ranks.items()
                if nid == node_id and rid not in view.superseded
                and view.rank_status[rid] not in RunStatus.TERMINAL
            ]
        sched_event("node_drain", node=node_id, runs=len(victims),
                    grace_s=grace_s, reason=reason)
        for _, rid in victims:
            self._send(node_id, {"type": "preempt_run", "run_id": rid,
                                 "grace_s": float(grace_s)})
        deadline = time.time() + timeout
        result: Dict = {"node": node_id, "preempted": [], "rescheduled": {},
                        "failed": []}
        for view, rid in victims:
            while time.time() < deadline:
                st = view.rank_status[rid]
                done = st in RunStatus.TERMINAL
                if done and (not view.durable or st != RunStatus.PREEMPTED
                             or rid in view.superseded
                             or (rid in view.resched_refused
                                 and rid not in view.lost_pending)):
                    break  # terminal AND (not resumable / already superseded)
                time.sleep(0.1)
            st = view.rank_status[rid]
            if st == RunStatus.PREEMPTED:
                result["preempted"].append(rid)
                new_rid = view.resched_map.get(rid)
                if new_rid is not None:
                    result["rescheduled"][rid] = new_rid
                elif rid in view.lost_pending:
                    # the watch loop is still retrying within its
                    # patience window — in-flight, not failed
                    result.setdefault("pending", []).append(rid)
                else:
                    # not resumable (or reschedule refused for good):
                    # the rank is lost
                    with self._lock:
                        view.rank_status[rid] = RunStatus.FAILED
                    result["failed"].append(rid)
            elif st not in RunStatus.TERMINAL:
                result["failed"].append(rid)  # never quiesced in time
        return result

    def undrain(self, node_id: str) -> None:
        with self._lock:
            self._draining.discard(node_id)

    def _reschedule(self, view: JobView, old_rid: str, reason: str) -> Optional[str]:
        """Place a successor for a preempted/lost durable rank on a
        surviving node (slot + peak-HBM admission), carrying the original
        env plus FEDML_RESUME=1. Returns the new run_id, or None when no
        node admits the job (the caller fails the rank)."""
        base = old_rid.split(".", 1)[0]
        with self._lock:
            attempts = view.resched_count.get(base, 0)
            if attempts >= self.max_reschedules:
                if old_rid not in view.resched_refused:  # once, not per retry
                    logger.warning(
                        "rank %s: reschedule budget (%d) exhausted",
                        old_rid, self.max_reschedules)
                    sched_event("reschedule_refused", run_id=old_rid,
                                job_id=view.job_id, reason="budget_exhausted",
                                attempts=attempts)
                # refused is terminal for the rank: the job must resolve
                # (JobView.status treats unresumable PREEMPTED as FAILED)
                # instead of reporting RUNNING forever
                view.resched_refused.add(old_rid)
                return None
            old_node = view.ranks[old_rid]
            draining = set(self._draining)
            in_use = self._ranks_in_use()
            hbm_used = self._hbm_in_use()
        candidates = []
        for n in self.live_nodes():
            if n in draining:
                continue
            slots = max(1, int(self.registry.get(n).get("slots", 1)))
            if in_use.get(n, 0) >= slots:
                continue
            if not self._admits(n, view.hbm_demand, hbm_used):
                continue
            candidates.append((n == old_node, in_use.get(n, 0), n))
        if not candidates:
            if old_rid not in view.resched_refused:  # once, not per retry
                logger.warning(
                    "rank %s: no surviving node admits the job "
                    "(demand %.0f B, draining=%s)", old_rid, view.hbm_demand,
                    sorted(draining))
                sched_event("reschedule_refused", run_id=old_rid,
                            job_id=view.job_id, reason=reason,
                            hbm_demand=view.hbm_demand)
            with self._lock:
                view.resched_refused.add(old_rid)
            return None
        candidates.sort()  # prefer other nodes, then least-loaded
        node_id = candidates[0][2]
        new_rid = f"{base}.{attempts + 1}"
        env = dict(view.rank_env.get(old_rid) or {})
        env["FEDML_RESUME"] = "1"
        with self._lock:
            # copy-on-write rebinds, not in-place inserts: describe()/
            # stop_job/wait pollers iterate these containers WITHOUT the
            # lock (they never needed it before this PR made the rank set
            # grow after construction), and a resize mid-iteration raises
            # RuntimeError in the reader
            view.resched_count = {**view.resched_count, base: attempts + 1}
            view.ranks = {**view.ranks, new_rid: node_id}
            view.rank_status = {**view.rank_status,
                                new_rid: RunStatus.QUEUED}
            view.rank_rc = {**view.rank_rc, new_rid: None}
            view.rank_env = {**view.rank_env, new_rid: env}
            view.superseded = view.superseded | {old_rid}
            view.resched_map = {**view.resched_map, old_rid: new_rid}
            self._awaiting_resume.add(new_rid)
        self._m_reschedules.inc()
        sched_event("run_rescheduled", run_id=old_rid, new_run_id=new_rid,
                    job_id=view.job_id, node=node_id, reason=reason)
        self._send_start(node_id, new_rid, view.spec, env)
        return new_rid

    # -- OTA --------------------------------------------------------------
    def push_upgrade(self, package: bytes, version: str,
                     nodes: Optional[List[str]] = None,
                     timeout: float = 60.0) -> Dict[str, str]:
        """Ship a code package to node agents for staged upgrade
        (slave daemon_ota_upgrade parity). Returns node → staged version
        once every target acked (or raises)."""
        if self._store is None:
            from fedml_tpu.core.distributed.communication.object_store import (
                create_object_store,
            )

            self._store = create_object_store()
        targets = nodes or self.live_nodes()
        if not targets:
            raise RuntimeError("no live nodes to upgrade")
        for n in targets:  # clear stale state from any previous push
            self.registry.touch(n, ota_version=None, ota_error=None)
        key = self._store.new_key(f"ota/{version}")
        # returned key is authoritative (CAS backends return a CID)
        key = self._store.put_object(key, package)
        for n in targets:
            self._send(n, {"type": "ota_upgrade", "package_key": key,
                           "version": str(version)})
        deadline = time.time() + timeout
        while time.time() < deadline:
            staged = {n: self.registry.get(n).get("ota_version")
                      for n in targets}
            errors = {n: self.registry.get(n).get("ota_error")
                      for n in targets if self.registry.get(n).get("ota_error")}
            if errors:
                raise RuntimeError(f"OTA staging failed: {errors}")
            if all(v == str(version) for v in staged.values()):
                self._store.delete_object(key)
                return staged
            time.sleep(0.1)
        raise TimeoutError(
            f"OTA {version}: staged on "
            f"{[n for n, v in staged.items() if v == str(version)]} "
            f"of {targets}")

    # -- internals --------------------------------------------------------
    def _send(self, node_id: str, msg: Dict) -> None:
        self.publish_json(f"sched/{self.cluster}/node/{node_id}", msg)

    def _apply_rank_status(self, run_id: str, status: str,
                           returncode=None) -> None:
        for view in self.jobs.values():
            if run_id not in view.rank_status:
                continue
            resumed = False
            needs_resched = False
            # the in-place value writes share the lock with _reschedule's
            # copy-on-write rebinds: an unlocked write racing a rebind
            # could land in the discarded pre-rebind snapshot — the rc
            # would then never heal (one-shot run_status messages are
            # deduped by the node agent; heartbeats carry no rc)
            with self._lock:
                current = view.rank_status[run_id]
                if current not in RunStatus.TERMINAL:
                    view.rank_status[run_id] = status
                    view.rank_rc[run_id] = returncode
                    if status == RunStatus.RUNNING and \
                            run_id in self._awaiting_resume:
                        self._awaiting_resume.discard(run_id)
                        resumed = True
                    needs_resched = (
                        status == RunStatus.PREEMPTED and view.durable
                        and not view.stopped
                        and run_id not in view.superseded)
                elif (current == status and returncode is not None
                      and view.rank_rc[run_id] is None):
                    # heartbeat reconciliation may latch a terminal status
                    # before the one-shot run_status carrying the rc lands;
                    # accept the rc for the SAME status
                    view.rank_rc[run_id] = returncode
                stale_running = (run_id in view.superseded
                                 and status == RunStatus.RUNNING)
            if resumed:
                self._m_jobs_resumed.inc()
                sched_event("run_resumed", run_id=run_id,
                            job_id=view.job_id, node=view.ranks[run_id])
            if needs_resched:
                # quiesce observed (master- OR node-initiated): resume the
                # rank elsewhere — OUTSIDE the lock, _reschedule takes it.
                # A transient refusal (capacity dip) hands off to the
                # watch loop's patience retry — same machinery as a lost
                # rank — rather than permafailing the job, as long as the
                # reschedule budget is not exhausted
                if self._reschedule(view, run_id, "preempted") is None:
                    base = run_id.split(".", 1)[0]
                    if (view.resched_count.get(base, 0)
                            < self.max_reschedules):
                        with self._lock:
                            view.lost_pending.setdefault(run_id,
                                                         time.time())
            if stale_running:
                # a lost node came back still running a run we already
                # rescheduled: exactly one of the twins may live
                logger.warning("superseded run %s reported RUNNING; "
                               "sending stop", run_id)
                self._send(view.ranks[run_id],
                           {"type": "stop_run", "run_id": run_id})
            break

    def _on_message(self, msg: Dict) -> None:
        mtype = msg.get("type")
        nid = str(msg.get("node_id", ""))
        if nid:
            self.liveness.note(nid)
            if self.liveness.is_evicted(nid):
                self.liveness.readmit(nid)
                sched_event("node_readmitted", node=nid)
                # a lost node came back before its ranks were rescheduled:
                # the runs survived with it — cancel the pending loss (the
                # heartbeat reconciles their true statuses)
                with self._lock:
                    views = list(self.jobs.values())
                for view in views:
                    for rid in list(view.lost_pending):
                        if (view.ranks.get(rid) == nid
                                and rid not in view.superseded):
                            with self._lock:
                                view.lost_pending.pop(rid, None)
                            sched_event("run_resurrected", run_id=rid,
                                        job_id=view.job_id, node=nid)
        if mtype == "node_online":
            self.registry.touch(nid, slots=int(msg.get("slots", 1)),
                                resources=msg.get("resources") or {})
        elif mtype == "heartbeat":
            attrs = {}
            if msg.get("slots") is not None:
                attrs["slots"] = int(msg["slots"])
            if msg.get("resources") is not None:
                attrs["resources"] = msg["resources"]
            self.registry.touch(nid, **attrs)
            # reconcile from the heartbeat's run table too: a lost one-shot
            # run_status message must not leave a rank RUNNING forever
            for rid, status in (msg.get("runs") or {}).items():
                self._apply_rank_status(str(rid), str(status))
        elif mtype == "run_status":
            self._apply_rank_status(str(msg["run_id"]), str(msg["status"]),
                                    msg.get("returncode"))
        elif mtype == "ota_staged":
            if msg.get("ok"):
                self.registry.touch(nid, ota_version=str(msg.get("version")),
                                    ota_error=None)
            else:
                self.registry.touch(nid, ota_error=str(msg.get("error")))
        elif mtype == "run_logs":
            rid = str(msg["run_id"])
            for view in self.jobs.values():
                if rid in view.ranks:
                    view.logs[rid] = str(msg.get("data", ""))
                    break
            event = self._log_events.get(rid)
            if event is not None:
                event.set()

    def _watch_loop(self) -> None:
        """Dead-node handling, two deadlines: a node dark past the
        heartbeat timeout takes its non-durable ranks to FAILED (the
        reference master's edge-offline handling); a node silent past
        ``node_loss_deadline_s`` has its durable ranks declared LOST and
        rescheduled onto surviving nodes, where they resume from their
        last durable state."""
        while not self._stopping.is_set():
            dark = set(self.registry.dark())
            with self._lock:
                views = list(self.jobs.values())
            for view in views:
                if view.durable:
                    continue  # durable jobs wait for the loss deadline
                for rid, node_id in view.ranks.items():
                    if (node_id in dark and rid not in view.superseded
                            and view.rank_status[rid] not in RunStatus.TERMINAL):
                        logger.warning("job %s rank %s lost: node %s dark",
                                       view.job_id, rid, node_id)
                        with self._lock:
                            view.rank_status[rid] = RunStatus.FAILED
            for node_id in self.liveness.silent_peers():
                if self.liveness.evict(node_id):
                    sched_event("node_lost", node=node_id,
                                deadline_s=self.node_loss_deadline_s)
            evicted = set(self.liveness.evicted())
            now = time.time()
            for view in views:
                if not view.durable or view.stopped:
                    continue
                for rid, nid in list(view.ranks.items()):
                    if rid in view.superseded:
                        continue
                    pending_since = view.lost_pending.get(rid)
                    if pending_since is None:
                        if (nid not in evicted
                                or view.rank_status[rid] in RunStatus.TERMINAL):
                            continue
                        # first sighting: declare the rank lost
                        with self._lock:
                            view.lost_pending[rid] = now
                        pending_since = now
                        self._m_jobs_lost.inc()
                        sched_event("job_lost", run_id=rid,
                                    job_id=view.job_id, node=nid)
                        logger.warning(
                            "job %s rank %s LOST with node %s (silent > "
                            "%gs); rescheduling", view.job_id, rid, nid,
                            self.node_loss_deadline_s)
                        # tell the node to stop the zombie if it ever
                        # returns, then place the successor
                        self._send(nid, {"type": "stop_run", "run_id": rid})
                    if self._reschedule(view, rid, "retry") is not None:
                        with self._lock:
                            view.lost_pending.pop(rid, None)
                            if view.rank_status[rid] == RunStatus.RUNNING:
                                # lost-node rank: the row will never
                                # report again — close it out (a preempt-
                                # pending rank keeps its honest PREEMPTED)
                                view.rank_status[rid] = RunStatus.FAILED
                    elif now - pending_since > self.reschedule_patience_s:
                        # patience exhausted: the rank fails for real
                        with self._lock:
                            view.lost_pending.pop(rid, None)
                            view.rank_status[rid] = RunStatus.FAILED
                        sched_event("reschedule_abandoned", run_id=rid,
                                    job_id=view.job_id,
                                    patience_s=self.reschedule_patience_s)
                    # else: no admissible node RIGHT NOW — retry next sweep
            time.sleep(0.5)
