"""Master agent — multi-node run orchestration.

Parity target: ``master/server_runner.py`` (``FedMLServerRunner`` :68 —
``run`` :427 drives a run across edges, ``callback_start_train`` :1462;
status aggregation back from the slaves). Re-design: the master keeps a
node registry fed by broker heartbeats, fans a multi-rank job out as one
run per node (each rank gets FEDML_RANK/FEDML_NUM_RANKS env — the
TPU-era replacement for the reference's MQTT-dispatched train configs),
aggregates per-rank status FSMs into one job status, detects dead nodes
by heartbeat loss, and pulls every rank's logs into one run view.

Job status semantics:
  RUNNING  while any rank is non-terminal and no rank has failed
  FINISHED when ALL ranks finished
  FAILED   as soon as any rank FAILED/EXCEPTION, or its node went dark
  KILLED   after stop_job()
"""
from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Dict, List, Optional

from fedml_tpu.core.distributed.communication.broker_agent import (
    BrokerJsonAgent,
    PeerRegistry,
)
from fedml_tpu.core.mlops.status import RunStatus
from fedml_tpu.scheduler.job_yaml import JobSpec

logger = logging.getLogger(__name__)


class JobView:
    """Aggregated state of one multi-rank job."""

    def __init__(self, job_id: str, ranks: Dict[str, str]):
        self.job_id = job_id
        self.ranks = ranks  # run_id → node_id
        self.rank_status: Dict[str, str] = {r: RunStatus.QUEUED for r in ranks}
        self.rank_rc: Dict[str, Optional[int]] = {r: None for r in ranks}
        self.logs: Dict[str, str] = {}
        self.stopped = False

    @property
    def status(self) -> str:
        statuses = set(self.rank_status.values())
        if self.stopped:
            return RunStatus.KILLED
        if statuses & {RunStatus.FAILED, RunStatus.EXCEPTION}:
            return RunStatus.FAILED
        if RunStatus.KILLED in statuses:
            return RunStatus.KILLED
        if statuses == {RunStatus.FINISHED}:
            return RunStatus.FINISHED
        return RunStatus.RUNNING

    @property
    def is_terminal(self) -> bool:
        return self.status in RunStatus.TERMINAL

    def describe(self) -> Dict:
        return {
            "job_id": self.job_id,
            "status": self.status,
            "ranks": [
                {"run_id": rid, "node_id": self.ranks[rid],
                 "status": self.rank_status[rid],
                 "returncode": self.rank_rc[rid]}
                for rid in sorted(self.ranks)
            ],
        }


class MasterAgent(BrokerJsonAgent):
    def __init__(self, broker_host: str, broker_port: int,
                 cluster: str = "default", node_timeout_s: float = 5.0,
                 store=None):
        super().__init__(broker_host, broker_port)
        self.cluster = cluster
        self._store = store  # lazily created for OTA pushes
        self.registry = PeerRegistry(node_timeout_s)
        self.jobs: Dict[str, JobView] = {}
        self._lock = threading.Lock()
        self._log_events: Dict[str, threading.Event] = {}
        self.subscribe_json(f"sched/{cluster}/master", self._on_message)
        self._watch_started = False

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "MasterAgent":
        if not self._watch_started:
            self._watch_started = True
            self.spawn_loop(self._watch_loop)
        return self

    def shutdown(self) -> None:
        self.stop_agent()

    # -- node registry ----------------------------------------------------
    def live_nodes(self) -> List[str]:
        return self.registry.live()

    def wait_for_nodes(self, n: int, timeout: float = 30.0) -> List[str]:
        return self.registry.wait_for(n, timeout, what="nodes")

    # -- job control ------------------------------------------------------
    def submit_job(self, spec: JobSpec, n_ranks: int = 1,
                   nodes: Optional[List[str]] = None,
                   extra_env: Optional[Dict[str, Dict[str, str]]] = None,
                   ) -> str:
        """Fan ``spec`` out as ``n_ranks`` runs over the given (or all
        live) nodes, respecting each node's advertised slots. Each rank's
        process sees FEDML_RANK / FEDML_NUM_RANKS / FEDML_JOB_ID;
        ``extra_env`` maps rank (as str) to additional env overrides."""
        live = self.live_nodes()
        if nodes:
            missing = sorted(set(nodes) - set(live))
            if missing:
                raise RuntimeError(
                    f"requested nodes not online: {missing} (live: {live})")
        targets = nodes or live
        if not targets:
            raise RuntimeError("no live nodes to schedule on")
        # resource matcher (reference: scheduler_core/scheduler_matcher.py
        # against the GPU inventory): the job yaml's `computing` block
        # filters candidate nodes by their advertised inventory
        req = spec.computing or {}
        min_chips = int(req.get("minimum_num_chips", 0) or 0)
        want_platform = str(req.get("platform", "") or "").lower()
        if min_chips or want_platform:
            matched = []
            for n in targets:
                res = self.registry.get(n).get("resources") or {}
                if min_chips and int(res.get("device_count", 0)) < min_chips:
                    continue
                if (want_platform
                        and str(res.get("platform", "")).lower()
                        != want_platform):
                    continue
                matched.append(n)
            if not matched:
                raise RuntimeError(
                    f"no node satisfies computing requirements {req}; "
                    f"inventories: "
                    f"{ {n: self.registry.get(n).get('resources') for n in targets} }")
            targets = matched
        # expand nodes by their advertised slots (a slot = one rank; each
        # rank is its own JAX/XLA process, so slots bound oversubscription
        # the way the deploy plane's --capacity does), deducting ranks
        # still running from OTHER jobs, interleaved so ranks spread
        # across nodes before doubling up
        in_use: Dict[str, int] = {}
        with self._lock:
            for view in self.jobs.values():
                for rid, node_id in view.ranks.items():
                    if view.rank_status[rid] not in RunStatus.TERMINAL:
                        in_use[node_id] = in_use.get(node_id, 0) + 1
        remaining = {
            n: max(0, max(1, int(self.registry.get(n).get("slots", 1)))
                   - in_use.get(n, 0))
            for n in targets
        }
        slot_list: List[str] = []
        while any(remaining.values()):
            for node_id in targets:
                if remaining[node_id] > 0:
                    remaining[node_id] -= 1
                    slot_list.append(node_id)
        if n_ranks > len(slot_list):
            raise RuntimeError(
                f"job needs {n_ranks} slots, cluster offers {len(slot_list)} "
                f"across {targets}")
        job_id = uuid.uuid4().hex[:10]
        ranks: Dict[str, str] = {}
        assignments = []
        for rank in range(n_ranks):
            node_id = slot_list[rank]
            run_id = f"{job_id}-r{rank}"
            ranks[run_id] = node_id
            env = {
                "FEDML_JOB_ID": job_id,
                "FEDML_RANK": str(rank),
                "FEDML_NUM_RANKS": str(n_ranks),
            }
            env.update((extra_env or {}).get(str(rank), {}))
            assignments.append((node_id, run_id, env))
        view = JobView(job_id, ranks)
        with self._lock:
            self.jobs[job_id] = view
        for node_id, run_id, env in assignments:
            self._send(node_id, {
                "type": "start_run", "run_id": run_id,
                "spec": {
                    "job_name": spec.job_name, "job": spec.job,
                    "workspace": spec.workspace,
                    "bootstrap": spec.bootstrap, "env": spec.env,
                },
                "env": env,
            })
        return job_id

    def stop_job(self, job_id: str) -> bool:
        view = self.jobs.get(job_id)
        if view is None:
            return False
        view.stopped = True
        for run_id, node_id in view.ranks.items():
            self._send(node_id, {"type": "stop_run", "run_id": run_id})
        return True

    def job_status(self, job_id: str) -> Optional[Dict]:
        view = self.jobs.get(job_id)
        return view.describe() if view else None

    def wait_job(self, job_id: str, timeout: float = 600.0) -> Dict:
        deadline = time.time() + timeout
        while time.time() < deadline:
            view = self.jobs.get(job_id)
            if view is not None and view.is_terminal:
                return view.describe()
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} not terminal after {timeout}s")

    def job_logs(self, job_id: str, tail: Optional[int] = 200,
                 timeout: float = 10.0) -> Dict[str, str]:
        """One run view: pull each rank's log from its node."""
        view = self.jobs.get(job_id)
        if view is None:
            return {}
        pending = []
        for run_id, node_id in view.ranks.items():
            event = threading.Event()
            self._log_events[run_id] = event
            pending.append((run_id, event))
            self._send(node_id, {"type": "get_logs", "run_id": run_id,
                                 "tail": tail})
        deadline = time.time() + timeout
        for run_id, event in pending:
            event.wait(timeout=max(0.0, deadline - time.time()))
            self._log_events.pop(run_id, None)
        return dict(view.logs)

    # -- OTA --------------------------------------------------------------
    def push_upgrade(self, package: bytes, version: str,
                     nodes: Optional[List[str]] = None,
                     timeout: float = 60.0) -> Dict[str, str]:
        """Ship a code package to node agents for staged upgrade
        (slave daemon_ota_upgrade parity). Returns node → staged version
        once every target acked (or raises)."""
        if self._store is None:
            from fedml_tpu.core.distributed.communication.object_store import (
                create_object_store,
            )

            self._store = create_object_store()
        targets = nodes or self.live_nodes()
        if not targets:
            raise RuntimeError("no live nodes to upgrade")
        for n in targets:  # clear stale state from any previous push
            self.registry.touch(n, ota_version=None, ota_error=None)
        key = self._store.new_key(f"ota/{version}")
        # returned key is authoritative (CAS backends return a CID)
        key = self._store.put_object(key, package)
        for n in targets:
            self._send(n, {"type": "ota_upgrade", "package_key": key,
                           "version": str(version)})
        deadline = time.time() + timeout
        while time.time() < deadline:
            staged = {n: self.registry.get(n).get("ota_version")
                      for n in targets}
            errors = {n: self.registry.get(n).get("ota_error")
                      for n in targets if self.registry.get(n).get("ota_error")}
            if errors:
                raise RuntimeError(f"OTA staging failed: {errors}")
            if all(v == str(version) for v in staged.values()):
                self._store.delete_object(key)
                return staged
            time.sleep(0.1)
        raise TimeoutError(
            f"OTA {version}: staged on "
            f"{[n for n, v in staged.items() if v == str(version)]} "
            f"of {targets}")

    # -- internals --------------------------------------------------------
    def _send(self, node_id: str, msg: Dict) -> None:
        self.publish_json(f"sched/{self.cluster}/node/{node_id}", msg)

    def _apply_rank_status(self, run_id: str, status: str,
                           returncode=None) -> None:
        for view in self.jobs.values():
            if run_id in view.rank_status:
                current = view.rank_status[run_id]
                if current not in RunStatus.TERMINAL:
                    view.rank_status[run_id] = status
                    view.rank_rc[run_id] = returncode
                elif (current == status and returncode is not None
                      and view.rank_rc[run_id] is None):
                    # heartbeat reconciliation may latch a terminal status
                    # before the one-shot run_status carrying the rc lands;
                    # accept the rc for the SAME status
                    view.rank_rc[run_id] = returncode
                break

    def _on_message(self, msg: Dict) -> None:
        mtype = msg.get("type")
        nid = str(msg.get("node_id", ""))
        if mtype == "node_online":
            self.registry.touch(nid, slots=int(msg.get("slots", 1)),
                                resources=msg.get("resources") or {})
        elif mtype == "heartbeat":
            self.registry.touch(nid)
            # reconcile from the heartbeat's run table too: a lost one-shot
            # run_status message must not leave a rank RUNNING forever
            for rid, status in (msg.get("runs") or {}).items():
                self._apply_rank_status(str(rid), str(status))
        elif mtype == "run_status":
            self._apply_rank_status(str(msg["run_id"]), str(msg["status"]),
                                    msg.get("returncode"))
        elif mtype == "ota_staged":
            if msg.get("ok"):
                self.registry.touch(nid, ota_version=str(msg.get("version")),
                                    ota_error=None)
            else:
                self.registry.touch(nid, ota_error=str(msg.get("error")))
        elif mtype == "run_logs":
            rid = str(msg["run_id"])
            for view in self.jobs.values():
                if rid in view.ranks:
                    view.logs[rid] = str(msg.get("data", ""))
                    break
            event = self._log_events.get(rid)
            if event is not None:
                event.set()

    def _watch_loop(self) -> None:
        """Dead-node detection: a node that stops heartbeating takes its
        non-terminal ranks to FAILED (the reference master's edge-offline
        handling)."""
        while not self._stopping.is_set():
            dark = set(self.registry.dark())
            with self._lock:
                views = list(self.jobs.values())
            for view in views:
                for rid, node_id in view.ranks.items():
                    if (node_id in dark
                            and view.rank_status[rid] not in RunStatus.TERMINAL):
                        logger.warning("job %s rank %s lost: node %s dark",
                                       view.job_id, rid, node_id)
                        view.rank_status[rid] = RunStatus.FAILED
            time.sleep(0.5)
