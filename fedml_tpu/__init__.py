"""fedml_tpu — a TPU-native federated learning + MLOps framework.

Capability parity with FedML (reference: ``/root/reference``, v0.8.18b9),
re-designed for TPU from the ground up: JAX/XLA/Pallas for compute, device
meshes + XLA collectives (ICI/DCN) for scale, functional pytree state
everywhere, and a deterministic in-process transport for testable federation
protocols.

Public surface parity with ``python/fedml/__init__.py``:
    fedml_tpu.init(args) / run_simulation() / FedMLRunner
    fedml_tpu.data.load / fedml_tpu.models.create / fedml_tpu.device.get_device
"""
from __future__ import annotations

import logging
import os
import random
from typing import Any, Optional

import numpy as np

__version__ = "0.1.0"

from fedml_tpu import constants  # noqa: E402
from fedml_tpu.arguments import (  # noqa: E402
    Arguments,
    load_arguments,
    load_arguments_from_dict,
)
from fedml_tpu.runner import FedMLRunner  # noqa: E402

_global_training_type: Optional[str] = None
_global_comm_backend: Optional[str] = None


def init(args: Optional[Arguments] = None, check_env: bool = True) -> Arguments:
    """Initialize the framework — parity with ``fedml.init()``
    (``python/fedml/__init__.py:64``): load args, seed RNGs, init the
    trust-stack singletons and the mlops sink, dispatch per training type.
    """
    global _global_training_type, _global_comm_backend
    if args is None:
        args = load_arguments(_global_training_type, _global_comm_backend)
    _global_training_type = str(getattr(args, "training_type", "simulation"))
    _global_comm_backend = str(getattr(args, "backend", ""))

    seed = int(getattr(args, "random_seed", 0))
    random.seed(seed)
    np.random.seed(seed)

    # multi-host slice init must precede any backend use (parity: the
    # reference's torchrun env parsing at __init__.py:353-360)
    from fedml_tpu.parallel.multihost import maybe_initialize_multihost

    maybe_initialize_multihost(args)
    # per-silo override yamls (parity: _update_client_specific_args /
    # hierarchical server/client_silo config paths)
    from fedml_tpu.arguments import update_client_specific_args

    update_client_specific_args(args)

    from fedml_tpu.core.dp.fedml_differential_privacy import FedMLDifferentialPrivacy
    from fedml_tpu.core.fhe.fhe_agg import FedMLFHE
    from fedml_tpu.core.mlops import metrics as mlops_metrics
    from fedml_tpu.core.security.attacker import FedMLAttacker
    from fedml_tpu.core.security.defender import FedMLDefender

    FedMLAttacker.get_instance().init(args)
    FedMLDefender.get_instance().init(args)
    FedMLDifferentialPrivacy.get_instance().init(args)
    FedMLFHE.get_instance().init(args)
    mlops_metrics.init(args)

    _update_client_id_list(args)
    return args


def _update_client_id_list(args: Arguments) -> None:
    """Parity with ``__init__.py:409``: materialize client_id_list."""
    if not hasattr(args, "client_id_list") or args.client_id_list in (None, "[]", ""):
        total = int(getattr(args, "client_num_in_total", 0) or 0)
        args.client_id_list = list(range(1, total + 1))


# ---- one-call launchers (parity: python/fedml/launch_*.py) ----------------

def run_simulation(backend: str = constants.FEDML_SIMULATION_TYPE_SP):
    """Parity with ``fedml.run_simulation()`` (``launch_simulation.py:9``)."""
    from fedml_tpu import data as data_mod
    from fedml_tpu import device as device_mod
    from fedml_tpu import models as models_mod

    global _global_training_type, _global_comm_backend
    _global_training_type = constants.FEDML_TRAINING_PLATFORM_SIMULATION
    _global_comm_backend = backend
    args = load_arguments(_global_training_type, _global_comm_backend)
    args = init(args)
    device = device_mod.get_device(args)
    dataset = data_mod.load_federated(args)
    model = models_mod.create(args, dataset.class_num)
    runner = FedMLRunner(args, device, dataset, model)
    return runner.run()


def run_cross_silo_server():
    return _run_cross_silo(constants.ROLE_SERVER)


def run_cross_silo_client():
    return _run_cross_silo(constants.ROLE_CLIENT)


def run_hierarchical_cross_silo_server():
    """Hierarchical cross-silo (reference ``run_hierarchical_cross_silo``):
    every silo is a (multi-host) device mesh; scenario drives the per-silo
    config-path overrides."""
    return _run_cross_silo(constants.ROLE_SERVER, scenario="hierarchical")


def run_hierarchical_cross_silo_client():
    return _run_cross_silo(constants.ROLE_CLIENT, scenario="hierarchical")


def run_cross_device_server():
    """Cross-device ("BeeHive") server launcher.

    Parity: ``fedml.run_mnn_server`` (``launch_cross_device.py``) — the
    reference boots the MNN-file server for mobile clients; here the
    server is the cross-silo FSM over the federation transport and the
    device clients run ``python -m fedml_tpu.cross_device.client``.
    """
    from fedml_tpu import data as data_mod
    from fedml_tpu import device as device_mod
    from fedml_tpu import models as models_mod

    global _global_training_type
    _global_training_type = constants.FEDML_TRAINING_PLATFORM_CROSS_DEVICE
    args = load_arguments(_global_training_type, None)
    args.role = constants.ROLE_SERVER
    args.rank = 0
    args.training_type = _global_training_type
    args = init(args)
    device = device_mod.get_device(args)
    dataset = data_mod.load_federated(args)
    model = models_mod.create(args, dataset.class_num)
    return FedMLRunner(args, device, dataset, model).run()


run_mnn_server = run_cross_device_server  # reference launcher name


def run_cross_cloud_server():
    """Parity: ``_init_cross_cloud`` (ref ``__init__.py:392``) server role."""
    return _run_cross_silo(constants.ROLE_SERVER,
                           constants.FEDML_TRAINING_PLATFORM_CROSS_CLOUD)


def run_cross_cloud_client():
    return _run_cross_silo(constants.ROLE_CLIENT,
                           constants.FEDML_TRAINING_PLATFORM_CROSS_CLOUD)


def _run_cross_silo(role: str, training_type: Optional[str] = None,
                    scenario: Optional[str] = None):
    from fedml_tpu import data as data_mod
    from fedml_tpu import device as device_mod
    from fedml_tpu import models as models_mod

    global _global_training_type
    _global_training_type = (training_type
                             or constants.FEDML_TRAINING_PLATFORM_CROSS_SILO)
    args = load_arguments(_global_training_type, None)
    args.role = role
    if training_type is not None:  # cross-cloud launcher overrides the yaml
        args.training_type = training_type
    if scenario is not None:
        args.scenario = scenario
    args = init(args)
    device = device_mod.get_device(args)
    dataset = data_mod.load_federated(args)
    model = models_mod.create(args, dataset.class_num)
    return FedMLRunner(args, device, dataset, model).run()


__all__ = [
    "Arguments",
    "FedMLRunner",
    "__version__",
    "constants",
    "init",
    "load_arguments",
    "load_arguments_from_dict",
    "run_simulation",
    "run_cross_cloud_client",
    "run_cross_cloud_server",
    "run_cross_device_server",
    "run_cross_silo_client",
    "run_cross_silo_server",
    "run_hierarchical_cross_silo_client",
    "run_hierarchical_cross_silo_server",
    "run_mnn_server",
]
