"""Logistic regression — the canonical SP-simulation model.

Parity: ``model/linear/lr.py`` (reference north-star config #1: LR on MNIST).
"""
from __future__ import annotations

import flax.linen as nn


class LogisticRegression(nn.Module):
    output_dim: int

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.output_dim)(x)


class MLP(nn.Module):
    hidden_dim: int
    output_dim: int

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden_dim)(x))
        return nn.Dense(self.output_dim)(x)
