from fedml_tpu.models.model_hub import create, example_input, init_params

__all__ = ["create", "example_input", "init_params"]
