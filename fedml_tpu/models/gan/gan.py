"""GAN pair for federated GAN training (FedGAN).

Parity: reference ``model/gan/`` used by ``simulation/mpi/fedgan``. MLP
generator/discriminator sized by data dim — federated GAN averages both
nets across clients each round.
"""
from __future__ import annotations

import flax.linen as nn


class Generator(nn.Module):
    out_dim: int
    latent_dim: int = 32
    hidden: int = 128
    bounded: bool = False  # tanh output for [-1,1]-scaled image data

    @nn.compact
    def __call__(self, z):
        h = nn.Dense(self.hidden)(z)
        h = nn.leaky_relu(h, 0.2)
        h = nn.Dense(self.hidden)(h)
        h = nn.leaky_relu(h, 0.2)
        out = nn.Dense(self.out_dim)(h)
        return nn.tanh(out) if self.bounded else out


class Discriminator(nn.Module):
    hidden: int = 128

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden)(x)
        h = nn.leaky_relu(h, 0.2)
        h = nn.Dense(self.hidden)(h)
        h = nn.leaky_relu(h, 0.2)
        return nn.Dense(1)(h)  # logit
