from fedml_tpu.models.gan.gan import Discriminator, Generator

__all__ = ["Generator", "Discriminator"]
