"""RNN/LSTM models for federated text tasks.

Parity: ``model/nlp/rnn.py`` — RNN_OriginalFedAvg (shakespeare next-char,
2-layer LSTM 256) and RNN_StackOverFlow (next-word prediction). The
recurrence runs as ``nn.RNN``/``lax.scan`` so the whole sequence unrolls
inside one XLA program.
"""
from __future__ import annotations

import flax.linen as nn


class RNNOriginalFedAvg(nn.Module):
    """Embedding(8) → LSTM(256) ×2 → Dense(vocab); shakespeare charset 90."""

    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: [batch, seq] int tokens
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        return nn.Dense(self.vocab_size)(h)  # [batch, seq, vocab]


class RNNStackOverflow(nn.Module):
    """Next-word prediction: Embed(96) → LSTM(670) → Dense(96) → Dense(vocab).

    Matches the layer plan of the reference's RNN_StackOverFlow
    (``model/nlp/rnn.py``, 10k vocab + special tokens).
    """

    vocab_size: int = 10004
    embedding_dim: int = 96
    hidden_size: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        h = nn.Dense(self.embedding_dim)(h)
        return nn.Dense(self.vocab_size)(h)
