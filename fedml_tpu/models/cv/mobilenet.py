"""MobileNetV3 (small) — flax, TPU-friendly.

Parity: reference ``model/cv/mobilenet.py`` / ``mobilenet_v3.py``. Inverted
residual blocks with squeeze-excite and hard-swish; GroupNorm instead of
BatchNorm (no running stats to federate — the same reasoning the reference
applies with its group_norm resnet variants).
"""
from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


def hard_swish(x):
    return x * nn.relu6(x + 3.0) / 6.0


def hard_sigmoid(x):
    return nn.relu6(x + 3.0) / 6.0


class SqueezeExcite(nn.Module):
    reduce: int = 4

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = nn.Dense(max(c // self.reduce, 8))(s)
        s = nn.relu(s)
        s = nn.Dense(c)(s)
        return x * hard_sigmoid(s)


class InvertedResidual(nn.Module):
    expand: int
    out_ch: int
    kernel: int
    stride: int
    use_se: bool
    use_hs: bool
    groups: int = 8

    @nn.compact
    def __call__(self, x):
        act = hard_swish if self.use_hs else nn.relu
        inp = x.shape[-1]
        h = x
        if self.expand != inp:
            h = nn.Conv(self.expand, (1, 1), use_bias=False)(h)
            h = nn.GroupNorm(num_groups=min(self.groups, self.expand))(h)
            h = act(h)
        h = nn.Conv(
            self.expand, (self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            feature_group_count=self.expand, use_bias=False,
        )(h)
        h = nn.GroupNorm(num_groups=min(self.groups, self.expand))(h)
        if self.use_se:
            h = SqueezeExcite()(h)
        h = act(h)
        h = nn.Conv(self.out_ch, (1, 1), use_bias=False)(h)
        h = nn.GroupNorm(num_groups=min(self.groups, self.out_ch))(h)
        if self.stride == 1 and inp == self.out_ch:
            h = h + x
        return h


class MobileNetV3Small(nn.Module):
    """Input [B, H, W, C] → logits [B, output_dim]."""

    output_dim: int = 10

    # (kernel, expand, out, SE, HS, stride) — MobileNetV3-small table
    CFG: Sequence[Tuple[int, int, int, bool, bool, int]] = (
        (3, 16, 16, True, False, 2),
        (3, 72, 24, False, False, 2),
        (3, 88, 24, False, False, 1),
        (5, 96, 40, True, True, 2),
        (5, 240, 40, True, True, 1),
        (5, 120, 48, True, True, 1),
        (5, 288, 96, True, True, 2),
    )

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(16, (3, 3), strides=(2, 2), use_bias=False)(x)
        h = nn.GroupNorm(num_groups=8)(h)
        h = hard_swish(h)
        for k, e, o, se, hs, s in self.CFG:
            h = InvertedResidual(e, o, k, s, se, hs)(h)
        h = nn.Conv(576, (1, 1), use_bias=False)(h)
        h = nn.GroupNorm(num_groups=8)(h)
        h = hard_swish(h)
        h = jnp.mean(h, axis=(1, 2))
        h = nn.Dense(1024)(h)
        h = hard_swish(h)
        return nn.Dense(self.output_dim)(h)
