"""ResNets: CIFAR-style resnet20/56 and torchvision-style resnet18.

Parity: ``model/cv/resnet.py`` (resnet20/56 for fed_cifar100) and
``model/cv/resnet_torch.py`` (resnet18). GroupNorm variants exist because FL
batches are tiny and BatchNorm running-stats don't aggregate well — the
reference ships `resnet*_gn`; we default to GroupNorm for the same reason
and it is also friendlier to SPMD (no cross-device batch stats).
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


def _norm(groups: int | None):
    if groups:
        return partial(nn.GroupNorm, num_groups=groups)
    return partial(nn.BatchNorm, use_running_average=True)


class BasicBlock(nn.Module):
    filters: int
    stride: int = 1
    groups: int | None = 2

    @nn.compact
    def __call__(self, x):
        norm = _norm(self.groups)
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.stride, self.stride),
                    padding="SAME", use_bias=False)(x)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False)(x)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNetCifar(nn.Module):
    """6n+2 CIFAR ResNet (n=3 → resnet20, n=9 → resnet56)."""

    n: int = 3
    output_dim: int = 10
    groups: int | None = 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(self.groups)
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        x = nn.relu(norm()(x))
        for filters, stride in ((16, 1), (32, 2), (64, 2)):
            for i in range(self.n):
                x = BasicBlock(filters, stride if i == 0 else 1, self.groups)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.output_dim)(x)


class ResNet18(nn.Module):
    """torchvision-shape resnet18 adapted to 32×32 or 224×224 inputs."""

    output_dim: int = 10
    groups: int | None = 2
    stage_sizes: Sequence[int] = (2, 2, 2, 2)

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(self.groups)
        small = x.shape[1] <= 64
        if small:
            x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False)(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2), padding="SAME", use_bias=False)(x)
        x = nn.relu(norm()(x))
        if not small:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, blocks in enumerate(self.stage_sizes):
            filters = 64 * (2 ** stage)
            for i in range(blocks):
                stride = 2 if (stage > 0 and i == 0) else 1
                x = BasicBlock(filters, stride, self.groups)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.output_dim)(x)


def resnet20(output_dim=10, groups=2):
    return ResNetCifar(n=3, output_dim=output_dim, groups=groups)


def resnet56(output_dim=100, groups=2):
    return ResNetCifar(n=9, output_dim=output_dim, groups=groups)


def resnet18(output_dim=10, groups=2):
    return ResNet18(output_dim=output_dim, groups=groups)
