"""DARTS-style differentiable NAS cell — flax.

Parity: reference ``model/cv/darts/`` (the FedNAS search space). One
searchable cell: every edge mixes candidate ops with softmax-weighted
architecture parameters ("alphas") that live in the SAME params tree as
the weights, so federated averaging of alphas == the FedNAS search step
(the reference exchanges alphas and weights exactly this way).
"""
from __future__ import annotations


import flax.linen as nn
import jax.numpy as jnp

OPS = ("skip", "conv3", "conv5", "maxpool", "zero")


class MixedOp(nn.Module):
    channels: int

    @nn.compact
    def __call__(self, x, alpha):
        outs = []
        for op in OPS:
            if op == "skip":
                outs.append(x)
            elif op == "conv3":
                h = nn.Conv(self.channels, (3, 3), padding=1, use_bias=False)(x)
                h = nn.GroupNorm(num_groups=min(8, self.channels))(h)
                outs.append(nn.relu(h))
            elif op == "conv5":
                h = nn.Conv(self.channels, (5, 5), padding=2, use_bias=False)(x)
                h = nn.GroupNorm(num_groups=min(8, self.channels))(h)
                outs.append(nn.relu(h))
            elif op == "maxpool":
                outs.append(
                    nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
                )
            else:  # zero
                outs.append(jnp.zeros_like(x))
        w = nn.softmax(alpha)
        return sum(wi * o for wi, o in zip(w, outs))


class DARTSCell(nn.Module):
    channels: int
    n_nodes: int = 3

    @nn.compact
    def __call__(self, x):
        # alphas: one op-mix vector per (node, predecessor) edge; stored as a
        # normal parameter so they federate/aggregate like weights
        n_edges = sum(i + 1 for i in range(self.n_nodes))
        alphas = self.param(
            "alphas", nn.initializers.zeros, (n_edges, len(OPS)), jnp.float32
        )
        states = [x]
        e = 0
        for i in range(self.n_nodes):
            acc = 0.0
            for prev in states:
                acc = acc + MixedOp(self.channels)(prev, alphas[e])
                e += 1
            states.append(acc)
        return jnp.concatenate(states[1:], axis=-1)


class DARTSNetwork(nn.Module):
    """Stem → searchable cells → classifier (FedNAS search network)."""

    output_dim: int = 10
    channels: int = 16
    n_cells: int = 2

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.channels, (3, 3), padding=1, use_bias=False)(x)
        h = nn.GroupNorm(num_groups=min(8, self.channels))(h)
        for i in range(self.n_cells):
            h = DARTSCell(self.channels, name=f"cell_{i}")(h)
            h = nn.Conv(self.channels, (1, 1), use_bias=False)(h)  # re-project
            if i < self.n_cells - 1:
                h = nn.max_pool(h, (2, 2), strides=(2, 2))
        h = jnp.mean(h, axis=(1, 2))
        return nn.Dense(self.output_dim)(h)
