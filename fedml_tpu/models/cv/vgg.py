"""VGG-11/16 — flax, GroupNorm variant for federation.

Parity: reference ``model/cv/vgg.py``.
"""
from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

_CFGS = {
    "vgg11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "vgg16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    output_dim: int = 10
    groups: int = 8

    @nn.compact
    def __call__(self, x):
        h = x
        for v in self.cfg:
            if v == "M":
                h = nn.max_pool(h, (2, 2), strides=(2, 2))
            else:
                h = nn.Conv(int(v), (3, 3), padding=1, use_bias=False)(h)
                h = nn.GroupNorm(num_groups=min(self.groups, int(v)))(h)
                h = nn.relu(h)
        h = jnp.mean(h, axis=(1, 2))  # adaptive pool → classifier
        h = nn.Dense(512)(h)
        h = nn.relu(h)
        return nn.Dense(self.output_dim)(h)


def vgg11(output_dim: int = 10) -> VGG:
    return VGG(cfg=_CFGS["vgg11"], output_dim=output_dim)


def vgg16(output_dim: int = 10) -> VGG:
    return VGG(cfg=_CFGS["vgg16"], output_dim=output_dim)
