"""EfficientNet-lite (B0-class) — flax.

Parity: reference ``model/cv/efficientnet.py``. MBConv stack with the lite
simplifications (no SE in lite variants; GroupNorm for federation — no
running batch stats to ship).
"""
from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


class MBConv(nn.Module):
    expand_ratio: int
    out_ch: int
    kernel: int
    stride: int
    groups: int = 8

    @nn.compact
    def __call__(self, x):
        inp = x.shape[-1]
        mid = inp * self.expand_ratio
        h = x
        if self.expand_ratio != 1:
            h = nn.Conv(mid, (1, 1), use_bias=False)(h)
            h = nn.GroupNorm(num_groups=min(self.groups, mid))(h)
            h = nn.relu6(h)
        h = nn.Conv(mid, (self.kernel, self.kernel),
                    strides=(self.stride, self.stride),
                    feature_group_count=mid, use_bias=False)(h)
        h = nn.GroupNorm(num_groups=min(self.groups, mid))(h)
        h = nn.relu6(h)
        h = nn.Conv(self.out_ch, (1, 1), use_bias=False)(h)
        h = nn.GroupNorm(num_groups=min(self.groups, self.out_ch))(h)
        if self.stride == 1 and inp == self.out_ch:
            h = h + x
        return h


class EfficientNetLite0(nn.Module):
    output_dim: int = 10

    # (expand, out, kernel, stride, repeats) — B0 table
    CFG: Sequence[Tuple[int, int, int, int, int]] = (
        (1, 16, 3, 1, 1),
        (6, 24, 3, 2, 2),
        (6, 40, 5, 2, 2),
        (6, 80, 3, 2, 3),
        (6, 112, 5, 1, 3),
        (6, 192, 5, 2, 4),
        (6, 320, 3, 1, 1),
    )

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(32, (3, 3), strides=(2, 2), use_bias=False)(x)
        h = nn.GroupNorm(num_groups=8)(h)
        h = nn.relu6(h)
        for e, o, k, s, r in self.CFG:
            for i in range(r):
                h = MBConv(e, o, k, s if i == 0 else 1)(h)
        h = nn.Conv(1280, (1, 1), use_bias=False)(h)
        h = nn.GroupNorm(num_groups=8)(h)
        h = nn.relu6(h)
        h = jnp.mean(h, axis=(1, 2))
        return nn.Dense(self.output_dim)(h)
