"""CNNs from the reference zoo (``model/cv/cnn.py``): the FedAvg-paper
femnist CNN (two 5×5 convs) and a CIFAR variant. NHWC layout — XLA's native
conv layout on TPU.
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CNNFemnist(nn.Module):
    """Conv(32,5x5)-pool-Conv(64,5x5)-pool-Dense(2048)-Dense(out).

    Parity: ``model/cv/cnn.py`` CNN_DropOut for femnist/mnist.
    """

    output_dim: int = 62
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 2:  # flat 784 → 28×28×1
            side = int(jnp.sqrt(x.shape[-1]))
            x = x.reshape((x.shape[0], side, side, 1))
        x = nn.relu(nn.Conv(32, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(2048)(x))
        if self.dropout > 0:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.output_dim)(x)


class LeNet5(nn.Module):
    """Classic LeNet-5 — the cross-device on-device model.

    Parity: ``model/mobile/mnn_lenet`` (the reference ships LeNet as the
    .mnn file BeeHive phones train); here it is the same architecture in
    flax for the JAX device runtime.
    """

    output_dim: int = 10

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:  # flat 784 → 28×28×1
            side = int(jnp.sqrt(x.shape[-1]))
            x = x.reshape((x.shape[0], side, side, 1))
        x = nn.relu(nn.Conv(6, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(16, (5, 5), padding="VALID")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        return nn.Dense(self.output_dim)(x)


class CNNCifar(nn.Module):
    output_dim: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(nn.Conv(32, (3, 3), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), padding="SAME")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), padding="SAME")(x))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(64)(x))
        return nn.Dense(self.output_dim)(x)
