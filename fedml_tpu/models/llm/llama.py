"""Llama-family causal LM — the flagship model of the LLM path.

Parity target: the reference fine-tunes HF Llama/GPT-NeoX checkpoints via
``train/llm`` (``configurations.py:140`` ModelArguments, flash-attn patch
``models/attention.py:30``). Here the architecture is implemented natively
in flax so the whole forward/backward is one XLA program:

- RMSNorm, rotary position embeddings, grouped-query attention, SwiGLU MLP
  (Llama-2/3 architecture);
- attention runs through the framework's Pallas flash kernel on TPU
  (``fedml_tpu/ops/flash_attention.py``) and plain XLA elsewhere;
- optional LoRA adapters on the attention projections (the federated LLM
  path exchanges *only* these — reference ``configurations.py:291``
  ``get_peft_config`` / ``peft_utils.py``);
- weights are stored with named axes that match the FSDP×TP partition
  rules in ``fedml_tpu/train/llm/sharding.py``.

Compute dtype is bf16 by default (MXU-native); params stay fp32 masters.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    # LoRA (0 = disabled)
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # Mixture-of-experts FFN (0 = dense MLP). Experts shard over the
    # mesh's "ep" axis (expert parallelism).
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 1.25
    moe_group_size: int = 1024  # routing group: dispatch memory is O(S·g·K)
    moe_aux_weight: float = 0.01  # load-balance pressure in the train loss
    # training knobs
    dtype: Any = jnp.bfloat16
    # storage dtype of the FROZEN base weights. fp32 default (full-FT
    # masters); LoRA fine-tuning can store the base in bf16 — frozen
    # weights need no master copy, and bf16 halves both HBM residency
    # and the per-step cast traffic (see PERF_NOTES.md)
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "full": recompute the whole block in backward (min memory, +1/3
    # forward flops); "dots": save matmul outputs, recompute elementwise
    # only (the XLA sweet spot — matmuls are the expensive part and HBM
    # usually fits their outputs); "none"/remat=False: save everything
    remat_policy: str = "full"
    use_flash: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    # -- presets (kw overrides win — e.g. a reduced-depth 7B) ------------
    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        for k, v in dict(
            vocab_size=32000, hidden_size=4096, intermediate_size=11008,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=32,
        ).items():
            kw.setdefault(k, v)
        return LlamaConfig(**kw)

    @staticmethod
    def llama2_13b(**kw) -> "LlamaConfig":
        for k, v in dict(
            vocab_size=32000, hidden_size=5120, intermediate_size=13824,
            num_hidden_layers=40, num_attention_heads=40,
            num_key_value_heads=40,
        ).items():
            kw.setdefault(k, v)
        return LlamaConfig(**kw)

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        for k, v in dict(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, rope_theta=500000.0,
        ).items():
            kw.setdefault(k, v)
        return LlamaConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """Unit-test / dry-run scale (runs on CPU in milliseconds)."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("remat", False)
        return LlamaConfig(**kw)

    PRESETS = ("tiny", "llama2_7b", "llama2_13b", "llama3_8b")

    @staticmethod
    def from_args(args: Any, vocab_size: Optional[int] = None) -> "LlamaConfig":
        preset = str(
            getattr(args, "model_size", None)
            or getattr(args, "model_name", "tiny")
        ).lower().replace("-", "_")
        kw = {}
        for field in ("lora_rank", "lora_alpha", "max_position_embeddings",
                      "num_hidden_layers", "hidden_size", "num_experts",
                      "num_experts_per_tok", "moe_capacity_factor"):
            if getattr(args, field, None) is not None:
                kw[field] = type(LlamaConfig.__dataclass_fields__[field].default)(
                    getattr(args, field)
                )
        if getattr(args, "use_flash_attention", None) is not None:
            kw["use_flash"] = bool(args.use_flash_attention)
        if getattr(args, "remat_policy", None) is not None:
            kw["remat_policy"] = str(args.remat_policy)
        if bool(getattr(args, "base_params_bf16", False)):
            kw["param_dtype"] = jnp.bfloat16
        builder = {
            "tiny": LlamaConfig.tiny,
            "llama2_7b": LlamaConfig.llama2_7b,
            "7b": LlamaConfig.llama2_7b,
            "llama2_13b": LlamaConfig.llama2_13b,
            "13b": LlamaConfig.llama2_13b,
            "llama3_8b": LlamaConfig.llama3_8b,
            "8b": LlamaConfig.llama3_8b,
        }.get(preset, LlamaConfig.tiny)
        # build the preset bare, then overlay user overrides — presets pass
        # their architecture fields explicitly, so builder(**kw) would raise
        # 'multiple values' for overlapping keys
        cfg = builder()
        if kw:
            cfg = dataclasses.replace(cfg, **kw)
        if vocab_size is not None and preset == "tiny":
            cfg = dataclasses.replace(cfg, vocab_size=max(vocab_size, 32))
        return cfg


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------
class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + self.eps)
        return (normed * scale).astype(self.dtype)


def rope_tables(positions: jax.Array, head_dim: int, theta: float):
    """cos/sin tables for rotary embeddings; positions [B, T] or [T]."""
    freqs = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., T, D/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array):
    """x: [B, H, T, D]; cos/sin: [B, T, D/2] or [T, D/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    if cos.ndim == 2:
        cos, sin = cos[None, None], sin[None, None]
    else:
        cos, sin = cos[:, None], sin[:, None]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


def _maybe_packed_param(module, name, init_box, shape, dtype):
    """``self.param``, except a 4-bit packed kernel is read straight from
    the variable dict.

    Flax's param path leaf-compares the stored value against the
    initializer's eval_shape; an int8 :class:`QuantizedTensor` passes
    (its data keeps the kernel shape) but a :class:`QuantizedTensor4`
    legitimately differs — packed nibbles are ``[n_blocks, block//2]``.
    The packed base is frozen (never initialized, never differentiated),
    so skipping the shape check loses nothing.
    """
    from fedml_tpu.ops.quant import QuantizedTensor4

    scope = module.scope
    if scope.has_variable("params", name):
        v = scope.get_variable("params", name)
        # raw model.init params keep flax partitioning boxes; the packed
        # value may live inside one (the trainer stores unboxed)
        if isinstance(v, nn.meta.AxisMetadata):
            v = v.unbox()
        if isinstance(v, QuantizedTensor4):
            return v
    return module.param(name, init_box, shape, dtype)


class LoRADense(nn.Module):
    """Dense with optional additive low-rank adapter: y = xW + (x A) B * s.

    The base kernel is a normal flax param (frozen by the LLM optimizer
    mask); ``lora_a/lora_b`` live under the same params tree with a
    ``lora_`` name prefix, which is what the trainable/exchange filters key
    on (``fedml_tpu/train/llm/federated.py``).
    """

    features: int
    rank: int = 0
    alpha: float = 16.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32  # base kernel storage; lora_a/b stay fp32
    kernel_axes: Tuple[str, ...] = ()

    @nn.compact
    def __call__(self, x):
        kernel = _maybe_packed_param(
            self,
            "kernel",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), self.kernel_axes
            ),
            (x.shape[-1], self.features),
            self.param_dtype,
        )
        from fedml_tpu.ops.quant import matmul_maybe_quantized

        y = matmul_maybe_quantized(x, kernel, self.dtype)
        if self.rank > 0:
            a = self.param(
                "lora_a",
                nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(),
                    (self.kernel_axes[0] if self.kernel_axes else None, None),
                ),
                (x.shape[-1], self.rank),
                jnp.float32,
            )
            b = self.param(
                "lora_b",
                nn.with_logical_partitioning(
                    nn.initializers.zeros,
                    (None, self.kernel_axes[1] if len(self.kernel_axes) > 1 else None),
                ),
                (self.rank, self.features),
                jnp.float32,
            )
            scaling = self.alpha / self.rank
            y = y + (x @ a.astype(self.dtype)) @ b.astype(self.dtype) * scaling
        return y


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin, kv_cache=None, attention_fn=None):
        cfg = self.cfg
        b, t, _ = x.shape
        h, hkv, d = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        dense = lambda feats, name, axes: LoRADense(
            feats, rank=cfg.lora_rank, alpha=cfg.lora_alpha, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, kernel_axes=axes, name=name,
        )
        q = dense(h * d, "q_proj", ("embed", "heads"))(x)
        k = dense(hkv * d, "k_proj", ("embed", "heads"))(x)
        v = dense(hkv * d, "v_proj", ("embed", "heads"))(x)
        q = q.reshape(b, t, h, d).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, hkv, d).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, hkv, d).transpose(0, 2, 1, 3)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        new_cache = None
        if kv_cache is not None:
            # decode: append to cache, attend over full prefix. cache_len may
            # be a scalar (all rows aligned) or a [B] vector of per-row
            # lengths — the latter is what continuous batching needs: each
            # slot of the serving batch sits at its own position.
            ck, cv, cache_len = kv_cache
            lens = jnp.broadcast_to(jnp.asarray(cache_len), (b,))
            ck = jax.vmap(
                lambda c, kk, l: jax.lax.dynamic_update_slice(c, kk, (0, l, 0))
            )(ck, k, lens)
            cv = jax.vmap(
                lambda c, vv, l: jax.lax.dynamic_update_slice(c, vv, (0, l, 0))
            )(cv, v, lens)
            k, v = ck, cv
            new_cache = (ck, cv, cache_len + t)
            s_len = ck.shape[2]
            group = h // hkv
            kk = jnp.repeat(k, group, axis=1)
            vv = jnp.repeat(v, group, axis=1)
            scale = d ** -0.5
            logits = jnp.einsum(
                "bhtd,bhsd->bhts", q.astype(jnp.float32), kk.astype(jnp.float32)
            ) * scale
            pos = lens[:, None] + jnp.arange(t)[None, :]  # [B, T]
            mask = (
                jnp.arange(s_len)[None, None, :] <= pos[:, :, None]
            )  # causal over each row's prefix [B, T, S]
            logits = jnp.where(mask[:, None], logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1)
            out = jnp.einsum("bhts,bhsd->bhtd", probs, vv.astype(jnp.float32))
            out = out.astype(cfg.dtype)
        else:
            if attention_fn is not None:
                out = attention_fn(q, k, v)
            elif cfg.use_flash:
                from fedml_tpu.ops.flash_attention import flash_attention

                out = flash_attention(q, k, v, causal=True)
            else:
                from fedml_tpu.ops.flash_attention import reference_attention

                out = reference_attention(q, k, v, causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(b, t, h * d)
        out = dense(cfg.hidden_size, "o_proj", ("heads", "embed"))(out)
        return out, new_cache


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name, axes: LoRADense(
            feats, rank=0, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_axes=axes, name=name
        )
        gate = dense(cfg.intermediate_size, "gate_proj", ("embed", "mlp"))(x)
        up = dense(cfg.intermediate_size, "up_proj", ("embed", "mlp"))(x)
        return dense(cfg.hidden_size, "down_proj", ("mlp", "embed"))(
            nn.silu(gate) * up
        )


class LlamaMoE(nn.Module):
    """Mixture-of-experts FFN (Mixtral/Switch shape) with expert parallelism.

    Expert weights are stacked with a leading ``expert`` logical dim,
    mapped to the mesh's ``ep`` axis (``train/llm/sharding.py``): the
    dispatch/combine einsums below contract token-major tensors against
    expert-major ones, and XLA inserts the all-to-alls that a hand-written
    NCCL MoE would issue. Top-k routing with capacity dropping; aux
    load-balance loss is sown as an intermediate. No reference
    counterpart — the reference has no MoE anywhere (SURVEY §2.10).
    """

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        E, K = cfg.num_experts, cfg.num_experts_per_tok
        B, T, H = x.shape
        S = B * T
        # Route within fixed-size token groups (Switch/Mesh-TF grouping):
        # dispatch/combine are [G, g, E, cap] with cap ∝ g·K/E, so memory
        # is O(S·g·K) — linear in S — instead of O(S²·K) ungrouped.
        g = min(int(cfg.moe_group_size), S)
        S_pad = ((S + g - 1) // g) * g
        xs = x.reshape(S, H)
        if S_pad != S:
            # padding tokens route like zeros and are sliced off after the
            # combine; they only waste capacity in the tail group
            xs = jnp.concatenate(
                [xs, jnp.zeros((S_pad - S, H), xs.dtype)], axis=0
            )
        G = S_pad // g
        xg = xs.reshape(G, g, H)
        # router in f32 for numerically-stable softmax/top-k
        router_w = self.param(
            "router",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("embed", None)
            ),
            (H, E), jnp.float32,
        )
        logits = xg.astype(jnp.float32) @ router_w              # [G, g, E]
        probs = jax.nn.softmax(logits, axis=-1)
        top_vals, top_idx = jax.lax.top_k(probs, K)             # [G, g, K]
        top_vals = top_vals / jnp.sum(top_vals, -1, keepdims=True)

        cap = max(4, int(cfg.moe_capacity_factor * g * K / E))
        counts = jnp.zeros((G, E), jnp.int32)
        dispatch = jnp.zeros((G, g, E, cap), cfg.dtype)
        combine = jnp.zeros((G, g, E, cap), jnp.float32)
        for j in range(K):  # K is tiny and static — unrolled at trace time
            oh = jax.nn.one_hot(top_idx[..., j], E, dtype=jnp.int32)  # [G,g,E]
            pos = counts[:, None, :] + jnp.cumsum(oh, 1) - oh         # [G,g,E]
            counts = counts + jnp.sum(oh, 1)
            keep = (pos < cap) & (oh > 0)                 # capacity dropping
            slot = jax.nn.one_hot(pos, cap, dtype=jnp.float32)  # [G,g,E,cap]
            sel = slot * keep[..., None].astype(jnp.float32)
            dispatch = dispatch + sel.astype(cfg.dtype)
            combine = combine + sel * top_vals[..., j, None, None]

        def experts(feats, name, in_axis, out_axis):
            return self.param(
                name,
                nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), ("expert", in_axis, out_axis)
                ),
                (E, *feats), cfg.param_dtype,
            )

        M = cfg.intermediate_size
        w_gate = experts((H, M), "gate_proj", "embed", "mlp")
        w_up = experts((H, M), "up_proj", "embed", "mlp")
        w_down = experts((M, H), "down_proj", "mlp", "embed")

        ein = xs.dtype
        expert_in = jnp.einsum("gsec,gsh->egch", dispatch, xg)   # all-to-all
        gate = jnp.einsum("egch,ehm->egcm", expert_in, w_gate.astype(ein))
        up = jnp.einsum("egch,ehm->egcm", expert_in, w_up.astype(ein))
        out = jnp.einsum("egcm,emh->egch",
                         nn.silu(gate) * up, w_down.astype(ein))
        ys = jnp.einsum("gsec,egch->gsh", combine.astype(ein), out)
        ys = ys.reshape(S_pad, H)[:S]                            # drop padding

        # Switch aux loss: E * Σ_e (fraction routed to e) * (mean prob of e),
        # over REAL tokens only — pad rows have uniform router probs whose
        # top-1 tie-breaks to expert 0 and would skew the statistics
        valid = (jnp.arange(S_pad) < S).astype(jnp.float32).reshape(G, g)
        n_valid = jnp.maximum(jnp.sum(valid), 1.0)
        top1 = jax.nn.one_hot(top_idx[..., 0], E, dtype=jnp.float32)
        frac = jnp.sum(top1 * valid[..., None], (0, 1)) / n_valid
        mean_prob = jnp.sum(probs * valid[..., None], (0, 1)) / n_valid
        aux = E * jnp.sum(frac * mean_prob)
        self.sow("intermediates", "moe_aux_loss", aux)
        return ys.reshape(B, T, H)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin, kv_cache=None, attention_fn=None):
        cfg = self.cfg
        # pin the residual stream to (batch, seq, embed) so SPMD never
        # round-trips activations through a tp-sharded layout in the bwd pass
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        attn_out, new_cache = LlamaAttention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_norm")(x),
            cos, sin, kv_cache, attention_fn,
        )
        x = x + attn_out
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        ffn = (LlamaMoE(cfg, name="moe") if cfg.num_experts > 0
               else LlamaMLP(cfg, name="mlp"))
        x = x + ffn(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="post_attn_norm")(x)
        )
        return x, new_cache


class LlamaForCausalLM(nn.Module):
    """Token ids [B, T] → logits [B, T, V].

    ``__call__(tokens)`` is the training forward; ``decode_step`` threads an
    explicit KV cache for serving (``fedml_tpu/serving``).
    """

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, positions=None, kv_caches=None, attention_fn=None):
        cfg = self.cfg
        emb = self.param(
            "embed_tokens",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")
            ),
            (cfg.vocab_size, cfg.hidden_size),
            cfg.param_dtype,
        )
        x = emb.astype(cfg.dtype)[tokens]
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)

        block = LlamaBlock
        if cfg.remat and cfg.remat_policy != "none" and kv_caches is None:
            policy = None  # "full": save only block inputs
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            block = nn.remat(LlamaBlock, static_argnums=(5,), policy=policy)
        new_caches = []
        for i in range(cfg.num_hidden_layers):
            cache_i = kv_caches[i] if kv_caches is not None else None
            x, new_cache = block(cfg, name=f"layer_{i}")(
                x, cos, sin, cache_i, attention_fn
            )
            new_caches.append(new_cache)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="final_norm")(x)
        if cfg.tie_word_embeddings:
            logits = x @ emb.astype(cfg.dtype).T
        else:
            head = _maybe_packed_param(
                self,
                "lm_head",
                nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), ("embed", "vocab")
                ),
                (cfg.hidden_size, cfg.vocab_size),
                cfg.param_dtype,
            )
            from fedml_tpu.ops.quant import matmul_maybe_quantized

            logits = matmul_maybe_quantized(x, head, cfg.dtype)
        logits = logits.astype(jnp.float32)
        if kv_caches is not None:
            return logits, new_caches
        return logits

    # -- serving helpers --------------------------------------------------
    def init_kv_caches(self, batch: int, max_len: int):
        cfg = self.cfg
        shape = (batch, cfg.num_key_value_heads, max_len, cfg.head_dim)
        return [
            (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype), 0)
            for _ in range(cfg.num_hidden_layers)
        ]


def causal_lm_loss(apply_fn):
    """Next-token CE over a [B, T] token batch; mask is [B] sample validity.

    Matches the trainer contract in ``ml/trainer/local_sgd.py`` so the LLM
    drops into every federated engine unchanged.
    """
    import optax

    def loss_fn(params, x, y, mask):
        out = apply_fn(params, x)  # y: next tokens [B, T]
        # MoE apply_fns return (logits, aux_loss); dense ones return logits
        logits, aux = out if isinstance(out, tuple) else (out, 0.0)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        valid = (y >= 0).astype(jnp.float32) * mask[:, None]
        total = jnp.sum(ce * valid)
        denom = jnp.maximum(jnp.sum(valid), 1.0)
        pred = jnp.argmax(logits, axis=-1)
        correct = jnp.sum((pred == y).astype(jnp.float32) * valid)
        return total / denom + aux, (correct, denom)

    return loss_fn
