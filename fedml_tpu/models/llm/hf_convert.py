"""HuggingFace Llama checkpoint → fedml_tpu flax params.

Parity target: the reference consumes Llama weights through HF
`transformers` directly (``train/llm/configurations.py`` model loading;
``spotlight_prj/fedllm`` targets ``meta-llama/Llama-2-7b-hf``). The TPU
build has its own flax implementation, so real checkpoints enter through
this converter: HF parameter names/layouts → the fedml_tpu tree, with
every tensor's shape checked and every unconsumed HF key reported.

Layout notes (verified by the logit-parity test):
- HF ``nn.Linear`` stores [out, in]; flax Dense kernels are [in, out]
  → transpose every projection;
- both sides use the half-split RoPE ("rotate_half") with the same
  frequency schedule, so q/k need NO permutation;
- LoRA adapters are fedml_tpu-local (zero-initialized ``lora_b`` makes
  them a no-op at load) and are left untouched.

Usage:
    params = model.init(key, tokens)                  # template tree
    params = convert_hf_llama_state_dict(sd, params)  # sd: HF state_dict
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import numpy as np

__all__ = ["convert_hf_llama_state_dict", "hf_key_map"]

# HF buffers that are derived, not weights
_IGNORABLE_SUFFIXES = (".rotary_emb.inv_freq",)


def hf_key_map(num_layers: int) -> Dict[str, Tuple[str, bool]]:
    """{fedml_flat_name: (hf_key, transpose)} for a Llama of given depth."""
    m: Dict[str, Tuple[str, bool]] = {
        "params/embed_tokens": ("model.embed_tokens.weight", False),
        "params/final_norm/scale": ("model.norm.weight", False),
        "params/lm_head": ("lm_head.weight", True),
    }
    for i in range(num_layers):
        ours = f"params/layer_{i}"
        hf = f"model.layers.{i}"
        m[f"{ours}/input_norm/scale"] = (f"{hf}.input_layernorm.weight",
                                         False)
        m[f"{ours}/post_attn_norm/scale"] = (
            f"{hf}.post_attention_layernorm.weight", False)
        for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
            m[f"{ours}/attn/{proj}/kernel"] = (
                f"{hf}.self_attn.{proj}.weight", True)
        for proj in ("gate_proj", "up_proj", "down_proj"):
            m[f"{ours}/mlp/{proj}/kernel"] = (
                f"{hf}.mlp.{proj}.weight", True)
    return m


def _flat_name(path) -> str:
    keys = [str(getattr(p, "key", getattr(p, "name", p))) for p in path]
    name = "/".join(keys)
    # strip flax Partitioned metadata suffix (GetAttrKey('value'))
    return name.removesuffix("/.value").removesuffix("/value")


def convert_hf_llama_state_dict(state_dict: Dict[str, Any],
                                params: Any) -> Any:
    """Fill ``params`` (an initialized fedml_tpu Llama tree) from an HF
    Llama ``state_dict``. Raises on shape mismatches, missing tensors,
    and unconsumed HF keys (so a truncated/renamed checkpoint cannot
    load silently)."""
    def _to_np(v):
        if hasattr(v, "detach"):
            v = v.detach().cpu()
            # torch bf16 tensors reject .numpy(); fp32 round-trip is
            # exact for them (bf16 ⊂ fp32)
            if str(v.dtype) == "torch.bfloat16":
                v = v.float()
            return v.numpy()
        return np.asarray(v)

    sd = {k: _to_np(v) for k, v in state_dict.items()}
    tied = "lm_head.weight" not in sd and "model.embed_tokens.weight" in sd

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    n_layers = sum(1 for p, _ in flat
                   if _flat_name(p).endswith("input_norm/scale"))
    keymap = hf_key_map(n_layers)

    used = set()
    out = []
    for path, leaf in flat:
        name = _flat_name(path)
        if name not in keymap or "lora" in name:
            out.append(leaf)
            continue
        hf_key, transpose = keymap[name]
        if hf_key == "lm_head.weight" and tied:
            hf_key = "model.embed_tokens.weight"  # tied embeddings
        if hf_key not in sd:
            raise KeyError(f"HF checkpoint is missing {hf_key!r} "
                           f"(needed for {name})")
        w = sd[hf_key]
        if transpose:
            w = w.T
        if tuple(w.shape) != tuple(leaf.shape):
            raise ValueError(
                f"{name}: HF tensor {hf_key!r} has shape {w.shape}, "
                f"model expects {tuple(leaf.shape)}")
        used.add(hf_key)
        out.append(np.asarray(w, dtype=np.asarray(leaf).dtype))
    leftovers = [k for k in sd
                 if k not in used and not k.endswith(_IGNORABLE_SUFFIXES)
                 and not (tied and k == "model.embed_tokens.weight")]
    if leftovers:
        raise ValueError(
            f"{len(leftovers)} HF tensors were not consumed "
            f"(first few: {leftovers[:5]}) — config/depth mismatch?")
    return jax.tree_util.tree_unflatten(treedef, out)
