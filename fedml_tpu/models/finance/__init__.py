from fedml_tpu.models.finance.vfl_models import (
    VFLFeatureExtractor,
    VFLTopModel,
)

__all__ = ["VFLFeatureExtractor", "VFLTopModel"]
