"""Vertical-FL finance models.

Parity: reference ``model/finance/vfl_*.py`` (lending-club / NUS-WIDE
vertical models): each party owns a feature extractor over ITS feature
columns; the label party runs the top model on the concatenated
embeddings. Only embeddings/gradients cross parties — never raw features.
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class VFLFeatureExtractor(nn.Module):
    """One party's bottom model: its feature slice → embedding."""

    embed_dim: int = 16
    hidden: int = 64

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden)(x)
        h = nn.relu(h)
        return nn.Dense(self.embed_dim)(h)


class VFLTopModel(nn.Module):
    """Label party: concatenated party embeddings → logits."""

    output_dim: int = 2
    hidden: int = 32

    @nn.compact
    def __call__(self, embeddings):
        h = jnp.concatenate(embeddings, axis=-1)
        h = nn.Dense(self.hidden)(h)
        h = nn.relu(h)
        return nn.Dense(self.output_dim)(h)
