"""``fedml_tpu.models.create`` — the model factory.

Parity: ``model/model_hub.py:19-83`` (name×dataset dispatch). Returns a flax
module; parameters are created by the engine with an explicit PRNG key so
every client/server sees identical init given ``args.random_seed``.
"""
from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def create(args: Any, output_dim: int = 10) -> nn.Module:
    name = str(getattr(args, "model", "lr")).lower()
    from fedml_tpu.models.cv.cnn import CNNCifar, CNNFemnist
    from fedml_tpu.models.cv.resnet import resnet18, resnet20, resnet56
    from fedml_tpu.models.linear.lr import MLP, LogisticRegression
    from fedml_tpu.models.nlp.rnn import RNNOriginalFedAvg, RNNStackOverflow

    dataset = str(getattr(args, "dataset", "")).lower()
    groups = None if getattr(args, "group_norm_channels", 2) in (0, None) else int(
        getattr(args, "group_norm_channels", 2)
    )

    if name in ("lr", "logistic_regression"):
        return LogisticRegression(output_dim=output_dim)
    if name == "mlp":
        return MLP(hidden_dim=int(getattr(args, "hidden_dim", 128)), output_dim=output_dim)
    if name in ("cnn", "cnn_dropout"):
        if "cifar" in dataset or "cinic" in dataset:
            return CNNCifar(output_dim=output_dim)
        return CNNFemnist(output_dim=output_dim)
    if name in ("lenet", "lenet5", "mnn_lenet"):
        # cross-device on-device model (reference: model/mobile/mnn_lenet)
        from fedml_tpu.models.cv.cnn import LeNet5

        return LeNet5(output_dim=output_dim)
    if name in ("segnet", "deeplab", "unet"):
        from fedml_tpu.simulation.sp.fedseg import SegNet

        return SegNet(n_classes=output_dim,
                      width=int(getattr(args, "seg_width", 16)))
    if name in ("resnet18", "resnet18_gn"):
        return resnet18(output_dim=output_dim, groups=groups)
    if name in ("resnet20",):
        return resnet20(output_dim=output_dim, groups=groups)
    if name in ("resnet56", "resnet56_gn"):
        return resnet56(output_dim=output_dim, groups=groups)
    if name in ("mobilenet", "mobilenet_v3", "mobilenetv3"):
        from fedml_tpu.models.cv.mobilenet import MobileNetV3Small

        return MobileNetV3Small(output_dim=output_dim)
    if name in ("efficientnet", "efficientnet_b0", "efficientnet_lite0"):
        from fedml_tpu.models.cv.efficientnet import EfficientNetLite0

        return EfficientNetLite0(output_dim=output_dim)
    if name in ("vgg11", "vgg16", "vgg"):
        from fedml_tpu.models.cv.vgg import vgg11, vgg16

        return vgg16(output_dim) if name == "vgg16" else vgg11(output_dim)
    if name in ("darts", "fednas"):
        from fedml_tpu.models.cv.darts import DARTSNetwork

        return DARTSNetwork(
            output_dim=output_dim,
            channels=int(getattr(args, "darts_channels", 16)),
            n_cells=int(getattr(args, "darts_cells", 2)),
        )
    if name in ("rnn", "lstm"):
        if "stackoverflow" in dataset or "reddit" in dataset:
            return RNNStackOverflow(vocab_size=max(output_dim, 4))
        return RNNOriginalFedAvg(vocab_size=max(output_dim, 4))
    if name in ("llama", "llama_lora", "transformer"):
        from fedml_tpu.models.llm.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig.from_args(args, vocab_size=max(output_dim, 32))
        return LlamaForCausalLM(cfg)
    raise ValueError(f"unknown model {name!r}")


def init_params(model: nn.Module, args: Any, sample_input: Any) -> Any:
    key = jax.random.key(int(getattr(args, "random_seed", 0)))
    x = jnp.asarray(sample_input)
    return model.init(key, x)


def example_input(args: Any, feature_shape: Tuple[int, ...], int_tokens: bool = False):
    batch = int(getattr(args, "batch_size", 32))
    if int_tokens:
        return np.zeros((batch, *feature_shape), dtype=np.int32)
    return np.zeros((batch, *feature_shape), dtype=np.float32)
