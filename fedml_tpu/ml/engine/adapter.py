"""Engine-adapter implementation (see package docstring for scope)."""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


def _is_torch_tensor(x: Any) -> bool:
    mod = type(x).__module__
    return mod is not None and mod.split(".")[0] == "torch"


def to_numpy(data: Any) -> Any:
    """Torch tensors / jax arrays / numpy (nested in dict/list/tuple) →
    numpy, structure preserved."""
    if _is_torch_tensor(data):
        return data.detach().cpu().numpy()
    if isinstance(data, (jax.Array, np.ndarray, np.generic)):
        return np.asarray(data)
    if isinstance(data, dict):
        return {k: to_numpy(v) for k, v in data.items()}
    if isinstance(data, (list, tuple)):
        return type(data)(to_numpy(v) for v in data)
    return data


def to_jax(data: Any, dtype=None) -> Any:
    """Anything :func:`to_numpy` accepts → jax arrays (reference:
    ``convert_numpy_to_jax_data_format``, ``ml_engine_adapter.py:37``)."""
    out = to_numpy(data)
    if isinstance(out, np.ndarray):
        return jnp.asarray(out, dtype)
    if isinstance(out, dict):
        return {k: to_jax(v, dtype) for k, v in out.items()}
    if isinstance(out, (list, tuple)):
        return type(out)(to_jax(v, dtype) for v in out)
    return out


def dataset_to_arrays(dataset: Any,
                      limit: Optional[int] = None,
                      batched: Optional[bool] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Drain a torch ``Dataset``/``DataLoader`` (or any iterable of
    (x, y) pairs / batches) into stacked numpy (x, y) — the form the
    federated data registry partitions.

    ``batched`` says whether each yielded item is a batch (concatenate)
    or one sample (stack). Default: DataLoader-like objects (anything
    exposing ``batch_size``) are batches, everything else is
    per-sample — NOT a shape heuristic, which would silently corrupt
    e.g. segmentation datasets whose (x, y) dims coincide."""
    if batched is None:
        batched = getattr(dataset, "batch_size", None) is not None
    xs, ys = [], []
    for item in dataset:
        if not (isinstance(item, (list, tuple)) and len(item) == 2):
            raise ValueError(
                "expected an iterable of (x, y) samples or batches; got "
                f"{type(item).__name__}")
        x, y = to_numpy(item[0]), to_numpy(item[1])
        if np.ndim(x) == 0 or (hasattr(x, "shape") and x.shape == ()):
            raise ValueError("scalar sample; expected array-like x")
        xs.append(np.asarray(x))
        ys.append(np.asarray(y))
        if limit is not None and len(xs) >= limit:
            break
    if batched:
        return np.concatenate(xs, 0), np.concatenate(ys, 0)
    return np.stack(xs, 0), np.stack(ys, 0)


def get_device(args: Any = None):
    """Parity with the reference's ``get_jax_device``
    (``ml_engine_adapter.py:176``): pick a device by ``args.device`` /
    ``args.gpu_id`` (index), defaulting to the first accelerator."""
    devices = jax.devices()
    idx = 0
    if args is not None:
        want = getattr(args, "device", None)
        if isinstance(want, str) and ":" in want:
            idx = int(want.split(":")[-1])
        elif getattr(args, "gpu_id", None) is not None:
            idx = int(args.gpu_id)
    return devices[min(idx, len(devices) - 1)]


def device_count() -> int:
    return jax.device_count()


def _fits(src_shape, dst_shape):
    """Return a transform name mapping a torch tensor shape onto a flax
    kernel shape, or None."""
    if tuple(src_shape) == tuple(dst_shape):
        return "same"
    if len(src_shape) == 2 and tuple(src_shape[::-1]) == tuple(dst_shape):
        return "linear_t"  # torch Linear [out, in] → flax [in, out]
    if len(src_shape) == 4 and (
            src_shape[2], src_shape[3], src_shape[1], src_shape[0]
    ) == tuple(dst_shape):
        return "conv_t"  # torch Conv2d [O, I, H, W] → flax [H, W, I, O]
    return None


def _apply(x: np.ndarray, how: str) -> np.ndarray:
    if how == "same":
        return x
    if how == "linear_t":
        return x.T
    return np.transpose(x, (2, 3, 1, 0))


def import_torch_state_dict(flax_params: Pytree, state_dict: Dict[str, Any],
                            strict: bool = True) -> Pytree:
    """Map a torch ``state_dict`` onto a flax params tree by structural
    position: both are walked in layer order and each torch tensor must
    fit the corresponding flax leaf directly or via the standard
    Linear/Conv transposes.

    This is the generic zoo-scale importer (an exact named mapper for
    Llama lives in ``models/llm/hf_convert.py``). It requires the torch
    module to mirror the flax model's layer order — the natural case for
    the reference's sequential LR/MLP/CNN models. Buffers that have no
    flax twin (``num_batches_tracked``) are skipped. ``strict=False``
    leaves unmatched flax leaves at their initialized values.
    """
    entries = [(k, to_numpy(v)) for k, v in state_dict.items()
               if not k.endswith("num_batches_tracked")]
    flat, treedef = jax.tree_util.tree_flatten_with_path(flax_params)

    # Modules pair positionally, but WITHIN a module the two worlds order
    # differently (torch: weight, bias; flax sorts: bias, kernel) — so
    # group both sides by module and shape-match inside each group.
    def _groups(items, keyfn):
        out, cur_key = [], object()
        for it in items:
            k = keyfn(it)
            if k != cur_key:
                out.append([])
                cur_key = k
            out[-1].append(it)
        return out

    fgroups = _groups(flat, lambda pl: tuple(
        str(getattr(p, "key", p)) for p in pl[0][:-1]))
    tgroups = _groups(
        entries,
        lambda kv: kv[0].rsplit(".", 1)[0] if "." in kv[0] else "")
    if len(fgroups) != len(tgroups):
        if strict:
            raise ValueError(
                f"module count mismatch: flax has {len(fgroups)} modules, "
                f"torch state_dict has {len(tgroups)}")
        tgroups = tgroups[: len(fgroups)]

    filled: Dict[int, Any] = {}
    leaf_pos = 0
    for fg, tg in zip(fgroups, tgroups):
        unused = list(range(len(tg)))
        for path, leaf in fg:
            shape = np.shape(leaf)
            hit = None
            for ui in unused:
                how = _fits(np.shape(tg[ui][1]), shape)
                if how is not None:
                    hit = (ui, how)
                    break
            if hit is None:
                if strict:
                    name = "/".join(str(getattr(p, "key", p)) for p in path)
                    raise ValueError(
                        f"no torch tensor in module {tg[0][0].rsplit('.', 1)[0]!r} "
                        f"fits flax leaf {name} {shape} "
                        f"(candidates: {[np.shape(tg[u][1]) for u in unused]})")
                filled[leaf_pos] = leaf
            else:
                ui, how = hit
                unused.remove(ui)
                filled[leaf_pos] = jnp.asarray(
                    _apply(tg[ui][1], how), np.asarray(leaf).dtype)
            leaf_pos += 1
        if strict and unused:
            raise ValueError(
                f"torch tensors left over in module "
                f"{tg[0][0].rsplit('.', 1)[0]!r}: {[tg[u][0] for u in unused]}")
    leaves = [filled.get(i, flat[i][1]) for i in range(len(flat))]
    return jax.tree_util.tree_unflatten(treedef, leaves)
