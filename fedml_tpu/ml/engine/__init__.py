"""Multi-framework engine adapter — bring torch/numpy worlds into the
JAX compute path.

Parity target: ``ml/engine/ml_engine_adapter.py`` in the reference
(``:37 convert_numpy_to_jax_data_format``, ``:127`` jax device_count,
``:176 get_jax_device``, ``:291 jax_model_ddp``). The reference shims
four engines (torch/tf/jax/mxnet) behind one interface so trainers stay
engine-agnostic; here JAX **is** the engine, and the adapter solves the
practical half of that job: users arriving from the reference bring
torch datasets, torch tensors, and torch ``state_dict`` checkpoints —
this module converts each into the JAX-native form the framework runs.

- data: :func:`to_jax` / :func:`to_numpy` accept torch tensors, numpy,
  jax arrays, and nested containers; :func:`dataset_to_arrays` drains a
  torch ``Dataset``/``DataLoader`` into the (x, y) numpy pair the
  federated data registry uses;
- models: :func:`import_torch_state_dict` maps a torch ``state_dict``
  onto a structurally-matching flax params tree, transposing
  Linear/Conv kernels (torch ``[out, in]`` / ``[out, in, kh, kw]`` →
  flax ``[in, out]`` / ``[kh, kw, in, out]``). The LLM path has its own
  exact mapper (``models/llm/hf_convert.py``); this is the generic
  by-structure version for zoo-scale models;
- devices: :func:`get_device` / :func:`device_count` parity helpers
  (the reference's ``get_jax_device``); "DDP wrap" maps to sharding —
  see ``train/llm/sharding.py`` / ``parallel/`` (the reference's jax
  branch stubs it too, ``ml_engine_adapter.py:291``).
"""
from fedml_tpu.ml.engine.adapter import (
    dataset_to_arrays,
    device_count,
    get_device,
    import_torch_state_dict,
    to_jax,
    to_numpy,
)

__all__ = [
    "dataset_to_arrays",
    "device_count",
    "get_device",
    "import_torch_state_dict",
    "to_jax",
    "to_numpy",
]
