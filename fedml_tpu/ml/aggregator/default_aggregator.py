"""Default ServerAggregator — parity with ``ml/aggregator/default_aggregator.py``."""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.alg_frame.server_aggregator import ServerAggregator
from fedml_tpu.ml.trainer.local_sgd import build_evaluator

Pytree = Any


class DefaultServerAggregator(ServerAggregator):
    def __init__(self, model, args):
        super().__init__(model, args)
        self.apply_fn = lambda params, x: model.apply(params, x)
        self._evaluate = build_evaluator(self.apply_fn)

    def test(self, params: Pytree, test_data, device, args) -> dict:
        x, y = test_data
        loss_sum, correct, n = self._evaluate(
            params, jnp.asarray(np.asarray(x)), jnp.asarray(np.asarray(y))
        )
        n = float(n)
        return {
            "test_loss": float(loss_sum) / max(n, 1.0),
            "test_acc": float(correct) / max(n, 1.0),
            "test_total": n,
            "test_correct": float(correct),
        }


def create_server_aggregator(model: Any, args: Any) -> ServerAggregator:
    return DefaultServerAggregator(model, args)
