"""FedMLAggOperator — per-optimizer aggregation as one XLA program.

Parity target: ``ml/aggregator/agg_operator.py:8-60`` in the reference, which
loops over state-dict keys in Python per optimizer. Here every branch bottoms
out in :func:`fedml_tpu.utils.tree.weighted_tree_sum`: client trees are
stacked on a leading axis and reduced in a single jitted program, so cost is
a few fused HBM passes regardless of how many layers the model has.

Supported federated optimizers (reference list at ``constants.py:40-63``):
FedAvg/FedAvg_seq/FedSGD/FedProx/FedDyn/FedNova → sample-weighted average;
FedOpt → weighted average of client models, server optimizer applied by
``ml/aggregator/server_optimizer.py``; SCAFFOLD/Mime → uniform average of
(model, control-variate) pairs.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from fedml_tpu.utils.tree import tree_stack, weighted_tree_sum

Pytree = Any

_UNIFORM_OPTS = {"SCAFFOLD", "Mime"}


class FedMLAggOperator:
    @staticmethod
    def agg(args: Any, raw_grad_list: List[Tuple[int, Pytree]]) -> Pytree:
        """Aggregate ``[(n_samples, params), ...]`` → params.

        Weighting: n_k / sum(n) for the FedAvg family; uniform for
        SCAFFOLD/Mime (matching the reference's ``torch_aggregator``
        branches at ``agg_operator.py:33-58``).
        """
        opt = getattr(args, "federated_optimizer", "FedAvg")
        n = len(raw_grad_list)
        if n == 0:
            raise ValueError("empty client model list")
        counts = jnp.asarray([float(num) for num, _ in raw_grad_list])
        if opt in _UNIFORM_OPTS:
            weights = jnp.full((n,), 1.0 / n)
        else:
            weights = counts / jnp.sum(counts)
        stacked = tree_stack([params for _, params in raw_grad_list])
        return weighted_tree_sum(stacked, weights)

    @staticmethod
    def agg_with_weights(
        raw_list: List[Pytree], weights: List[float]
    ) -> Pytree:
        w = jnp.asarray(weights, dtype=jnp.float32)
        w = w / jnp.sum(w)
        return weighted_tree_sum(tree_stack(raw_list), w)
