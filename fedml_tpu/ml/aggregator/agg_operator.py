"""FedMLAggOperator — per-optimizer aggregation as one XLA program.

Parity target: ``ml/aggregator/agg_operator.py:8-60`` in the reference, which
loops over state-dict keys in Python per optimizer. Here every branch bottoms
out in :func:`fedml_tpu.utils.tree.weighted_tree_sum`: client trees are
stacked on a leading axis and reduced in a single jitted program, so cost is
a few fused HBM passes regardless of how many layers the model has.

Supported federated optimizers (reference list at ``constants.py:40-63``):
FedAvg/FedAvg_seq/FedSGD/FedProx/FedDyn/FedNova → sample-weighted average;
FedOpt → weighted average of client models, server optimizer applied by
``ml/aggregator/server_optimizer.py``; SCAFFOLD/Mime → uniform average of
(model, control-variate) pairs.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from fedml_tpu.utils.tree import tree_stack, weighted_tree_sum

Pytree = Any

_UNIFORM_OPTS = {"SCAFFOLD", "Mime"}


class FedMLAggOperator:
    @staticmethod
    def agg(args: Any, raw_grad_list: List[Tuple[int, Pytree]]) -> Pytree:
        """Aggregate ``[(n_samples, params), ...]`` → params.

        Weighting: n_k / sum(n) for the FedAvg family; uniform for
        SCAFFOLD/Mime (matching the reference's ``torch_aggregator``
        branches at ``agg_operator.py:33-58``).
        """
        opt = getattr(args, "federated_optimizer", "FedAvg")
        n = len(raw_grad_list)
        if n == 0:
            raise ValueError("empty client model list")
        counts = jnp.asarray([float(num) for num, _ in raw_grad_list])
        if opt in _UNIFORM_OPTS:
            weights = jnp.full((n,), 1.0 / n)
        else:
            weights = counts / jnp.sum(counts)
        stacked = tree_stack([params for _, params in raw_grad_list])
        return weighted_tree_sum(stacked, weights)

    @staticmethod
    def agg_with_weights(
        raw_list: List[Pytree], weights: List[float]
    ) -> Pytree:
        w = jnp.asarray(weights, dtype=jnp.float32)
        w = w / jnp.sum(w)
        return weighted_tree_sum(tree_stack(raw_list), w)

    @staticmethod
    def _weights(args: Any, raw_list: List[Tuple[int, Any]]) -> jnp.ndarray:
        """The same weighting rule :meth:`agg` applies, as a vector."""
        opt = getattr(args, "federated_optimizer", "FedAvg")
        n = len(raw_list)
        if opt in _UNIFORM_OPTS:
            return jnp.full((n,), 1.0 / n)
        counts = jnp.asarray([float(num) for num, _ in raw_list])
        return counts / jnp.sum(counts)

    @staticmethod
    def agg_compressed(
        args: Any, raw_list: List[Tuple[int, Any]], global_params: Pytree,
        clip_factors: Any = None, agg_robust: Any = None,
    ) -> Pytree:
        """Dequant-fused aggregation of compressed client updates.

        ``raw_list`` is ``[(n_samples, CompressedTree), ...]`` where each
        tree encodes the client's **delta** against ``global_params``
        (float leaves; int/bool leaves ride absolute — see ``tree_delta``).
        Since the weights are normalized, x̄ = Σpᵢxᵢ = g + Σpᵢdᵢ — so the
        stacked int8 blocks + scales reduce inside one jitted weighted
        sum and only the final aggregated f32 tree is materialized.

        ``agg_robust`` (a spec like ``trimmed_mean@0.1`` / ``median``)
        swaps the weighted mean for the coordinate-wise robust statistic
        of ``fedml_tpu.integrity.fused_robust_sum`` — same fused
        contract, sort-based reduction, deliberately unweighted (the
        statistic is shift-equivariant, so robust(deltas) + g equals
        the reference defense applied to full client models).
        """
        from fedml_tpu.compression import CompressedTree, fused_weighted_sum
        from fedml_tpu.compression.codecs import tree_undelta

        if len(raw_list) == 0:
            raise ValueError("empty client model list")
        cts = [ct for _, ct in raw_list]
        if not all(isinstance(ct, CompressedTree) for ct in cts):
            raise ValueError("agg_compressed requires CompressedTree updates")
        if not all(ct.is_delta for ct in cts):
            raise ValueError(
                "agg_compressed requires delta-encoded updates")
        if agg_robust:
            from fedml_tpu.integrity import (
                fused_robust_sum,
                parse_robust_spec,
            )

            if clip_factors is not None:
                raise ValueError(
                    "agg_robust cannot compose with norm-clip factors — "
                    "the robust statistic is unweighted, so there is no "
                    "weight to fold the clip into; pick one defense")
            mode, trim = parse_robust_spec(agg_robust)
            return tree_undelta(global_params,
                                fused_robust_sum(cts, mode, trim))
        weights = FedMLAggOperator._weights(args, raw_list)
        if clip_factors is not None:
            # norm-only defense on the fused path: clipping client i's
            # delta to the norm bound is d_i · f_i with
            # f_i = min(1, bound/‖d_i‖), and the weighted sum is linear,
            # so the factor folds into the weight — deliberately NOT
            # renormalized (clipping shrinks updates, it does not
            # redistribute their mass)
            import jax.numpy as jnp

            weights = weights * jnp.asarray(clip_factors, jnp.float32)
        return tree_undelta(global_params, fused_weighted_sum(cts, weights))
