"""Server-side optimizers on the aggregated pseudo-gradient.

Parity: the reference's FedOpt server update (``simulation/sp/fedopt``) and
FedNova normalization (``simulation/sp/fednova``), expressed as optax on the
pseudo-gradient ``g = w_global - w_aggregated`` (Reddi et al., FedOpt).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import optax

from fedml_tpu.utils.tree import tree_sub

Pytree = Any


class ServerOptimizer:
    """w_{t+1} = server_opt(w_t, pseudo_grad). FedAvg = plain replacement."""

    def __init__(self, args: Any):
        self.fed_opt = str(getattr(args, "federated_optimizer", "FedAvg"))
        name = str(getattr(args, "server_optimizer", "sgd")).lower()
        lr = float(getattr(args, "server_lr", 1.0))
        momentum = float(getattr(args, "server_momentum", 0.9))
        if self.fed_opt in ("FedOpt", "FedOpt_seq"):
            if name == "adam":
                self.tx = optax.adam(lr, b1=momentum)
            else:
                self.tx = optax.sgd(lr, momentum=momentum or None)
        elif self.fed_opt == "SCAFFOLD":
            self.tx = optax.sgd(lr)
        else:
            self.tx = None
        self._opt_state = None

    # -- round-checkpoint plumbing ---------------------------------------
    def get_state(self, params: Pytree) -> Pytree:
        """Materialized optimizer state (forces lazy init) for checkpoints."""
        if self.tx is None:
            return {}
        if self._opt_state is None:
            self._opt_state = self.tx.init(params)
        return self._opt_state

    def set_state(self, state: Pytree) -> None:
        if self.tx is not None:
            self._opt_state = state

    def step(self, w_global: Pytree, w_aggregated: Pytree,
             tau_eff: Optional[float] = None) -> Pytree:
        if self.fed_opt == "FedNova" and tau_eff is not None:
            # clients uploaded x̂_i = anchor − d_i (normalized updates);
            # x⁺ = anchor − τ_eff·Σ p_i d_i = anchor + τ_eff·(x̄ − anchor)
            t = float(tau_eff)
            return jax.tree.map(
                lambda g, a: g + t * (a - g), w_global, w_aggregated
            )
        if self.tx is None:
            return w_aggregated
        pseudo_grad = tree_sub(w_global, w_aggregated)
        if self._opt_state is None:
            self._opt_state = self.tx.init(w_global)
        updates, self._opt_state = self.tx.update(pseudo_grad, self._opt_state, w_global)
        return optax.apply_updates(w_global, updates)
