"""create_model_trainer — parity with ``ml/trainer/trainer_creator.py``."""
from __future__ import annotations

from typing import Any

from fedml_tpu.ml.trainer.classification_trainer import ClassificationTrainer


def create_model_trainer(model: Any, args: Any):
    # classification covers seq tasks too (3-D logits handled by the loss);
    # dataset-specific trainers can be registered here as they are added.
    return ClassificationTrainer(model, args)
