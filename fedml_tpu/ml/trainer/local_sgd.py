"""Jitted local-training programs — the client hot loop.

This replaces the reference's per-client torch loop
(``ml/trainer/my_model_trainer_classification.py`` + the algorithm-specific
local optimizers in ``ml/trainer/{fedprox,fednova,feddyn,scaffold,mime}_*``).

Design: one *compiled* function per (model, optimizer, shape) combination:

    run_local(params, extras, xs, ys, mask) -> (new_params, extras, metrics)

where ``xs/ys`` are [steps, batch, ...] arrays and ``mask`` is
[steps, batch] validity (pad-and-mask, static shapes → single XLA program,
local epochs under ``lax.scan``). ``extras`` carries algorithm state:
FedProx's anchor, SCAFFOLD's control variates, FedDyn's lagrangian term —
all explicit pytrees so the same program can be ``shard_map``'d over a
client mesh axis (simulation/parallel) with zero host round-trips.
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.utils.tree import tree_zeros_like

Pytree = Any


class LocalState(NamedTuple):
    """Algorithm extras threaded through local training (all optional trees).

    anchor: global params at round start (FedProx / FedDyn / SCAFFOLD / deltas)
    c_global/c_local: SCAFFOLD control variates
    h: FedDyn per-client lagrangian accumulator
    """

    anchor: Pytree
    c_global: Optional[Pytree] = None
    c_local: Optional[Pytree] = None
    h: Optional[Pytree] = None


def softmax_ce_loss(apply_fn):
    def loss_fn(params, x, y, mask):
        logits = apply_fn(params, x)
        if logits.ndim == 3:  # sequence task: [B, T, V] vs y [B, T]
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            ce = ce.mean(axis=-1)
        else:
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        total = jnp.sum(ce * mask)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        if logits.ndim == 3:
            pred = jnp.argmax(logits, axis=-1)
            correct = jnp.sum(jnp.mean((pred == y).astype(jnp.float32), axis=-1) * mask)
        else:
            pred = jnp.argmax(logits, axis=-1)
            correct = jnp.sum((pred == y).astype(jnp.float32) * mask)
        return total / denom, (correct, denom)

    return loss_fn


def build_optimizer(args: Any) -> optax.GradientTransformation:
    name = str(getattr(args, "client_optimizer", "sgd")).lower()
    lr = float(getattr(args, "learning_rate", 0.03))
    wd = float(getattr(args, "weight_decay", 0.0))
    momentum = float(getattr(args, "momentum", 0.0))
    chain = []
    if wd > 0:
        chain.append(optax.add_decayed_weights(wd))
    if name == "adam":
        chain.append(optax.adam(lr))
    elif name == "adamw":
        chain.append(optax.adamw(lr, weight_decay=wd))
    else:
        chain.append(optax.sgd(lr, momentum=momentum if momentum > 0 else None))
    return optax.chain(*chain)


def build_local_trainer(
    apply_fn: Callable,
    args: Any,
    loss_builder: Callable = softmax_ce_loss,
) -> Callable:
    """Compile the full local-training program for one client shape.

    Registered in the program catalog as ``sp/local_train`` — the sp
    backend's hot-path program — so its XLA flops/bytes/peak-HBM and
    recompile count feed the attribution layer."""
    from fedml_tpu.telemetry.profiling import wrap_jit

    return wrap_jit("sp/local_train",
                    jax.jit(build_local_fn(apply_fn, args, loss_builder)))


def build_local_fn(
    apply_fn: Callable,
    args: Any,
    loss_builder: Callable = softmax_ce_loss,
) -> Callable:
    """The *un-jitted* local-training program.

    run_local(params, state: LocalState, xs, ys, mask)
      -> (new_params, new_state, metrics dict)

    Exposed un-jitted so the mesh simulator can ``vmap`` it over a
    client axis and ``shard_map`` the result over devices — the whole
    round (N clients' local SGD + FedAvg psum) becomes ONE XLA program.
    """
    fed_opt = str(getattr(args, "federated_optimizer", "FedAvg"))
    mu = float(getattr(args, "fedprox_mu", 0.1))
    feddyn_alpha = float(getattr(args, "feddyn_alpha", 0.01))
    mime_beta = float(getattr(args, "mime_beta", 0.9))
    lr = float(getattr(args, "learning_rate", 0.03))
    base_loss = loss_builder(apply_fn)
    tx = build_optimizer(args)

    def loss_fn(params, state: LocalState, x, y, mask):
        loss, aux = base_loss(params, x, y, mask)
        if fed_opt == "FedProx":
            prox = 0.5 * mu * sum(
                jnp.sum((p - a) ** 2)
                for p, a in zip(jax.tree.leaves(params), jax.tree.leaves(state.anchor))
            )
            loss = loss + prox
        elif fed_opt == "FedDyn":
            lin = sum(
                jnp.vdot(h, p)
                for h, p in zip(jax.tree.leaves(state.h), jax.tree.leaves(params))
            )
            quad = 0.5 * feddyn_alpha * sum(
                jnp.sum((p - a) ** 2)
                for p, a in zip(jax.tree.leaves(params), jax.tree.leaves(state.anchor))
            )
            loss = loss - lin + quad
        return loss, aux

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def _scan(f, carry, batches, params):
        # XLA:CPU runs convolutions inside while-loops ~18x slower than
        # unrolled (the loop blocks the fast conv layout path — measured in
        # PERF_NOTES.md round-3 addendum); fully unroll small scans there.
        # Only conv models (rank-4 kernels) pay the loop tax — dense/LSTM
        # models keep the rolled scan and its fast compile. TPU always
        # keeps the rolled scan: loops compile fast and run at speed.
        n = jax.tree.leaves(batches)[0].shape[0]
        has_conv = any(getattr(leaf, "ndim", 0) == 4
                       for leaf in jax.tree.leaves(params))
        unroll = n if (
            jax.default_backend() == "cpu" and has_conv and n <= 32
        ) else 1
        return jax.lax.scan(f, carry, batches, unroll=unroll)

    def run_local(params, state: LocalState, xs, ys, mask):
        opt_state = tx.init(params)

        # Mime (Karimireddy et al. '21): the full-batch local gradient at the
        # round anchor drives both the SVRG correction and the server
        # momentum update — one masked pass over the staged batches
        mime_full_grad = None
        if fed_opt == "Mime":
            def accum(carry, batch):
                gsum, wsum = carry
                x, y, m = batch
                (_, _), g = grad_fn(state.anchor, state, x, y, m)
                w = jnp.sum(m)
                gsum = jax.tree.map(lambda a, b: a + b * w, gsum, g)
                return (gsum, wsum + w), None

            (gsum, wsum), _ = _scan(
                accum, (tree_zeros_like(params), 0.0), (xs, ys, mask), params
            )
            mime_full_grad = jax.tree.map(
                lambda g: g / jnp.maximum(wsum, 1.0), gsum
            )

        def step(carry, batch):
            params, opt_state = carry
            x, y, m = batch
            (loss, (correct, denom)), grads = grad_fn(params, state, x, y, m)
            if fed_opt == "SCAFFOLD" and state.c_global is not None:
                # SCAFFOLD drift correction: g - c_i + c
                grads = jax.tree.map(
                    lambda g, cg, cl: g + cg - cl,
                    grads,
                    state.c_global,
                    state.c_local,
                )
            if fed_opt == "Mime":
                # SVRG correction g(y) − g_batch(anchor) + ḡ_i, then the
                # FIXED server momentum s (state.c_global) — the momentum is
                # never updated locally, that is Mime's defining property
                (_, _), g_anchor = grad_fn(state.anchor, state, x, y, m)
                grads = jax.tree.map(
                    lambda g, ga, gf: g - ga + gf,
                    grads, g_anchor, mime_full_grad,
                )
                updates = jax.tree.map(
                    lambda g, s: -lr * ((1.0 - mime_beta) * g + mime_beta * s),
                    grads, state.c_global,
                )
            else:
                updates, opt_state = tx.update(grads, opt_state, params)
            # fully-padded steps (mask all zero) must be no-ops so clients with
            # fewer batches than the shared compiled shape stay exact
            valid = (jnp.sum(m) > 0).astype(jnp.float32)
            updates = jax.tree.map(lambda u: u * valid, updates)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), (loss, correct, denom, valid)

        (new_params, _), (losses, corrects, denoms, valids) = _scan(
            step, (params, opt_state), (xs, ys, mask), params
        )
        n_steps = xs.shape[0]
        tau = jnp.sum(valids)  # actual (non-padded) local optimizer steps

        if fed_opt == "FedNova":
            # normalized update (Wang et al. '20): upload the pseudo-model
            # x̂ = anchor − d_i where d_i = (anchor − x_τ)/τ; the server
            # rescales Σ p_i d_i by τ_eff = Σ p_i τ_i (ServerOptimizer)
            safe_tau = jnp.maximum(tau, 1.0)
            new_params = jax.tree.map(
                lambda a, p: a - (a - p) / safe_tau, state.anchor, new_params
            )

        new_state = state
        if fed_opt == "SCAFFOLD":
            # c_i+ = c_i - c + (anchor - new_params) / (K * lr)
            coef = 1.0 / (n_steps * lr)
            new_c_local = jax.tree.map(
                lambda cl, cg, a, p: cl - cg + coef * (a - p),
                state.c_local,
                state.c_global,
                state.anchor,
                new_params,
            )
            new_state = state._replace(c_local=new_c_local)
        elif fed_opt == "FedDyn":
            # h_i+ = h_i - alpha * (params+ - anchor)
            new_h = jax.tree.map(
                lambda h, p, a: h - feddyn_alpha * (p - a),
                state.h,
                new_params,
                state.anchor,
            )
            new_state = state._replace(h=new_h)

        metrics = {
            "train_loss": jnp.mean(losses),
            "train_correct": jnp.sum(corrects),
            "train_samples": jnp.sum(denoms),
            "local_steps": tau,
        }
        if mime_full_grad is not None:
            metrics["mime_full_grad"] = mime_full_grad
        return new_params, new_state, metrics

    return run_local


def init_local_state(params: Pytree, args: Any) -> LocalState:
    fed_opt = str(getattr(args, "federated_optimizer", "FedAvg"))
    zeros = tree_zeros_like(params)
    # SCAFFOLD: c_global/c_local are control variates; Mime: c_global holds
    # the SERVER momentum s (fixed during local steps — Mime's invariant)
    return LocalState(
        anchor=params,
        c_global=zeros if fed_opt in ("SCAFFOLD", "Mime") else None,
        c_local=zeros if fed_opt == "SCAFFOLD" else None,
        h=zeros if fed_opt == "FedDyn" else None,
    )


def build_evaluator(apply_fn: Callable) -> Callable:
    """Compiled full-batch evaluation: returns (loss_sum, correct, count).

    Cataloged as ``sp/evaluate`` (multi-shape: each test-set shape is a
    legitimate variant, not treedef churn)."""

    @jax.jit
    def evaluate(params, x, y):
        logits = apply_fn(params, x)
        if logits.ndim == 3:
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean(-1)
            pred_ok = jnp.mean(
                (jnp.argmax(logits, -1) == y).astype(jnp.float32), axis=-1
            )
        else:
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            pred_ok = (jnp.argmax(logits, -1) == y).astype(jnp.float32)
        return jnp.sum(ce), jnp.sum(pred_ok), jnp.asarray(y.shape[0], jnp.float32)

    from fedml_tpu.telemetry.profiling import wrap_jit

    return wrap_jit("sp/evaluate", evaluate, multi_shape=True)
