"""Default ClientTrainer for classification/seq tasks.

Parity: ``ml/trainer/my_model_trainer_classification.py`` (+ NWP variant) —
but the torch epoch loop is a single compiled XLA program built by
:mod:`fedml_tpu.ml.trainer.local_sgd`.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.alg_frame.client_trainer import ClientTrainer
from fedml_tpu.data.dataset import batch_epochs
from fedml_tpu.ml.trainer.local_sgd import (
    build_evaluator,
    build_local_trainer,
    init_local_state,
)

Pytree = Any


class ClassificationTrainer(ClientTrainer):
    def __init__(self, model, args):
        super().__init__(model, args)
        self.apply_fn = lambda params, x: model.apply(params, x)
        self._run_local = build_local_trainer(self.apply_fn, args)
        self._evaluate = build_evaluator(self.apply_fn)
        self._pad_to_batches: Optional[int] = None
        self._round_seed = 0
        self._data_sharding = None
        self._server_state: dict = {}

    def set_pad_to_batches(self, n: Optional[int]) -> None:
        """Share one compiled shape across heterogeneous clients."""
        self._pad_to_batches = n

    def set_round(self, round_idx: int) -> None:
        self._round_seed = round_idx

    def set_data_sharding(self, sharding) -> None:
        """Shard [steps, batch, ...] arrays over the silo's data axis; the
        jitted local step follows the input sharding, so XLA inserts the
        in-silo gradient all-reduce (the torch-DDP replacement)."""
        self._data_sharding = sharding

    def set_server_state(self, server_state: dict) -> None:
        self._server_state = dict(server_state or {})

    def train(
        self, params: Pytree, train_data: Tuple[np.ndarray, np.ndarray], device, args
    ) -> Tuple[Pytree, dict]:
        x, y = train_data
        state = init_local_state(params, args)
        # engine-pushed round state: SCAFFOLD's server control variate,
        # Mime's server momentum (both ride the c_global slot)
        if self._server_state.get("c_global") is not None:
            state = state._replace(c_global=self._server_state["c_global"])
        xs, ys, mask = batch_epochs(
            np.asarray(x),
            np.asarray(y),
            int(getattr(args, "batch_size", 32)),
            int(getattr(args, "epochs", 1)),
            seed=int(getattr(args, "random_seed", 0)) * 100003
            + self.id * 1009
            + self._round_seed,
            pad_to_batches=self._pad_to_batches,
        )
        xs, ys, mask = jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(mask)
        if self._data_sharding is not None:
            import jax as _jax

            xs, ys, mask = (
                _jax.device_put(a, self._data_sharding) for a in (xs, ys, mask)
            )
        new_params, new_state, metrics = self._run_local(
            params, state, xs, ys, mask
        )
        metrics = {
            k: (float(v) if getattr(v, "ndim", 1) == 0 else v)
            for k, v in metrics.items()
        }
        metrics["scaffold_c_delta"] = None
        if new_state.c_local is not None:
            import jax

            metrics["scaffold_c_delta"] = jax.tree.map(
                lambda a, b: a - b, new_state.c_local, state.c_local
            )
        return new_params, metrics

    def test(self, params: Pytree, test_data, device, args) -> dict:
        x, y = test_data
        loss_sum, correct, n = self._evaluate(
            params, jnp.asarray(np.asarray(x)), jnp.asarray(np.asarray(y))
        )
        n = float(n)
        return {
            "test_loss": float(loss_sum) / max(n, 1.0),
            "test_acc": float(correct) / max(n, 1.0),
            "test_total": n,
            "test_correct": float(correct),
        }
