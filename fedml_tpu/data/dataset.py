"""In-memory federated dataset containers, built for XLA.

Design: a client's data is a pair of numpy arrays ``(x, y)``; batching for
the jitted train loop produces a fixed-shape [num_batches, batch, ...] array
(pad+mask) so local epochs run under ``lax.scan`` with static shapes — the
TPU-native replacement for the reference's torch DataLoader iteration
(``ml/trainer/my_model_trainer_classification.py``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np


@dataclass
class FederatedDataset:
    """The 8-tuple the reference's ``fedml.data.load`` returns, as a struct.

    Reference shape (``data/data_loader.py:234``):
    (train_data_num, test_data_num, train_data_global, test_data_global,
     train_data_local_num_dict, train_data_local_dict, test_data_local_dict,
     class_num)
    """

    train_data_num: int
    test_data_num: int
    train_data_global: Tuple[np.ndarray, np.ndarray]
    test_data_global: Tuple[np.ndarray, np.ndarray]
    train_data_local_num_dict: Dict[int, int]
    train_data_local_dict: Dict[int, Tuple[np.ndarray, np.ndarray]]
    test_data_local_dict: Dict[int, Tuple[np.ndarray, np.ndarray]]
    class_num: int
    feature_dim: Optional[int] = None
    stats: dict = field(default_factory=dict)

    def as_tuple(self):
        return (
            self.train_data_num,
            self.test_data_num,
            self.train_data_global,
            self.test_data_global,
            self.train_data_local_num_dict,
            self.train_data_local_dict,
            self.test_data_local_dict,
            self.class_num,
        )


def batch_epochs(
    x: np.ndarray,
    y: np.ndarray,
    batch_size: int,
    epochs: int,
    seed: int = 0,
    pad_to_batches: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack (x, y) into [steps, batch_size, ...] with a validity mask.

    Shuffles per epoch, pads the tail batch, and optionally pads the step
    dimension to ``pad_to_batches`` per epoch so heterogeneous clients share
    one compiled shape (SURVEY §7 hard part (b): mask-and-pad over SPMD).
    """
    n = x.shape[0]
    rng = np.random.default_rng(seed)
    per_epoch = max(1, int(np.ceil(n / batch_size)))
    steps_per_epoch = pad_to_batches or per_epoch
    if n == 0:
        # empty client (tiny datasets / unlucky partition): fully padded,
        # mask 0 everywhere → training step is a masked no-op
        shape = (steps_per_epoch * epochs, batch_size)
        return (
            np.zeros((*shape, *x.shape[1:]), dtype=x.dtype),
            np.zeros((*shape, *y.shape[1:]), dtype=y.dtype),
            np.zeros(shape, dtype=np.float32),
        )
    xs, ys, ms = [], [], []
    for _ in range(epochs):
        order = rng.permutation(n)
        padded = steps_per_epoch * batch_size
        reps = int(np.ceil(padded / max(n, 1)))
        idx = np.concatenate([order] * reps)[:padded]
        mask = np.zeros(padded, dtype=np.float32)
        mask[: min(n, padded)] = 1.0
        xs.append(x[idx].reshape(steps_per_epoch, batch_size, *x.shape[1:]))
        ys.append(y[idx].reshape(steps_per_epoch, batch_size, *y.shape[1:]))
        ms.append(mask.reshape(steps_per_epoch, batch_size))
    return (
        np.concatenate(xs, axis=0),
        np.concatenate(ys, axis=0),
        np.concatenate(ms, axis=0),
    )


def assemble_slots(
    id_matrix: np.ndarray,
    arrays_by_cid: Dict[int, Sequence[np.ndarray]],
) -> Tuple[np.ndarray, ...]:
    """Gather per-client staged arrays into ``[n_dev, slots, ...]`` blocks.

    ``id_matrix`` is the scheduler's ``[n_dev, slots]`` client-id matrix
    (padded with -1); ``arrays_by_cid[cid]`` is the tuple of same-shaped
    per-client tensors (e.g. ``(x, y, mask)`` from :func:`batch_epochs`).
    One ``np.stack`` gather per tensor replaces the per-slot Python copy
    loop — the stack writes each [steps, B, ...] block with one memcpy
    instead of slots × n_dev strided assignments, and padded slots share
    one zero template instead of re-zeroing per slot.
    """
    n_dev, slots = id_matrix.shape
    flat = [int(c) for c in id_matrix.reshape(-1)]
    template = next(iter(arrays_by_cid.values()))
    pads = tuple(np.zeros_like(a) for a in template)
    out = []
    for t, pad in enumerate(pads):
        col = np.stack(
            [arrays_by_cid[c][t] if c >= 0 else pad for c in flat]
        )
        out.append(col.reshape(n_dev, slots, *pad.shape))
    return tuple(out)
