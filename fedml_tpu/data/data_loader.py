"""``fedml_tpu.data.load`` — federated dataset factory.

Parity: ``data/data_loader.py:234`` in the reference, which dispatches on
``args.dataset`` to per-dataset loaders and returns the canonical 8-tuple.
Here each loader returns a :class:`FederatedDataset`.

Offline discipline: this environment has zero network egress, so every
loader first looks for real data files under ``args.data_cache_dir`` (the
standard formats: ``mnist.npz`` keras layout, CIFAR pickle batches, LEAF
json for femnist/shakespeare) and otherwise generates a *deterministic,
learnable* synthetic stand-in with identical shapes/classes, so every
pipeline remains runnable and convergence-testable anywhere.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from fedml_tpu.core.data.noniid_partition import (
    homo_partition,
    non_iid_partition_with_dirichlet_distribution,
    record_data_stats,
)
from fedml_tpu.data.dataset import FederatedDataset

_LOADERS: Dict[str, Callable] = {}


def register_dataset(*names: str):
    def deco(fn):
        for n in names:
            _LOADERS[n] = fn
        return fn

    return deco


def load(args: Any) -> Tuple:
    """Reference-compatible entry: returns the 8-tuple (dataset, class_num)."""
    ds = load_federated(args)
    return ds.as_tuple(), ds.class_num


def load_federated(args: Any) -> FederatedDataset:
    name = str(getattr(args, "dataset", "synthetic")).lower()
    if name not in _LOADERS:
        _synthetic_fallback(
            name,
            f"unknown dataset name {name!r} (registered: {sorted(_LOADERS)})",
            advice="fix the `dataset:` config value",
        )
        name = "synthetic"
    return _LOADERS[name](args)


def _synthetic_fallback(name: str, reason: str,
                        advice: str = "place the real files under "
                        "args.data_cache_dir") -> None:
    """Loudly record that a run is about to train on synthetic stand-in data.

    Silent substitution would make accuracy-parity claims meaningless and
    let a typo'd ``dataset:`` train on fake data unnoticed — so this both
    warns at WARNING level and writes the substitution into the metrics
    sink, where it sits next to the run's accuracy numbers.
    """
    import logging

    msg = (f"dataset {name!r}: SYNTHETIC STAND-IN in use — {reason}. "
           f"Accuracy is NOT comparable to the real dataset; {advice} "
           "to silence this.")
    logging.getLogger(__name__).warning(msg)
    from fedml_tpu.core.mlops import metrics as mlops

    mlops.log({"synthetic_data_fallback": name, "reason": reason})


# --------------------------------------------------------------------------
# synthetic class-structured generator (shared machinery)
# --------------------------------------------------------------------------

def _make_classification_arrays(
    n_train: int,
    n_test: int,
    feature_shape: Tuple[int, ...],
    class_num: int,
    seed: int,
    noise: float = 0.35,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Gaussian class clusters in feature space — linearly separable enough
    to show real convergence curves, hard enough to be non-trivial."""
    rng = np.random.default_rng(seed)
    dim = int(np.prod(feature_shape))
    centers = rng.normal(0.0, 1.0, size=(class_num, dim)).astype(np.float32)

    def gen(n):
        y = rng.integers(0, class_num, size=n)
        x = centers[y] + noise * rng.normal(size=(n, dim)).astype(np.float32)
        return x.reshape((n, *feature_shape)).astype(np.float32), y.astype(np.int32)

    xtr, ytr = gen(n_train)
    xte, yte = gen(n_test)
    return xtr, ytr, xte, yte


def _partition_and_pack(
    args: Any,
    xtr: np.ndarray,
    ytr: np.ndarray,
    xte: np.ndarray,
    yte: np.ndarray,
    class_num: int,
) -> FederatedDataset:
    client_num = int(getattr(args, "client_num_in_total", 4))
    method = str(getattr(args, "partition_method", "hetero")).lower()
    alpha = float(getattr(args, "partition_alpha", 0.5))
    seed = int(getattr(args, "random_seed", 0))
    if method in ("hetero", "dirichlet", "noniid"):
        train_map = non_iid_partition_with_dirichlet_distribution(
            ytr, client_num, class_num, alpha, seed=seed
        )
    else:
        train_map = homo_partition(len(ytr), client_num, seed=seed)
    test_map = homo_partition(len(yte), client_num, seed=seed + 1)

    train_local = {i: (xtr[idx], ytr[idx]) for i, idx in train_map.items()}
    test_local = {i: (xte[idx], yte[idx]) for i, idx in test_map.items()}
    return FederatedDataset(
        train_data_num=len(ytr),
        test_data_num=len(yte),
        train_data_global=(xtr, ytr),
        test_data_global=(xte, yte),
        train_data_local_num_dict={i: len(idx) for i, idx in train_map.items()},
        train_data_local_dict=train_local,
        test_data_local_dict=test_local,
        class_num=class_num,
        feature_dim=int(np.prod(xtr.shape[1:])),
        stats=record_data_stats(ytr, train_map),
    )


# --------------------------------------------------------------------------
# datasets
# --------------------------------------------------------------------------

@register_dataset("synthetic", "synthetic_1_1")
def load_synthetic(args: Any) -> FederatedDataset:
    class_num = int(getattr(args, "class_num", 10))
    dim = int(getattr(args, "feature_dim", 60))
    n_train = int(getattr(args, "train_size", 2000))
    n_test = int(getattr(args, "test_size", 500))
    seed = int(getattr(args, "random_seed", 0))
    xtr, ytr, xte, yte = _make_classification_arrays(
        n_train, n_test, (dim,), class_num, seed
    )
    return _partition_and_pack(args, xtr, ytr, xte, yte, class_num)


@register_dataset("synthetic_image")
def load_synthetic_image(args: Any) -> FederatedDataset:
    """Class-clustered synthetic images at a configurable size — the
    CPU-friendly stand-in for CV-model tests (image_size=8 keeps conv
    stacks fast where a 28x28 input buys nothing)."""
    class_num = int(getattr(args, "class_num", 10))
    size = int(getattr(args, "image_size", 8))
    channels = int(getattr(args, "image_channels", 1))
    n_train = int(getattr(args, "train_size", 256))
    n_test = int(getattr(args, "test_size", 64))
    seed = int(getattr(args, "random_seed", 0))
    xtr, ytr, xte, yte = _make_classification_arrays(
        n_train, n_test, (size, size, channels), class_num, seed
    )
    return _partition_and_pack(args, xtr, ytr, xte, yte, class_num)


@register_dataset("mnist")
def load_mnist(args: Any) -> FederatedDataset:
    """MNIST: real ``mnist.npz`` if cached locally, else synthetic 28×28."""
    cache = str(getattr(args, "data_cache_dir", "") or "")
    path = os.path.join(cache, "mnist.npz") if cache else ""
    idx_files = [os.path.join(cache, f) for f in (
        "train-images-idx3-ubyte", "train-labels-idx1-ubyte",
        "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")] if cache else []
    if path and os.path.exists(path):
        with np.load(path) as d:
            xtr = (d["x_train"].astype(np.float32) / 255.0).reshape(-1, 784)
            ytr = d["y_train"].astype(np.int32)
            xte = (d["x_test"].astype(np.float32) / 255.0).reshape(-1, 784)
            yte = d["y_test"].astype(np.int32)
    elif idx_files and all(os.path.exists(f) for f in idx_files):
        # the raw download format (yann.lecun.com idx files) — parsed by
        # the native reader (C++ kernel or bit-identical numpy twin).
        # ALL four files must be present: a partial cache (interrupted
        # download) takes the documented synthetic fallback instead of
        # crashing on the missing sibling.
        from fedml_tpu.data.native_reader import read_mnist

        xtr, ytr = read_mnist(idx_files[0], idx_files[1])
        xte, yte = read_mnist(idx_files[2], idx_files[3])
    else:
        _synthetic_fallback("mnist", f"no mnist.npz under {cache!r}")
        xtr, ytr, xte, yte = _make_classification_arrays(
            int(getattr(args, "train_size", 6000)),
            int(getattr(args, "test_size", 1000)),
            (784,),
            10,
            int(getattr(args, "random_seed", 0)) + 1,
        )
    return _partition_and_pack(args, xtr, ytr, xte, yte, 10)


# -- LEAF json (femnist/shakespeare natural per-user partitions) -----------

LEAF_CHARSET = (
    "\n !\"&'(),-.0123456789:;>?ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    "[]abcdefghijklmnopqrstuvwxyz}" + "".join(chr(c) for c in range(1, 12))
)  # 90 symbols, matching the shakespeare vocab


def leaf_encode(text: str, vocab: int = 90) -> np.ndarray:
    table = {ch: i for i, ch in enumerate(LEAF_CHARSET[:vocab])}
    return np.asarray([table.get(ch, 0) for ch in text], np.int32)


def _load_leaf_json(cache: str, name: str):
    """Read LEAF's ``{name}_train.json`` / ``{name}_test.json``:
    {"users": [...], "user_data": {user: {"x": [...], "y": [...]}}}.
    Returns (train_user_data, test_user_data) or None."""
    import json as _json

    out = []
    for split in ("train", "test"):
        path = os.path.join(cache, f"{name}_{split}.json") if cache else ""
        if not path or not os.path.exists(path):
            return None
        with open(path) as f:
            payload = _json.load(f)
        out.append({u: payload["user_data"][u] for u in payload["users"]})
    return out


def _pack_leaf_users(args, train_users, test_users, to_arrays, class_num,
                     feature_dim):
    """LEAF's point is the NATURAL partition: clients = users (grouped
    round-robin onto client_num buckets when there are more users)."""
    users = sorted(train_users)
    client_num = int(getattr(args, "client_num_in_total", len(users)))
    if client_num > len(users):
        # more clients than LEAF users cannot be satisfied — an empty
        # client would crash concatenation and train on nothing anyway
        import logging

        logging.getLogger(__name__).warning(
            "LEAF partition: %d clients requested but only %d users; "
            "using %d clients", client_num, len(users), len(users))
        client_num = len(users)
    buckets = {i: [] for i in range(client_num)}
    for j, u in enumerate(users):
        buckets[j % client_num].append(u)

    def cat(users_list, table):
        xs, ys = [], []
        for u in users_list:
            x, y = to_arrays(table[u])
            xs.append(x)
            ys.append(y)
        return (np.concatenate(xs), np.concatenate(ys)) if xs else \
            (np.zeros((0, feature_dim), np.float32), np.zeros(0, np.int32))

    train_local = {i: cat(buckets[i], train_users) for i in buckets}
    test_all_users = sorted(test_users)
    xte, yte = cat(test_all_users, test_users)
    xtr = np.concatenate([train_local[i][0] for i in buckets])
    ytr = np.concatenate([train_local[i][1] for i in buckets])
    test_local = {i: (xte, yte) for i in buckets}
    return FederatedDataset(
        train_data_num=len(ytr),
        test_data_num=len(yte),
        train_data_global=(xtr, ytr),
        test_data_global=(xte, yte),
        train_data_local_num_dict={i: len(train_local[i][1]) for i in buckets},
        train_data_local_dict=train_local,
        test_data_local_dict=test_local,
        class_num=class_num,
        feature_dim=feature_dim,
        stats={"leaf_users": len(users)},
    )


@register_dataset("femnist")
def load_femnist(args: Any) -> FederatedDataset:
    """FEMNIST: LEAF json (natural writer partition) if cached, else npz,
    else synthetic."""
    cache = str(getattr(args, "data_cache_dir", "") or "")
    leaf = _load_leaf_json(cache, "femnist")
    if leaf is not None:
        def to_arrays(ud):
            x = np.asarray(ud["x"], np.float32).reshape(-1, 28, 28, 1)
            return x, np.asarray(ud["y"], np.int32)

        return _pack_leaf_users(args, leaf[0], leaf[1], to_arrays, 62, 784)
    xtr, ytr, xte, yte = _load_image_or_synthetic(args, (28, 28, 1), 62, "femnist")
    return _partition_and_pack(args, xtr, ytr, xte, yte, 62)


@register_dataset("cifar10", "cinic10")
def load_cifar10(args: Any) -> FederatedDataset:
    xtr, ytr, xte, yte = _load_image_or_synthetic(args, (32, 32, 3), 10, "cifar10")
    return _partition_and_pack(args, xtr, ytr, xte, yte, 10)


@register_dataset("cifar100", "fed_cifar100")
def load_cifar100(args: Any) -> FederatedDataset:
    xtr, ytr, xte, yte = _load_image_or_synthetic(args, (32, 32, 3), 100, "cifar100")
    return _partition_and_pack(args, xtr, ytr, xte, yte, 100)


def _load_image_or_synthetic(args, shape, classes, name):
    cache = str(getattr(args, "data_cache_dir", "") or "")
    path = os.path.join(cache, f"{name}.npz") if cache else ""
    if path and os.path.exists(path):
        with np.load(path) as d:
            return (
                d["x_train"].astype(np.float32) / 255.0,
                d["y_train"].astype(np.int32).ravel(),
                d["x_test"].astype(np.float32) / 255.0,
                d["y_test"].astype(np.int32).ravel(),
            )
    bin1 = os.path.join(cache, "data_batch_1.bin") if cache else ""
    if name == "cifar10" and bin1 and os.path.exists(bin1):
        # the raw cifar-10-binary download layout — native reader (C++
        # kernel or bit-identical numpy twin), CHW records → HWC floats
        from fedml_tpu.data.native_reader import read_cifar10_batches

        train_bins = [os.path.join(cache, f"data_batch_{i}.bin")
                      for i in range(1, 6)]
        xtr, ytr = read_cifar10_batches(
            [p for p in train_bins if os.path.exists(p)])
        test_bin = os.path.join(cache, "test_batch.bin")
        if os.path.exists(test_bin):
            xte, yte = read_cifar10_batches([test_bin])
        else:  # no test batch shipped: hold out the tail of train
            k = max(1, len(ytr) // 10)
            xte, yte = xtr[-k:], ytr[-k:]
            xtr, ytr = xtr[:-k], ytr[:-k]
        return xtr, ytr, xte, yte
    _synthetic_fallback(name, f"no {name}.npz under {cache!r}")
    return _make_classification_arrays(
        int(getattr(args, "train_size", 4000)),
        int(getattr(args, "test_size", 800)),
        shape,
        classes,
        int(getattr(args, "random_seed", 0)) + hash(name) % 1000,
    )


@register_dataset("shakespeare", "fed_shakespeare")
def load_shakespeare(args: Any) -> FederatedDataset:
    """Next-character prediction; LEAF-format json if cached, else synthetic
    character streams with n-gram structure (so an LSTM can actually learn)."""
    seq_len = int(getattr(args, "seq_len", 80))
    vocab = 90  # LEAF shakespeare charset size
    cache = str(getattr(args, "data_cache_dir", "") or "")
    # LEAF json (natural speaker partition): x = seq_len-char strings,
    # y = the next character
    leaf = _load_leaf_json(cache, "shakespeare")
    if leaf is not None:
        def to_arrays(ud):
            xs = np.stack([
                np.pad(leaf_encode(s, vocab)[:seq_len],
                       (0, max(0, seq_len - len(s))))
                for s in ud["x"]
            ])
            # next-char target broadcast over the sequence positions:
            # shifted input + final next-char (LEAF's y)
            ys = np.concatenate(
                [xs[:, 1:], np.stack([leaf_encode(c, vocab)[:1]
                                      for c in ud["y"]])], axis=1)
            return xs.astype(np.int32), ys.astype(np.int32)

        ds = _pack_leaf_users(args, leaf[0], leaf[1], to_arrays, vocab,
                              seq_len)
        return ds
    corpus = None
    if cache:
        for fname in ("shakespeare.txt", "all_data.txt"):
            p = os.path.join(cache, fname)
            if os.path.exists(p):
                with open(p, "rb") as f:
                    corpus = np.frombuffer(f.read(), dtype=np.uint8) % vocab
                break
    if corpus is None:
        _synthetic_fallback(
            str(getattr(args, "dataset", "shakespeare")),
            f"no shakespeare.txt/all_data.txt under {cache!r}")
        rng = np.random.default_rng(int(getattr(args, "random_seed", 0)) + 5)
        # order-1 markov chain over the charset → learnable structure
        trans = rng.dirichlet(np.full(vocab, 0.05), size=vocab)
        n = int(getattr(args, "train_size", 200_000))
        corpus = np.empty(n, dtype=np.int64)
        corpus[0] = 0
        # vectorized markov sampling via inverse-cdf on per-state uniforms
        cdf = np.cumsum(trans, axis=1)
        u = rng.random(n)
        for i in range(1, n):
            corpus[i] = np.searchsorted(cdf[corpus[i - 1]], u[i])
    n_seq = len(corpus) // (seq_len + 1)
    chunks = corpus[: n_seq * (seq_len + 1)].reshape(n_seq, seq_len + 1)
    x, y = chunks[:, :-1].astype(np.int32), chunks[:, 1:].astype(np.int32)
    n_test = max(1, n_seq // 10)
    xtr, ytr, xte, yte = x[:-n_test], y[:-n_test], x[-n_test:], y[-n_test:]
    # partition by contiguous ranges (clients = "speakers")
    client_num = int(getattr(args, "client_num_in_total", 4))
    train_local = {}
    if len(xtr) >= client_num:
        # near-contiguous split; linspace bounds differ by >=1 everywhere
        # when len(xtr) >= client_num, so no client is empty
        bounds = np.linspace(0, len(xtr), client_num + 1).astype(int)
        for i in range(client_num):
            sl = slice(bounds[i], bounds[i + 1])
            train_local[i] = (xtr[sl], ytr[sl])
    else:
        # tiny corpus: stride with wraparound so every client still holds
        # >=1 sequence (duplication is fine for the synthetic path)
        for i in range(client_num):
            idx = np.arange(i, i + 1) % len(xtr)
            train_local[i] = (xtr[idx], ytr[idx])
    test_local = {i: (xte, yte) for i in range(client_num)}
    return FederatedDataset(
        train_data_num=len(xtr),
        test_data_num=len(xte),
        train_data_global=(xtr, ytr),
        test_data_global=(xte, yte),
        train_data_local_num_dict={i: len(train_local[i][0]) for i in train_local},
        train_data_local_dict=train_local,
        test_data_local_dict=test_local,
        class_num=vocab,
        feature_dim=seq_len,
    )


@register_dataset("stackoverflow_lr")
def load_stackoverflow_lr(args: Any) -> FederatedDataset:
    # bag-of-words tag prediction: 10k vocab → 500 tags in the reference
    class_num = int(getattr(args, "class_num", 500))
    dim = int(getattr(args, "feature_dim", 10000))
    setattr(args, "class_num", class_num)
    setattr(args, "feature_dim", dim)
    return load_synthetic(args)


@register_dataset("stackoverflow_nwp", "reddit")
def load_stackoverflow_nwp(args: Any) -> FederatedDataset:
    setattr(args, "seq_len", int(getattr(args, "seq_len", 20)))
    return load_shakespeare(args)


@register_dataset("synthetic_lm", "fedllm", "databricks-dolly")
def load_synthetic_lm(args: Any) -> FederatedDataset:
    """Causal-LM token streams for the LLM path.

    Parity: the reference's LLM path fine-tunes on instruction datasets
    (``train/llm/configurations.py:326`` DatasetArguments). With zero
    egress, we synthesize an order-1 Markov token stream with a banded
    transition matrix — enough structure that per-round eval loss falls
    measurably, which is what the FedLLM CI asserts.

    Samples are (x, y) = (tokens[:-1], tokens[1:]) of shape [T].
    """
    seq_len = int(getattr(args, "max_seq_length", getattr(args, "seq_len", 128)))
    vocab = int(getattr(args, "vocab_size", 256))
    n_train = int(getattr(args, "train_size", 512))
    n_test = int(getattr(args, "test_size", 64))
    seed = int(getattr(args, "random_seed", 0))
    rng = np.random.default_rng(seed + 77)

    # banded Markov transitions: token t mostly moves to t+1 or t+2 (mod V)
    def gen(n):
        toks = np.zeros((n, seq_len + 1), np.int32)
        toks[:, 0] = rng.integers(0, vocab, size=n)
        step = rng.choice([1, 2], p=[0.8, 0.2], size=(n, seq_len))
        noise = rng.random((n, seq_len)) < 0.05
        rand_tok = rng.integers(0, vocab, size=(n, seq_len))
        for t in range(seq_len):
            nxt = (toks[:, t] + step[:, t]) % vocab
            toks[:, t + 1] = np.where(noise[:, t], rand_tok[:, t], nxt)
        return toks[:, :-1], toks[:, 1:]

    xtr, ytr = gen(n_train)
    xte, yte = gen(n_test)

    client_num = int(getattr(args, "client_num_in_total", 4))
    bounds = np.linspace(0, n_train, client_num + 1).astype(int)
    train_local = {
        i: (xtr[bounds[i]: bounds[i + 1]], ytr[bounds[i]: bounds[i + 1]])
        for i in range(client_num)
    }
    test_local = {i: (xte, yte) for i in range(client_num)}
    return FederatedDataset(
        train_data_num=n_train,
        test_data_num=n_test,
        train_data_global=(xtr, ytr),
        test_data_global=(xte, yte),
        train_data_local_num_dict={i: len(train_local[i][0]) for i in train_local},
        train_data_local_dict=train_local,
        test_data_local_dict=test_local,
        class_num=vocab,
        feature_dim=seq_len,
    )


# --------------------------------------------------------------------------
# large-vision / NLP / tabular / VFL federated datasets (round-2 additions)
# --------------------------------------------------------------------------

# Same extension set the reference's ImageFolder walk accepts
# (``data/ImageNet/datasets.py:137``).
_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif")


def _decode_image(path: str, size: int) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB")
        if im.size != (size, size):
            im = im.resize((size, size))
        return np.asarray(im, np.float32) / 255.0


def _read_image_folder(split_dir: str, size: int, class_to_idx,
                       max_images: int = 0, seed: int = 0):
    """Read one split of the torchvision-style ImageFolder layout the
    reference's loader walks (``data/ImageNet/datasets.py:83-174``):
    ``split_dir/<class_name>/<image>.<ext>``. Returns (x, y).

    The file list is enumerated FIRST and (when ``max_images`` caps it)
    subsampled before any decode: real ImageNet is 1.28M images — eager
    full-tree decoding would need ~60 GB and hours, so large trees must
    be capped via args.train_size/test_size (a loud log says when).
    """
    entries = []
    for cls in sorted(os.listdir(split_dir)):
        cdir = os.path.join(split_dir, cls)
        if not os.path.isdir(cdir) or cls not in class_to_idx:
            continue
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(_IMG_EXTENSIONS):
                entries.append((os.path.join(cdir, fname),
                                class_to_idx[cls]))
    if max_images and len(entries) > max_images:
        import logging

        logging.getLogger(__name__).warning(
            "image folder %s: subsampling %d of %d images "
            "(args.train_size/test_size cap)",
            split_dir, max_images, len(entries))
        rng = np.random.default_rng(seed)
        keep = rng.choice(len(entries), size=max_images, replace=False)
        entries = [entries[i] for i in sorted(keep)]
    if not entries:
        return (np.zeros((0, size, size, 3), np.float32),
                np.zeros(0, np.int32))
    xs = np.stack([_decode_image(p, size) for p, _ in entries])
    ys = np.asarray([label for _, label in entries], np.int32)
    return xs, ys


def _find_image_folder_root(cache: str, names) -> Optional[str]:
    """First candidate dir containing a ``train/`` of class subdirs —
    the cache dir itself or ``cache/<Name>/``."""
    if not cache:
        return None
    for base in (cache, *(os.path.join(cache, n) for n in names)):
        train = os.path.join(base, "train")
        if os.path.isdir(train) and any(
                os.path.isdir(os.path.join(train, d))
                for d in os.listdir(train)):
            return base
    return None


@register_dataset("imagenet", "imagenet100")
def load_imagenet(args: Any) -> FederatedDataset:
    """ImageNet-shaped federated loader (ref ``data/ImageNet``).

    Real branch reads the reference's on-disk layout — the ImageFolder
    directory tree ``<root>/{train,val}/<class_name>/*.JPEG``
    (``data/ImageNet/datasets.py:83-174``) under ``data_cache_dir`` (or
    ``data_cache_dir/ImageNet``) — with classes indexed by sorted
    directory name, exactly like ``find_classes``. A repo-local
    ``imagenet.npz`` is still accepted; otherwise a loud synthetic
    stand-in keeps offline runs alive.
    """
    size = int(getattr(args, "image_size", 64) or 64)
    root = _find_image_folder_root(
        str(getattr(args, "data_cache_dir", "") or ""),
        ("ImageNet", "imagenet"))
    if root is not None:
        train_dir = os.path.join(root, "train")
        classes = sorted(
            d for d in os.listdir(train_dir)
            if os.path.isdir(os.path.join(train_dir, d)))
        class_to_idx = {c: i for i, c in enumerate(classes)}
        seed = int(getattr(args, "random_seed", 0))
        cap_tr = int(getattr(args, "train_size", 0) or 0)
        cap_te = int(getattr(args, "test_size", 0) or 0)
        xtr, ytr = _read_image_folder(train_dir, size, class_to_idx,
                                      max_images=cap_tr, seed=seed)
        val_dir = os.path.join(root, "val")
        if os.path.isdir(val_dir):
            xte, yte = _read_image_folder(val_dir, size, class_to_idx,
                                          max_images=cap_te, seed=seed + 1)
        else:  # train-only trees: hold OUT every 10th image (not a copy —
            # evaluating on trained-on images would inflate accuracy)
            hold = np.zeros(len(ytr), bool)
            hold[::10] = True
            xte, yte = xtr[hold], ytr[hold]
            xtr, ytr = xtr[~hold], ytr[~hold]
        return _partition_and_pack(args, xtr, ytr, xte, yte, len(classes))
    classes = int(getattr(args, "class_num", 100) or 100)
    xtr, ytr, xte, yte = _load_image_or_synthetic(
        args, (size, size, 3), classes, "imagenet"
    )
    return _partition_and_pack(args, xtr, ytr, xte, yte, classes)


def _find_landmarks_csvs(cache: str) -> Optional[tuple]:
    """Locate the federated Landmarks mapping csvs + image dir under the
    cache. Accepts the reference's file names (``mini_gld_train_split.csv``
    / ``mini_gld_test.csv``, ``data/Landmarks/data_loader.py:281``) or
    plain ``train.csv`` / ``test.csv``; images live next to the csvs or
    under ``images/``."""
    if not cache:
        return None
    for base in (cache, os.path.join(cache, "Landmarks"),
                 os.path.join(cache, "landmarks")):
        for tr_name, te_name in (
                ("mini_gld_train_split.csv", "mini_gld_test.csv"),
                ("federated_train.csv", "test.csv"),
                ("train.csv", "test.csv")):
            tr, te = os.path.join(base, tr_name), os.path.join(base, te_name)
            if os.path.exists(tr) and os.path.exists(te):
                img_dir = os.path.join(base, "images")
                return tr, te, (img_dir if os.path.isdir(img_dir) else base)
    return None


def _read_landmarks_csv(path: str):
    """Rows of the reference's mapping schema: user_id, image_id, class
    (``data/Landmarks/data_loader.py:123-156``)."""
    import csv as _csv

    with open(path, newline="") as f:
        rows = list(_csv.DictReader(f))
    required = {"user_id", "image_id", "class"}
    if rows and not required <= set(rows[0]):
        raise ValueError(
            f"{path}: landmarks mapping csv must have columns "
            f"user_id,image_id,class; got {sorted(rows[0])}")
    return rows


@register_dataset("gld23k", "gld160k", "landmarks")
def load_landmarks(args: Any) -> FederatedDataset:
    """Google Landmarks federated split (ref ``data/Landmarks``).

    Real branch reads the reference's on-disk layout: mapping csvs with
    ``user_id,image_id,class`` columns and ``<image_id>.jpg`` files
    (``data/Landmarks/{data_loader,datasets}.py``). The train partition
    is the csv's NATURAL per-user split — Landmarks is a federated-by-
    construction dataset, so users map to clients (round-robin grouped
    when client_num < users), not Dirichlet. Falls back to npz, then to
    a loud synthetic stand-in.
    """
    size = int(getattr(args, "image_size", 64) or 64)
    found = _find_landmarks_csvs(str(getattr(args, "data_cache_dir", "") or ""))
    if found is not None:
        tr_csv, te_csv, img_dir = found
        train_rows = _read_landmarks_csv(tr_csv)
        test_rows = _read_landmarks_csv(te_csv)
        classes = sorted({r["class"] for r in train_rows})
        cls_idx = {c: i for i, c in enumerate(classes)}
        unseen = [r for r in test_rows if r["class"] not in cls_idx]
        if unseen:
            # mapping them to an arbitrary index would silently corrupt
            # evaluation labels; drop with a warning instead
            import logging

            logging.getLogger(__name__).warning(
                "landmarks: dropping %d test rows whose class never "
                "appears in the train split (e.g. %r)",
                len(unseen), unseen[0]["class"])
            test_rows = [r for r in test_rows if r["class"] in cls_idx]

        def img(row):
            return _decode_image(
                os.path.join(img_dir, f"{row['image_id']}.jpg"), size)

        train_users = {}
        for r in train_rows:
            train_users.setdefault(str(r["user_id"]), []).append(r)

        def to_arrays(rows):
            if not rows:
                return (np.zeros((0, size, size, 3), np.float32),
                        np.zeros(0, np.int32))
            return (np.stack([img(r) for r in rows]),
                    np.asarray([cls_idx.get(r["class"], 0) for r in rows],
                               np.int32))

        return _pack_leaf_users(args, train_users, {"all": test_rows},
                                to_arrays, len(classes), size * size * 3)
    classes = int(getattr(args, "class_num", 203) or 203)
    xtr, ytr, xte, yte = _load_image_or_synthetic(
        args, (size, size, 3), classes, "landmarks"
    )
    return _partition_and_pack(args, xtr, ytr, xte, yte, classes)


@register_dataset("agnews", "fednlp_text_classification", "20news", "sst_2", "sentiment140")
def load_fednlp_text(args: Any) -> FederatedDataset:
    """FedNLP text-classification suite (ref ``data/fednlp``): token-id
    sequences → class. Real npz {x_train [N,T] int32, y_train, ...} from the
    cache dir, else synthetic keyword-structured sequences an RNN/transformer
    can genuinely fit."""
    name = str(getattr(args, "dataset", "agnews")).lower()
    seq_len = int(getattr(args, "seq_len", 32))
    vocab = int(getattr(args, "vocab_size", 512) or 512)
    classes = int(getattr(args, "class_num", 4) or 4)
    cache = str(getattr(args, "data_cache_dir", "") or "")
    path = os.path.join(cache, f"{name}.npz") if cache else ""
    if path and os.path.exists(path):
        with np.load(path) as d:
            xtr, ytr = d["x_train"].astype(np.int32), d["y_train"].astype(np.int32).ravel()
            xte, yte = d["x_test"].astype(np.int32), d["y_test"].astype(np.int32).ravel()
    else:
        _synthetic_fallback(name, f"no {name}.npz under {cache!r}")
        rng = np.random.default_rng(int(getattr(args, "random_seed", 0)) + 7)
        n_train = int(getattr(args, "train_size", 2000))
        n_test = int(getattr(args, "test_size", 400))
        # each class owns a keyword block; documents mix class keywords with
        # common words — a learnable bag-of-words signal
        kw_per_class = max(4, vocab // (4 * classes))

        def gen(n):
            y = rng.integers(0, classes, size=n).astype(np.int32)
            base = rng.integers(0, vocab, size=(n, seq_len))
            kw = (y[:, None] * kw_per_class
                  + rng.integers(0, kw_per_class, size=(n, seq_len)))
            use_kw = rng.random((n, seq_len)) < 0.35
            return np.where(use_kw, kw % vocab, base).astype(np.int32), y

        xtr, ytr = gen(n_train)
        xte, yte = gen(n_test)
    ds = _partition_and_pack(args, xtr, ytr, xte, yte, classes)
    return ds


@register_dataset("uci_adult", "adult")
def load_uci_adult(args: Any) -> FederatedDataset:
    """UCI Adult census income (ref ``data/UCI``): csv from cache dir when
    present (14 features, binary label), else synthetic tabular stand-in."""
    cache = str(getattr(args, "data_cache_dir", "") or "")
    path = os.path.join(cache, "adult.npz") if cache else ""
    if path and os.path.exists(path):
        with np.load(path) as d:
            xtr, ytr = d["x_train"].astype(np.float32), d["y_train"].astype(np.int32).ravel()
            xte, yte = d["x_test"].astype(np.float32), d["y_test"].astype(np.int32).ravel()
    else:
        _synthetic_fallback("uci_adult", f"no adult.npz under {cache!r}")
        xtr, ytr, xte, yte = _make_classification_arrays(
            int(getattr(args, "train_size", 2000)),
            int(getattr(args, "test_size", 400)),
            (14,), 2, int(getattr(args, "random_seed", 0)) + 11,
        )
    return _partition_and_pack(args, xtr, ytr, xte, yte, 2)


@register_dataset("lending_club")
def load_lending_club(args: Any) -> FederatedDataset:
    """Lending-club loan default (ref ``data/lending_club_loan``)."""
    cache = str(getattr(args, "data_cache_dir", "") or "")
    path = os.path.join(cache, "lending_club.npz") if cache else ""
    if path and os.path.exists(path):
        with np.load(path) as d:
            xtr, ytr = d["x_train"].astype(np.float32), d["y_train"].astype(np.int32).ravel()
            xte, yte = d["x_test"].astype(np.float32), d["y_test"].astype(np.int32).ravel()
    else:
        _synthetic_fallback("lending_club", f"no lending_club.npz under {cache!r}")
        xtr, ytr, xte, yte = _make_classification_arrays(
            int(getattr(args, "train_size", 2000)),
            int(getattr(args, "test_size", 400)),
            (28,), 2, int(getattr(args, "random_seed", 0)) + 13,
        )
    return _partition_and_pack(args, xtr, ytr, xte, yte, 2)


@register_dataset("nus_wide", "nuswide")
def load_nus_wide(args: Any) -> FederatedDataset:
    """NUS-WIDE for VERTICAL FL (ref ``data/NUS_WIDE``): two parties hold
    different feature views of the SAME samples. The packed dataset keys
    clients 0/1 to the two views; ``vfl`` engines consume them by column."""
    cache = str(getattr(args, "data_cache_dir", "") or "")
    path = os.path.join(cache, "nus_wide.npz") if cache else ""
    dim_a = int(getattr(args, "vfl_party_a_dim", 64))
    dim_b = int(getattr(args, "vfl_party_b_dim", 225))
    if path and os.path.exists(path):
        with np.load(path) as d:
            xtr, ytr = d["x_train"].astype(np.float32), d["y_train"].astype(np.int32).ravel()
            xte, yte = d["x_test"].astype(np.float32), d["y_test"].astype(np.int32).ravel()
    else:
        _synthetic_fallback("nus_wide", f"no nus_wide.npz under {cache!r}")
        xtr, ytr, xte, yte = _make_classification_arrays(
            int(getattr(args, "train_size", 1500)),
            int(getattr(args, "test_size", 300)),
            (dim_a + dim_b,), 2, int(getattr(args, "random_seed", 0)) + 17,
        )
    n_train, n_test = len(xtr), len(xte)
    train_local = {0: (xtr[:, :dim_a], ytr), 1: (xtr[:, dim_a:], ytr)}
    test_local = {0: (xte[:, :dim_a], yte), 1: (xte[:, dim_a:], yte)}
    return FederatedDataset(
        train_data_num=n_train,
        test_data_num=n_test,
        train_data_global=(xtr, ytr),
        test_data_global=(xte, yte),
        train_data_local_num_dict={0: n_train, 1: n_train},
        train_data_local_dict=train_local,
        test_data_local_dict=test_local,
        class_num=2,
        feature_dim=dim_a + dim_b,
    )


@register_dataset("fets", "fets2021")
def load_fets(args: Any) -> FederatedDataset:
    """FeTS-2021 medical-imaging federation shape (ref ``data/FeTS``):
    per-institution volumetric patches → tumor class."""
    classes = int(getattr(args, "class_num", 2) or 2)
    cache = str(getattr(args, "data_cache_dir", "") or "")
    path = os.path.join(cache, "fets.npz") if cache else ""
    if path and os.path.exists(path):
        with np.load(path) as d:
            xtr, ytr = d["x_train"].astype(np.float32), d["y_train"].astype(np.int32).ravel()
            xte, yte = d["x_test"].astype(np.float32), d["y_test"].astype(np.int32).ravel()
    else:
        _synthetic_fallback("fets", f"no fets.npz under {cache!r}")
        xtr, ytr, xte, yte = _make_classification_arrays(
            int(getattr(args, "train_size", 400)),
            int(getattr(args, "test_size", 80)),
            (16, 16, 16), classes, int(getattr(args, "random_seed", 0)) + 19,
        )
        xtr = xtr.reshape(len(xtr), -1)
        xte = xte.reshape(len(xte), -1)
    return _partition_and_pack(args, xtr, ytr, xte, yte, classes)


def available_datasets() -> list:
    return sorted(_LOADERS)
