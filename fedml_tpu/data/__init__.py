from fedml_tpu.data.data_loader import available_datasets, load, load_federated
from fedml_tpu.data.dataset import FederatedDataset, batch_epochs

__all__ = [
    "available_datasets",
    "batch_epochs",
    "FederatedDataset",
    "load",
    "load_federated",
]
