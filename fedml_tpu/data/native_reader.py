"""Raw-format dataset readers: MNIST idx + CIFAR-10 binary — C++ kernel
with a numpy twin.

Parity target: the reference's native MobileNN dataset readers
(``android/fedmlsdk/MobileNN/src/MNN/{mnist,cifar10}.cpp``,
``src/torch/{mnist,cifar10}.cpp``), which parse exactly these raw
formats for the on-device trainer. Here they feed the data registry /
cross-device runtime: ``native/dataset.cpp`` via ctypes when a
toolchain is present, bit-identical numpy fallback otherwise
(``tests/test_native_reader.py`` enforces parity).

Formats:
- idx3/idx1 (big-endian magic 0x803/0x801): images → float32 [0, 1]
  flattened rows, labels → int32;
- CIFAR-10 binary batches (3073-byte records, CHW uint8): images →
  float32 [0, 1] **HWC** (TPU/XLA's native conv layout), labels int32.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdataset.so")
_lib = None
_lib_tried = False


def _load_native():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR, "libdataset.so"],
                           check=True, capture_output=True, timeout=120)
        except Exception as e:  # pragma: no cover
            logger.info("native dataset build unavailable (%s); numpy twin", e)
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        LL, F32P, I32P, LLP, CP = (ctypes.c_longlong,
                                   ctypes.POINTER(ctypes.c_float),
                                   ctypes.POINTER(ctypes.c_int32),
                                   ctypes.POINTER(ctypes.c_longlong),
                                   ctypes.c_char_p)
        lib.mnist_read_images.restype = LL
        lib.mnist_read_images.argtypes = [CP, F32P, LL, LLP, LLP]
        lib.mnist_read_labels.restype = LL
        lib.mnist_read_labels.argtypes = [CP, I32P, LL]
        lib.cifar10_read_batch.restype = LL
        lib.cifar10_read_batch.argtypes = [CP, F32P, I32P, LL]
        _lib = lib
    except OSError as e:  # pragma: no cover
        logger.info("native dataset load failed (%s); numpy twin", e)
        _lib = None
    return _lib


# -- numpy twins (bit-identical; also the no-toolchain path) ---------------

def _mnist_images_np(path: str, max_n: Optional[int]) -> np.ndarray:
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < 16 or int.from_bytes(raw[:4], "big") != 0x803:
        raise ValueError(f"{path}: not an idx3 image file")
    n, r, c = (int.from_bytes(raw[o: o + 4], "big") for o in (4, 8, 12))
    if max_n is not None:
        n = min(n, max_n)
    body = np.frombuffer(raw, np.uint8, count=n * r * c, offset=16)
    return (body.astype(np.float32) / 255.0).reshape(n, r * c)


def _mnist_labels_np(path: str, max_n: Optional[int]) -> np.ndarray:
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < 8 or int.from_bytes(raw[:4], "big") != 0x801:
        raise ValueError(f"{path}: not an idx1 label file")
    n = int.from_bytes(raw[4:8], "big")
    if max_n is not None:
        n = min(n, max_n)
    return np.frombuffer(raw, np.uint8, count=n, offset=8).astype(np.int32)


def _cifar10_np(path: str,
                max_n: Optional[int]) -> Tuple[np.ndarray, np.ndarray]:
    raw = np.fromfile(path, np.uint8)
    rec = 1 + 3 * 32 * 32
    n = raw.size // rec
    if max_n is not None:
        n = min(n, max_n)
    rows = raw[: n * rec].reshape(n, rec)
    labels = rows[:, 0].astype(np.int32)
    chw = rows[:, 1:].reshape(n, 3, 32, 32)
    hwc = np.transpose(chw, (0, 2, 3, 1)).astype(np.float32) / 255.0
    return hwc, labels


# -- public API ------------------------------------------------------------

def read_mnist(images_path: str, labels_path: str,
               max_n: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """(x [n, 784] float32 in [0,1], y [n] int32) from raw idx files."""
    lib = _load_native()
    if lib is None:
        return (_mnist_images_np(images_path, max_n),
                _mnist_labels_np(labels_path, max_n))
    rows = ctypes.c_longlong()
    cols = ctypes.c_longlong()
    n = lib.mnist_read_images(images_path.encode(), None, 0,
                              ctypes.byref(rows), ctypes.byref(cols))
    if n < 0:
        raise ValueError(f"{images_path}: not an idx3 image file")
    if max_n is not None:
        n = min(n, max_n)
    x = np.empty((n, rows.value * cols.value), np.float32)
    got = lib.mnist_read_images(
        images_path.encode(),
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n,
        ctypes.byref(rows), ctypes.byref(cols))
    y = np.empty((n,), np.int32)
    gotl = lib.mnist_read_labels(
        labels_path.encode(),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
    if gotl < 0:
        raise ValueError(f"{labels_path}: not an idx1 label file")
    m = min(int(got), int(gotl))
    return x[:m], y[:m]


def read_cifar10_batches(paths, max_n: Optional[int] = None
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """(x [n, 32, 32, 3] float32 HWC in [0,1], y [n] int32) from binary
    batch files (concatenated in the given order)."""
    lib = _load_native()
    xs, ys = [], []
    remaining = max_n
    for path in paths:
        if remaining is not None and remaining <= 0:
            break
        if lib is None:
            x, y = _cifar10_np(path, remaining)
        else:
            rec_bytes = os.path.getsize(path)
            cap = rec_bytes // (1 + 3 * 32 * 32)
            if remaining is not None:
                cap = min(cap, remaining)
            x = np.empty((cap, 32, 32, 3), np.float32)
            y = np.empty((cap,), np.int32)
            n = lib.cifar10_read_batch(
                path.encode(),
                x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
            if n < 0:
                raise ValueError(f"{path}: unreadable CIFAR-10 batch")
            x, y = x[:n], y[:n]
        xs.append(x)
        ys.append(y)
        if remaining is not None:
            remaining -= len(y)
    return np.concatenate(xs), np.concatenate(ys)
