"""Pytree (de)serialization at the transport boundary.

Model payloads stay on device as JAX arrays until a transport needs bytes;
then leaves are pulled to host numpy and packed. Format: a small header
(treedef repr via pickle of the numpy-leaved pytree). The reference ships
state dicts with torch.save/pickle over S3 (``communication/s3/remote_storage.py``);
we keep the same contract with numpy.
"""
from __future__ import annotations

import io
import pickle
from typing import Any

import jax
import numpy as np

Pytree = Any


def tree_to_bytes(tree: Pytree) -> bytes:
    host_tree = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)
    buf = io.BytesIO()
    pickle.dump(host_tree, buf, protocol=4)
    return buf.getvalue()


def tree_from_bytes(data: bytes) -> Pytree:
    return pickle.loads(data)


def tree_nbytes(tree: Pytree) -> int:
    return sum(np.asarray(x).nbytes for x in jax.tree.leaves(tree))
