"""Pytree (de)serialization at the transport boundary — pickle-free.

Model payloads stay on device as JAX arrays until a transport needs bytes;
then leaves are pulled to host numpy and packed. The reference ships state
dicts with torch.save/pickle over S3 (``communication/s3/remote_storage.py``)
— a design that executes attacker-controlled bytecode on load. Here the
wire format is deliberately dumb: a JSON skeleton (dicts/lists/tuples/
scalars with array placeholders) plus concatenated raw ``.npy`` blobs read
back with ``allow_pickle=False``, so deserializing a hostile payload can at
worst produce wrong numbers, never code execution.

Format:  [4-byte header length][header JSON][npy blob]*
         header = {"skeleton": ..., "arrays": [nbytes, ...]}

Compressed payloads (``fedml_tpu/compression``) ride the same format as a
versioned, codec-tagged skeleton node: ``{"__codec__": name, "v": 1, ...}``
wrapping the codec's array blobs. Decode validates the tag against the
codec registry and rejects unknown tags/versions with ``ValueError`` —
the same rejection contract as every other hostile-payload path here.
"""
from __future__ import annotations

import io
import json
import struct
from typing import Any, List

import jax
import numpy as np

Pytree = Any

_ARRAY = "__ndarray__"
_TUPLE = "__tuple__"
_BYTES = "__bytes__"
_CODEC = "__codec__"
_RESERVED = (_ARRAY, _TUPLE, _BYTES, _CODEC)

# extension dtypes with no npy descr ride the wire as a same-itemsize
# integer view plus a "dt" tag on the array node
_EXT_DTYPES = {"bfloat16": np.uint16}


def _npy_parts(arr: np.ndarray):
    """(header_bytes, data_view) for one array — no BytesIO/np.save pass.

    The ~100-byte npy header is built via ``np.lib.format``; the array
    payload is *aliased* (a memoryview of the array's own buffer) rather
    than copied, so the only copy is the final ``b"".join`` in
    :func:`safe_dumps` — the encode-side counterpart of the zero-copy
    ``frombuffer`` decode below.
    """
    d = np.lib.format.header_data_from_array_1_0(arr)
    buf = io.BytesIO()
    np.lib.format.write_array_header_1_0(buf, d)
    header = buf.getvalue()
    if arr.ndim == 0:
        return header, arr.tobytes()
    if arr.flags.c_contiguous:
        return header, _alias_bytes(arr)
    if d["fortran_order"] and arr.T.flags.c_contiguous:
        # header says F order; the transposed view aliases those bytes
        return header, _alias_bytes(arr.T)
    return header, arr.tobytes()  # non-contiguous: one unavoidable copy


def _alias_bytes(arr: np.ndarray):
    """Byte view of a C-contiguous array without copying.

    Extension dtypes (ml_dtypes bfloat16 etc.) refuse the buffer
    protocol on the typed array — reinterpreting as uint8 first aliases
    the same memory and always exports.
    """
    try:
        return memoryview(arr).cast("B")
    except (ValueError, TypeError):
        return memoryview(arr.view(np.uint8)).cast("B")


def _encode(obj: Any, blobs: List[Any]) -> Any:
    """Recursively JSON-ify; arrays become placeholders into ``blobs``.

    ``blobs`` entries are bytes-likes or tuples of bytes-likes (an array's
    header + aliased data) — sized and joined by :func:`safe_dumps`.
    """
    from fedml_tpu.compression.codecs import CompressedTree

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (bytes, bytearray)):
        blobs.append(b"RAW0" + bytes(obj))
        return {_BYTES: len(blobs) - 1}
    if isinstance(obj, CompressedTree):
        node = {
            _CODEC: obj.codec,
            "v": obj.version,
            "delta": obj.is_delta,
            "raw_nbytes": obj.raw_nbytes,
            "meta": [[dt, list(sh)] for dt, sh in obj.meta],
            "structure": _encode(obj.structure, blobs),
            "state": _encode(obj.arrays, blobs),
        }
        if obj.sa is not None:
            # masked wire node (v2): the sa field carries the mask-domain
            # metadata the receiving aggregator validates before fusing
            node["sa"] = _encode(obj.sa, blobs)
        return node
    if isinstance(obj, (np.ndarray, jax.Array, np.generic)):
        # already-host arrays skip the device_get + asarray double hop
        arr = obj if isinstance(obj, np.ndarray) else np.asarray(
            jax.device_get(obj))
        dt = str(arr.dtype)
        if dt in _EXT_DTYPES:
            # extension dtypes (bf16) have no npy descr: ship the bytes
            # as a same-itemsize integer view, tag the true dtype in the
            # skeleton so decode restores it losslessly
            blobs.append(_npy_parts(arr.view(_EXT_DTYPES[dt])))
            return {_ARRAY: len(blobs) - 1, "dt": dt}
        blobs.append(_npy_parts(arr))
        return {_ARRAY: len(blobs) - 1}
    if isinstance(obj, dict):
        if any(not isinstance(k, str) or k in _RESERVED for k in obj):
            # JSON keys must be strings, and user keys that collide with
            # the decode tags must not be interpretable as tags: both go
            # through the lossless items encoding
            return {
                _TUPLE: "dict_items",
                "items": [
                    [_encode(k, blobs), _encode(v, blobs)] for k, v in obj.items()
                ],
            }
        return {k: _encode(v, blobs) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return {_TUPLE: "tuple", "items": [_encode(v, blobs) for v in obj]}
    if isinstance(obj, list):
        return [_encode(v, blobs) for v in obj]
    raise TypeError(
        f"safe serialization does not support {type(obj).__name__}; "
        "transport payloads must be pytrees of arrays/scalars/str"
    )


def _blob_at(blobs: List[Any], idx: Any) -> Any:
    try:
        i = int(idx)
    except (TypeError, ValueError):
        raise ValueError(f"non-integer blob index {idx!r}") from None
    if not 0 <= i < len(blobs):
        raise ValueError(f"payload references blob {i} of {len(blobs)}")
    return blobs[i]


_NPY_MAGIC = b"\x93NUMPY"


def _ndarray_from_npy(mv: memoryview) -> np.ndarray:
    """Decode one ``.npy`` blob without copying the array payload.

    The (~100-byte) header is parsed via ``np.lib.format``; the array
    data itself is aliased straight out of the transport buffer with
    ``np.frombuffer`` — zero-copy, so the result is read-only (writers
    downstream feed it to jax, which copies on device transfer anyway).
    Falls back to ``np.load`` for layouts frombuffer can't alias
    (non-contiguous/pickled payloads are rejected there as before).
    """
    head = mv[: min(len(mv), 12)].tobytes()
    if head[:6] != _NPY_MAGIC:
        raise ValueError("array blob is not in npy format")
    # hostile/truncated payloads must fail as ValueError (the rejection
    # contract of safe_loads), never struct.error/IndexError
    if len(head) < 10:
        raise ValueError("array blob header is truncated")
    major = head[6]
    if major == 1:
        (hlen,) = struct.unpack_from("<H", head, 8)
        data_start = 10 + hlen
        header_fn = np.lib.format.read_array_header_1_0
    else:
        if len(head) < 12:
            raise ValueError("array blob header is truncated")
        (hlen,) = struct.unpack_from("<I", head, 8)
        data_start = 12 + hlen
        header_fn = np.lib.format.read_array_header_2_0
    fp = io.BytesIO(mv[8:data_start].tobytes())
    shape, fortran_order, dtype = header_fn(fp)
    if dtype.hasobject:
        raise ValueError("object arrays are not allowed in safe payloads")
    count = int(np.prod(shape, dtype=np.int64)) if shape else 1
    nbytes = count * dtype.itemsize
    data = mv[data_start:data_start + nbytes]
    if len(data) != nbytes:
        raise ValueError("array blob is truncated")
    arr = np.frombuffer(data, dtype=dtype, count=count)
    return arr.reshape(shape, order="F" if fortran_order else "C")


def _decode_codec(node: dict, blobs: List[memoryview]) -> Any:
    """Rebuild a CompressedTree from its tagged skeleton node.

    Unknown codec tags and unsupported wire versions are rejected with
    ``ValueError`` — a hostile peer must not be able to smuggle bytes
    past the registry by inventing a tag.
    """
    from fedml_tpu.compression.codecs import (
        WIRE_VERSION,
        WIRE_VERSION_MASKED,
        CompressedTree,
        available_codecs,
    )

    codec = node.get(_CODEC)
    if not isinstance(codec, str) or codec not in available_codecs():
        raise ValueError(f"unknown compression codec tag {codec!r}")
    version = node.get("v")
    if version not in (WIRE_VERSION, WIRE_VERSION_MASKED):
        raise ValueError(f"unsupported compression wire version {version!r}")
    sa = None
    if version == WIRE_VERSION_MASKED:
        # masked wire nodes REQUIRE a maskable codec and a well-formed
        # sa dict; v1 nodes must not smuggle one — every direction
        # rejects, same contract as unknown tags (a hostile peer gets
        # ValueError, never a guess). The maskable check stops a plain
        # codec from masquerading as the masked wire.
        from fedml_tpu.compression.codecs import get_codec

        if not getattr(get_codec(codec), "maskable", False):
            raise ValueError(
                f"codec {codec!r} is not maskable; v2 wire nodes carry "
                "masked payloads only")
        sa = _decode(node.get("sa"), blobs)
        if not isinstance(sa, dict):
            raise ValueError("masked (v2) payload missing its sa field")
    elif "sa" in node:
        raise ValueError("v1 compressed payload carries a masked sa field")
    meta = node.get("meta")
    arrays = _decode(node.get("state"), blobs)
    structure = _decode(node.get("structure"), blobs)
    if not isinstance(meta, list) or not isinstance(arrays, list):
        raise ValueError("malformed compressed payload")
    try:
        meta_t = tuple((str(dt), tuple(int(d) for d in sh))
                       for dt, sh in meta)
        return CompressedTree(
            codec, int(version), bool(node.get("delta", False)),
            int(node.get("raw_nbytes", 0)), meta_t, structure, arrays,
            sa=sa,
        )
    except (TypeError, ValueError) as e:
        raise ValueError(f"malformed compressed payload: {e}") from None


def _decode(node: Any, blobs: List[memoryview]) -> Any:
    if isinstance(node, dict):
        if _CODEC in node:
            return _decode_codec(node, blobs)
        if _ARRAY in node and (
                len(node) == 1 or (len(node) == 2 and "dt" in node)):
            raw = _blob_at(blobs, node[_ARRAY])
            if raw[:4].tobytes() == b"RAW0":
                raise ValueError("array tag references a bytes blob")
            arr = _ndarray_from_npy(raw)
            dt = node.get("dt")
            if dt is not None:
                if dt not in _EXT_DTYPES:
                    raise ValueError(f"unknown extension dtype tag {dt!r}")
                if arr.dtype != _EXT_DTYPES[dt]:
                    raise ValueError(
                        f"extension dtype tag {dt!r} on a "
                        f"{arr.dtype} blob")
                arr = arr.view(np.dtype(jax.numpy.bfloat16))
            return arr
        if _BYTES in node and len(node) == 1:
            raw = _blob_at(blobs, node[_BYTES])
            if raw[:4].tobytes() != b"RAW0":
                raise ValueError("bytes tag references a non-bytes blob")
            return raw[4:].tobytes()
        if node.get(_TUPLE) == "tuple":
            if not isinstance(node.get("items"), list):
                raise ValueError("malformed tuple node")
            return tuple(_decode(v, blobs) for v in node["items"])
        if node.get(_TUPLE) == "dict_items":
            items = node.get("items")
            if not isinstance(items, list) or not all(
                    isinstance(kv, list) and len(kv) == 2 for kv in items):
                raise ValueError("malformed dict_items node")
            return {
                _decode(k, blobs): _decode(v, blobs) for k, v in items
            }
        return {k: _decode(v, blobs) for k, v in node.items()}
    if isinstance(node, list):
        return [_decode(v, blobs) for v in node]
    return node


def safe_dumps(obj: Any) -> bytes:
    blobs: List[Any] = []
    skeleton = _encode(obj, blobs)
    sizes = [sum(len(p) for p in b) if isinstance(b, tuple) else len(b)
             for b in blobs]
    header = json.dumps(
        {"skeleton": skeleton, "arrays": sizes}
    ).encode()
    parts: List[Any] = [struct.pack("<I", len(header)), header]
    for b in blobs:
        if isinstance(b, tuple):
            parts.extend(b)
        else:
            parts.append(b)
    return b"".join(parts)


def safe_loads(data: bytes) -> Any:
    # hostile/truncated payloads must fail as ValueError — the single
    # rejection contract callers (and the wire-format fuzz smoke) rely on
    if len(data) < 4:
        raise ValueError("payload too short for a header")
    (hlen,) = struct.unpack_from("<I", data, 0)
    if 4 + hlen > len(data):
        raise ValueError("header length overruns the payload")
    try:
        header = json.loads(data[4 : 4 + hlen].decode())
    except UnicodeDecodeError as e:
        raise ValueError(f"payload header is not UTF-8: {e}") from None
    if not isinstance(header, dict) or not isinstance(
            header.get("arrays"), list):
        raise ValueError("malformed payload header")
    offset = 4 + hlen
    # memoryview slices alias the payload — no per-blob copy; array
    # leaves are then aliased out of these views by _ndarray_from_npy
    mv = memoryview(data)
    blobs: List[memoryview] = []
    for nbytes in header["arrays"]:
        nbytes = _blob_size(nbytes)
        if offset + nbytes > len(data):
            raise ValueError("blob table overruns the payload")
        blobs.append(mv[offset : offset + nbytes])
        offset += nbytes
    return _decode(header["skeleton"], blobs)


def _blob_size(nbytes: Any) -> int:
    try:
        n = int(nbytes)
    except (TypeError, ValueError):
        raise ValueError(f"non-integer blob size {nbytes!r}") from None
    if n < 0:
        raise ValueError(f"negative blob size {n}")
    return n


# -- pytree-payload convenience (kept API-compatible) -----------------------

def tree_to_bytes(tree: Pytree) -> bytes:
    return safe_dumps(tree)


def tree_from_bytes(data: bytes) -> Pytree:
    return safe_loads(data)


def tree_nbytes(tree: Pytree) -> int:
    # x.nbytes, not np.asarray(x).nbytes: asarray on a jax array forces a
    # device→host transfer just to read a size that both jax and numpy
    # arrays already expose as metadata
    total = 0
    for x in jax.tree.leaves(tree):
        nb = getattr(x, "nbytes", None)
        total += int(nb) if nb is not None else np.asarray(x).nbytes
    return total
