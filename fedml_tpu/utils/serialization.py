"""Pytree (de)serialization at the transport boundary — pickle-free.

Model payloads stay on device as JAX arrays until a transport needs bytes;
then leaves are pulled to host numpy and packed. The reference ships state
dicts with torch.save/pickle over S3 (``communication/s3/remote_storage.py``)
— a design that executes attacker-controlled bytecode on load. Here the
wire format is deliberately dumb: a JSON skeleton (dicts/lists/tuples/
scalars with array placeholders) plus concatenated raw ``.npy`` blobs read
back with ``allow_pickle=False``, so deserializing a hostile payload can at
worst produce wrong numbers, never code execution.

Format:  [4-byte header length][header JSON][npy blob]*
         header = {"skeleton": ..., "arrays": [nbytes, ...]}
"""
from __future__ import annotations

import io
import json
import struct
from typing import Any, List

import jax
import numpy as np

Pytree = Any

_ARRAY = "__ndarray__"
_TUPLE = "__tuple__"
_BYTES = "__bytes__"
_RESERVED = (_ARRAY, _TUPLE, _BYTES)


def _encode(obj: Any, blobs: List[bytes]) -> Any:
    """Recursively JSON-ify; arrays become placeholders into ``blobs``."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (bytes, bytearray)):
        blobs.append(b"RAW0" + bytes(obj))
        return {_BYTES: len(blobs) - 1}
    if isinstance(obj, (np.ndarray, jax.Array, np.generic)):
        arr = np.asarray(jax.device_get(obj))
        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        blobs.append(buf.getvalue())
        return {_ARRAY: len(blobs) - 1}
    if isinstance(obj, dict):
        if any(not isinstance(k, str) or k in _RESERVED for k in obj):
            # JSON keys must be strings, and user keys that collide with
            # the decode tags must not be interpretable as tags: both go
            # through the lossless items encoding
            return {
                _TUPLE: "dict_items",
                "items": [
                    [_encode(k, blobs), _encode(v, blobs)] for k, v in obj.items()
                ],
            }
        return {k: _encode(v, blobs) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return {_TUPLE: "tuple", "items": [_encode(v, blobs) for v in obj]}
    if isinstance(obj, list):
        return [_encode(v, blobs) for v in obj]
    raise TypeError(
        f"safe serialization does not support {type(obj).__name__}; "
        "transport payloads must be pytrees of arrays/scalars/str"
    )


def _blob_at(blobs: List[Any], idx: Any) -> Any:
    i = int(idx)
    if not 0 <= i < len(blobs):
        raise ValueError(f"payload references blob {i} of {len(blobs)}")
    return blobs[i]


_NPY_MAGIC = b"\x93NUMPY"


def _ndarray_from_npy(mv: memoryview) -> np.ndarray:
    """Decode one ``.npy`` blob without copying the array payload.

    The (~100-byte) header is parsed via ``np.lib.format``; the array
    data itself is aliased straight out of the transport buffer with
    ``np.frombuffer`` — zero-copy, so the result is read-only (writers
    downstream feed it to jax, which copies on device transfer anyway).
    Falls back to ``np.load`` for layouts frombuffer can't alias
    (non-contiguous/pickled payloads are rejected there as before).
    """
    head = mv[: min(len(mv), 12)].tobytes()
    if head[:6] != _NPY_MAGIC:
        raise ValueError("array blob is not in npy format")
    # hostile/truncated payloads must fail as ValueError (the rejection
    # contract of safe_loads), never struct.error/IndexError
    if len(head) < 10:
        raise ValueError("array blob header is truncated")
    major = head[6]
    if major == 1:
        (hlen,) = struct.unpack_from("<H", head, 8)
        data_start = 10 + hlen
        header_fn = np.lib.format.read_array_header_1_0
    else:
        if len(head) < 12:
            raise ValueError("array blob header is truncated")
        (hlen,) = struct.unpack_from("<I", head, 8)
        data_start = 12 + hlen
        header_fn = np.lib.format.read_array_header_2_0
    fp = io.BytesIO(mv[8:data_start].tobytes())
    shape, fortran_order, dtype = header_fn(fp)
    if dtype.hasobject:
        raise ValueError("object arrays are not allowed in safe payloads")
    count = int(np.prod(shape, dtype=np.int64)) if shape else 1
    nbytes = count * dtype.itemsize
    data = mv[data_start:data_start + nbytes]
    if len(data) != nbytes:
        raise ValueError("array blob is truncated")
    arr = np.frombuffer(data, dtype=dtype, count=count)
    return arr.reshape(shape, order="F" if fortran_order else "C")


def _decode(node: Any, blobs: List[memoryview]) -> Any:
    if isinstance(node, dict):
        if _ARRAY in node and len(node) == 1:
            raw = _blob_at(blobs, node[_ARRAY])
            if raw[:4].tobytes() == b"RAW0":
                raise ValueError("array tag references a bytes blob")
            return _ndarray_from_npy(raw)
        if _BYTES in node and len(node) == 1:
            raw = _blob_at(blobs, node[_BYTES])
            if raw[:4].tobytes() != b"RAW0":
                raise ValueError("bytes tag references a non-bytes blob")
            return raw[4:].tobytes()
        if node.get(_TUPLE) == "tuple":
            return tuple(_decode(v, blobs) for v in node["items"])
        if node.get(_TUPLE) == "dict_items":
            return {
                _decode(k, blobs): _decode(v, blobs) for k, v in node["items"]
            }
        return {k: _decode(v, blobs) for k, v in node.items()}
    if isinstance(node, list):
        return [_decode(v, blobs) for v in node]
    return node


def safe_dumps(obj: Any) -> bytes:
    blobs: List[bytes] = []
    skeleton = _encode(obj, blobs)
    header = json.dumps(
        {"skeleton": skeleton, "arrays": [len(b) for b in blobs]}
    ).encode()
    return b"".join([struct.pack("<I", len(header)), header, *blobs])


def safe_loads(data: bytes) -> Any:
    (hlen,) = struct.unpack_from("<I", data, 0)
    header = json.loads(data[4 : 4 + hlen].decode())
    offset = 4 + hlen
    # memoryview slices alias the payload — no per-blob copy; array
    # leaves are then aliased out of these views by _ndarray_from_npy
    mv = memoryview(data)
    blobs: List[memoryview] = []
    for nbytes in header["arrays"]:
        nbytes = int(nbytes)
        if nbytes < 0 or offset + nbytes > len(data):
            raise ValueError("blob table overruns the payload")
        blobs.append(mv[offset : offset + nbytes])
        offset += nbytes
    return _decode(header["skeleton"], blobs)


# -- pytree-payload convenience (kept API-compatible) -----------------------

def tree_to_bytes(tree: Pytree) -> bytes:
    return safe_dumps(tree)


def tree_from_bytes(data: bytes) -> Pytree:
    return safe_loads(data)


def tree_nbytes(tree: Pytree) -> int:
    # x.nbytes, not np.asarray(x).nbytes: asarray on a jax array forces a
    # device→host transfer just to read a size that both jax and numpy
    # arrays already expose as metadata
    total = 0
    for x in jax.tree.leaves(tree):
        nb = getattr(x, "nbytes", None)
        total += int(nb) if nb is not None else np.asarray(x).nbytes
    return total
