"""Version shims for the narrow slice of the jax API the engine uses.

The codebase targets the current jax surface (``jax.shard_map``,
``jax.lax.pcast``); older runtimes (0.4.x) ship the same functionality
under experimental names or simply don't enforce the varying-type system
that ``pcast`` feeds. Routing every call site through this module keeps
the simulators importable across the jax versions the fleet actually
runs — one hasattr probe at import, zero per-call overhead.
"""
from __future__ import annotations

from typing import Any

import jax

Pytree = Any

if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # jax < 0.6: same callable, experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

_HAS_PCAST = hasattr(jax.lax, "pcast")


def shard_map(f, mesh, in_specs, out_specs, check_vma=None, axis_names=None):
    """``jax.shard_map`` wherever it lives in this jax version.

    ``check_vma`` / ``axis_names`` are the current-jax spellings; on the
    experimental (0.4.x) shard_map they translate to ``check_rep`` and
    ``auto`` (the complement: axes NOT manually mapped).
    """
    kwargs = {}
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = frozenset(axis_names)
    else:
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        if axis_names is not None:
            kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def axis_size(axis_name: str):
    """``jax.lax.axis_size`` with a psum(1) fallback for older jax.

    Inside shard_map/pmap the axis size is static, so the fallback's
    psum of a constant folds to a compile-time constant — no collective
    is actually emitted.
    """
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast_varying(tree: Pytree, axis_names) -> Pytree:
    """Cast replicated leaves to device-varying over ``axis_names``.

    On jax versions without ``jax.lax.pcast`` there is no varying-type
    check to satisfy — the cast is the identity.
    """
    if not _HAS_PCAST:
        return tree
    return jax.tree.map(
        lambda p: jax.lax.pcast(p, tuple(axis_names), to="varying"), tree
    )


def sharding_mesh_axes(sharding) -> dict:
    """``{axis_name: size}`` of a sharding's mesh, or ``{}``.

    Version-tolerant introspection for the program catalog's mesh/
    sharding records: ``NamedSharding`` exposes a mesh on every jax this
    repo runs; anything else (``SingleDeviceSharding``, GSPMD opaque
    shardings from older compilers) reports no axes rather than raising.
    """
    mesh = getattr(sharding, "mesh", None)
    if mesh is None:
        return {}
    try:
        return {str(name): int(size)
                for name, size in dict(mesh.shape).items()}
    except Exception:  # pragma: no cover - exotic mesh type
        return {}


def pspec_str(sharding) -> str:
    """A stable one-line spelling of a sharding's partition spec.

    ``NamedSharding`` → ``"P('dp', None)"``-style; shardings without a
    ``spec`` (fully replicated, single-device, opaque GSPMD) render via
    ``repr`` truncated — the catalog wants a human-auditable label, not
    a round-trippable object.
    """
    spec = getattr(sharding, "spec", None)
    if spec is not None:
        return f"P{tuple(spec)!r}"
    return repr(sharding)[:80]


__all__ = [
    "axis_size",
    "pcast_varying",
    "pspec_str",
    "shard_map",
    "sharding_mesh_axes",
]
