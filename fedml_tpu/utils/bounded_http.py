"""Bounded-admission helper for stdlib ``ThreadingHTTPServer`` handlers.

The serving runner (PR 7) and the live-plane scrape endpoint (PR 8) each
grew the same overload policy independently: a ``ThreadingHTTPServer``
accepts one OS thread per connection, but *work* admission is gated by a
semaphore permit — a request that cannot get one within ``queue_wait_s``
is shed immediately with ``429`` + ``Retry-After`` instead of queueing
unboundedly behind a saturated engine. Shedding on a keep-alive
(HTTP/1.1) connection must also drain the unread request body, or the
NEXT request on the socket is parsed from leftover bytes (the desync
PR 7 fixed).

This module is that policy, once: an :class:`AdmissionGate` owning the
permit pool, the measured queue wait, the drain-on-shed 429 path, and
the observer hooks the request-observability layer needs — ``on_wait``
(every admission decision reports how long the caller queued for a
permit) and ``on_shed`` (fired with the number of callers still waiting
at shed time, the queue depth an operator wants in the overload event).
Hooks are best-effort by contract: observability must never break the
served request.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional

__all__ = ["AdmissionGate", "drain_body"]


def drain_body(handler, max_drain_bytes: int = 1 << 20) -> None:
    """Consume the unread request body before an error reply.

    Error replies on a keep-alive (HTTP/1.1) connection must consume the
    unread request body, or the NEXT request on the socket is parsed
    from leftover bytes (400). Bodies past ``max_drain_bytes`` are too
    big to drain cheaply — drop the connection instead.
    """
    n = int(handler.headers.get("Content-Length", 0))
    if n > max_drain_bytes:
        handler.close_connection = True
    elif n > 0:
        handler.rfile.read(n)


class AdmissionGate:
    """Permit pool + queue-wait measurement + the 429 shed path.

    ``admit(handler)`` returns True and charges one permit (release with
    :meth:`release`), or writes the full 429 response — body drained,
    ``Retry-After: 1`` — and returns False.
    """

    def __init__(self, max_inflight: int, queue_wait_s: float,
                 max_drain_bytes: int = 1 << 20,
                 on_wait: Optional[Callable[[float], None]] = None,
                 on_shed: Optional[Callable[[int, float], None]] = None):
        self._permits = threading.BoundedSemaphore(int(max_inflight))
        self._queue_wait_s = float(queue_wait_s)
        self._max_drain_bytes = int(max_drain_bytes)
        self._on_wait = on_wait
        self._on_shed = on_shed
        self._waiting = 0
        self._lock = threading.Lock()

    @property
    def waiting(self) -> int:
        """Callers currently blocked on a permit (the admission queue
        depth an overload event should carry)."""
        with self._lock:
            return self._waiting

    def admit(self, handler) -> bool:
        t0 = time.perf_counter()
        with self._lock:
            self._waiting += 1
        try:
            ok = self._permits.acquire(timeout=self._queue_wait_s)
        finally:
            with self._lock:
                self._waiting -= 1
        wait_s = time.perf_counter() - t0
        if self._on_wait is not None:
            try:
                self._on_wait(wait_s)
            except Exception:  # noqa: BLE001 - hooks are best-effort
                pass
        if ok:
            return True
        depth = self.waiting
        drain_body(handler, self._max_drain_bytes)
        if self._on_shed is not None:
            try:
                self._on_shed(depth, wait_s)
            except Exception:  # noqa: BLE001 - hooks are best-effort
                pass
        body = json.dumps({"error": "overloaded"}).encode()
        try:
            handler.send_response(429)
            handler.send_header("Retry-After", "1")
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
        except BrokenPipeError:  # pragma: no cover - client gone
            pass
        return False

    def release(self) -> None:
        self._permits.release()
