"""Pytree utilities — the numeric backbone of every aggregator and defense.

The reference iterates over ``state_dict`` keys in Python for each aggregation
(``python/fedml/ml/aggregator/agg_operator.py:33``). Here model state is a JAX
pytree and every reduction is a single jitted program, so XLA fuses the whole
weighted average into a handful of HBM passes regardless of layer count.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

Pytree = Any


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: Pytree, scalar) -> Pytree:
    return jax.tree.map(lambda x: x * scalar, tree)


def tree_axpy(alpha, x: Pytree, y: Pytree) -> Pytree:
    """alpha * x + y, elementwise over the tree."""
    return jax.tree.map(lambda a, b: alpha * a + b, x, y)


@functools.partial(jax.jit, static_argnames=("ord_",))
def tree_norm(tree: Pytree, ord_: int = 2) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    if ord_ == 2:
        return jnp.sqrt(sum(jnp.vdot(x, x) for x in leaves))
    flat = jnp.concatenate([jnp.ravel(x) for x in leaves])
    return jnp.linalg.norm(flat, ord=ord_)


def tree_dot(a: Pytree, b: Pytree) -> jax.Array:
    return sum(
        jnp.vdot(x, y)
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def tree_size(tree: Pytree) -> int:
    """Total number of scalar parameters."""
    return sum(x.size for x in jax.tree.leaves(tree))


def tree_flatten_vector(tree: Pytree) -> jax.Array:
    """Concatenate every leaf into one flat fp32 vector (device-resident)."""
    return jnp.concatenate(
        [jnp.ravel(x).astype(jnp.float32) for x in jax.tree.leaves(tree)]
    )


def tree_unflatten_vector(vec: jax.Array, tree_like: Pytree) -> Pytree:
    """Inverse of :func:`tree_flatten_vector` against a template tree."""
    leaves, treedef = jax.tree.flatten(tree_like)
    out, offset = [], 0
    for leaf in leaves:
        n = leaf.size
        out.append(vec[offset : offset + n].reshape(leaf.shape).astype(leaf.dtype))
        offset += n
    return jax.tree.unflatten(treedef, out)


@jax.jit
def weighted_tree_sum(trees: Pytree, weights: jax.Array) -> Pytree:
    """Weighted sum over stacked trees.

    ``trees`` is a pytree whose leaves have a leading "participant" axis of
    size N; ``weights`` is shape (N,) and should already be normalized.
    This is the whole of FedAvg aggregation as one XLA program — the
    replacement for the per-key dict loop in the reference
    (``ml/aggregator/agg_operator.py:33-47``).
    """

    def _wsum(leaf):
        w = weights.reshape((-1,) + (1,) * (leaf.ndim - 1)).astype(leaf.dtype)
        return jnp.sum(leaf * w, axis=0)

    return jax.tree.map(_wsum, trees)


def tree_stack(trees: Sequence[Pytree]) -> Pytree:
    """Stack N structurally-identical trees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(stacked: Pytree, n: int) -> list[Pytree]:
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n)]


def tree_index(stacked: Pytree, i) -> Pytree:
    return jax.tree.map(lambda x: x[i], stacked)


def tree_cast(tree: Pytree, dtype) -> Pytree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_map_with_path_filter(
    fn: Callable, tree: Pytree, predicate: Callable[[str], bool]
) -> Pytree:
    """Apply ``fn`` only to leaves whose joined key-path satisfies predicate."""

    def _apply(path, leaf):
        name = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        return fn(leaf) if predicate(name) else leaf

    return jax.tree_util.tree_map_with_path(_apply, tree)
