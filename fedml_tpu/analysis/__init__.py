"""graftcheck — semantic static analysis for the repo's own invariants.

PRs 2-10 established conventions that no generic linter can check:
hot-path programs are pure and jitted once, donated buffers are never
re-read, host syncs happen only at sanctioned round boundaries, state
shared across timer/heartbeat/comm-handler/HTTP threads is lock-guarded,
and every wire message type has a receiver.  ``fedml_tpu.analysis`` is
the AST-based framework that machine-checks them: a shared file/scope/
call-graph core (:mod:`fedml_tpu.analysis.core`) plus one module per
invariant under :mod:`fedml_tpu.analysis.passes`.

Entry points:

* ``tools/graftcheck.py`` / ``fedml_tpu analyze`` — the CLI
  (:func:`fedml_tpu.analysis.runner.main`);
* :func:`run_analysis` — the library API used by tests;
* ``tools/check_span_names.py`` and ``tools/lint.py`` remain as thin
  shims over the migrated ``span-names`` and ``lint`` passes.

Suppression: a line comment ``# graft: allow(<pass-id>): <why>`` waives
one line (the justification is mandatory), and ``analysis_baseline.txt``
at the repo root waives verified-benign pre-existing findings.  See
``docs/static_analysis.md``.
"""
from __future__ import annotations

from fedml_tpu.analysis.core import Finding, Repo  # noqa: F401
from fedml_tpu.analysis.runner import (  # noqa: F401
    ALL_PASSES,
    load_baseline,
    run_analysis,
)

__all__ = ["ALL_PASSES", "Finding", "Repo", "load_baseline", "run_analysis"]
