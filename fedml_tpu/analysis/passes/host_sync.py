"""host-sync — round loops sync to host only at sanctioned boundaries.

PR 2 removed the per-round barrier: rounds chain on device, and the
host reads back (``float(loss)``, ``device_get``, ``block_until_ready``)
only under the eval/checkpoint/host-agg/FedNova guards.  This pass scans
the round-loop modules for implicit device->host transfers and flags any
that sit outside a sanctioned region.

Sanctioned = an ancestor ``if``/ternary whose condition mentions one of
the sync-gate names (``eval_round``, ``_sync_each_round``, ``_host_agg``,
``fednova``, ``should_save``, ...), or an enclosing function that IS a
sync site by role (eval/test/checkpoint/save/finish/close/report).  A
deliberate sync anywhere else takes a ``# graft: allow(host-sync): why``.

Sync constructs recognized: ``.item()``, ``jax.device_get``,
``[jax.]block_until_ready``, ``np.asarray``/``np.array`` on non-literal
arguments, and ``float()``/``int()`` applied to a name bound from a
call of a jitted-program binding in the same function.
"""
from __future__ import annotations

import ast
import re
from typing import List, Set

from fedml_tpu.analysis.core import (
    Finding,
    Repo,
    SourceFile,
    call_name,
    dotted,
    enclosing_function,
    names_in,
)

PASS_ID = "host-sync"

ROUND_LOOP_PATTERNS = (
    re.compile(r"^fedml_tpu/simulation/sp/[^/]+\.py$"),
    re.compile(r"^fedml_tpu/simulation/parallel/mesh_simulator\.py$"),
    re.compile(r"^fedml_tpu/hierarchy/runner\.py$"),
    re.compile(r"^fedml_tpu/cross_silo/server/[^/]+\.py$"),
    re.compile(r"^fedml_tpu/cross_silo/client/[^/]+\.py$"),
)

# names whose presence in a guarding condition marks the branch as a
# sanctioned sync region (the PR 2 gates plus their later siblings)
_GUARD_HINTS = ("eval", "sync", "host_agg", "fednova", "checkpoint",
                "should_save", "ckpt", "rejoin", "finish", "final")
# functions that ARE sanctioned sync sites by role
_FUNC_HINTS = re.compile(
    r"(eval|test|checkpoint|save|finish|close|report|metric|summary|"
    r"ckpt|aggregate_host|digest)", re.I)

# a jitted-program binding: assignments from jax.jit/wrap_jit give the
# names whose call results are device arrays (see donation pass); the
# conservative name shapes below catch the repo's conventions without
# needing whole-program type inference
_PROGRAM_BINDING = re.compile(
    r"(^|\.)_?(round_fn|train_step|eval_step|step|program|fused|"
    r"local_train|evaluate)\w*$")


def _is_round_loop_file(rel: str) -> bool:
    return any(p.match(rel) for p in ROUND_LOOP_PATTERNS)


def _sanctioned(file: SourceFile, node: ast.AST) -> bool:
    for anc in file.ancestors(node):
        if isinstance(anc, (ast.If, ast.While)):
            if any(h in ast.unparse(anc.test).lower() for h in _GUARD_HINTS):
                return True
        elif isinstance(anc, ast.IfExp):
            if any(h in ast.unparse(anc.test).lower() for h in _GUARD_HINTS):
                return True
        elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _FUNC_HINTS.search(anc.name):
                return True
    return False


def _device_names(file: SourceFile, fn: ast.AST) -> Set[str]:
    """Names in ``fn`` bound (possibly via tuple unpack) from a call of
    a jitted-program binding — their values live on device."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        target_name = dotted(value.func)
        if target_name is None or not _PROGRAM_BINDING.search(target_name):
            continue
        for t in node.targets:
            # bare names and tuple unpacks only — an Attribute target's
            # base ('self') is not itself a device value
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    if isinstance(e, ast.Name):
                        out.add(e.id)
    return out


def _literal_arg(arg: ast.AST) -> bool:
    return isinstance(arg, (ast.List, ast.Tuple, ast.Dict, ast.Set,
                            ast.Constant, ast.ListComp, ast.GeneratorExp))


def _check_file(file: SourceFile, findings: List[Finding]) -> None:
    tree = file.tree
    if tree is None:
        return

    def flag(node: ast.AST, desc: str) -> None:
        if _sanctioned(file, node):
            return
        findings.append(Finding(
            PASS_ID, file.rel, node.lineno,
            f"unsanctioned device->host sync: {desc} (round loops sync "
            "only at eval/checkpoint/host-agg boundaries)"))

    device_cache = {}

    def device_names_for(node: ast.AST) -> Set[str]:
        fn = enclosing_function(file, node)
        if fn is None:
            return set()
        if id(fn) not in device_cache:
            device_cache[id(fn)] = _device_names(file, fn)
        return device_cache[id(fn)]

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name is None:
            # `expr().item()` chains: the base is an expression but the
            # trailing sync method still transfers
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "item" and not node.args:
                    flag(node, ".item() on an expression")
                elif node.func.attr == "block_until_ready":
                    flag(node, ".block_until_ready() on an expression")
            continue
        parts = name.split(".")
        if parts[-1] == "item" and not node.args:
            flag(node, f"{name}()")
        elif name in ("jax.device_get", "device_get"):
            flag(node, f"{name}(...)")
        elif parts[-1] == "block_until_ready":
            flag(node, f"{name}()")
        elif name in ("np.asarray", "np.array", "numpy.asarray",
                      "numpy.array", "onp.asarray", "onp.array"):
            if node.args and not _literal_arg(node.args[0]):
                # host materialization of an already-host value is fine;
                # flag only when the argument mentions a device binding
                touched = names_in(node.args[0]) & device_names_for(node)
                if touched:
                    flag(node, f"{name}({sorted(touched)[0]}...)")
        elif name in ("float", "int") and node.args:
            touched = names_in(node.args[0]) & device_names_for(node)
            if touched:
                flag(node, f"{name}() on device value "
                           f"'{sorted(touched)[0]}'")


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for file in repo.package_files():
        if _is_round_loop_file(file.rel):
            _check_file(file, findings)
    return findings
