"""jit-purity — bodies reachable from jit/wrap_jit sites must stay pure.

A "jit root" is any function handed to ``jax.jit`` (decorator, partial
decorator, direct call, or as the program inside ``telemetry.wrap_jit``).
From each root we walk the bare-name call graph (same module, plus one
``from fedml_tpu.x import y`` hop) and flag, anywhere in a reachable
body:

* host APIs — ``time.*``, ``logging.*`` / ``logger.*`` calls,
  ``print``/``open``/``input``, module-level RNG (``random.*``,
  ``np.random.*`` — randomness must come from threaded PRNG keys);
* sync forcers — ``.item()``, ``.block_until_ready()``,
  ``jax.device_get``, ``np.asarray``/``np.array``, and
  ``float()``/``int()``/``bool()`` applied to a non-static parameter of
  the root.

Trace-time-only host work is still a finding: the convention these
programs live by is that a jitted body re-traces bit-identically, and a
host call inside one is either dead weight re-run per compile or a
silent impurity.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from fedml_tpu.analysis.core import (
    Finding,
    Repo,
    SourceFile,
    call_name,
    dotted,
    import_map,
    names_in,
)

PASS_ID = "jit-purity"

_HOST_MODULES = ("time", "logging", "socket", "requests", "subprocess")
_LOGGER_NAMES = {"logger", "log", "_logger", "_log"}
_LOGGER_METHODS = {"debug", "info", "warning", "error", "exception",
                   "critical", "log"}
_MAX_DEPTH = 6


def _resolve_base(file: SourceFile, name: str,
                  imports: Dict[str, Tuple[str, Optional[str]]]) -> str:
    """Map an imported alias back to the real module path for matching
    (``onp.random.rand`` -> ``numpy.random.rand``)."""
    head, _, rest = name.partition(".")
    entry = imports.get(head)
    if entry is None:
        return name
    module, orig = entry
    real = module if orig is None else f"{module}.{orig}"
    return f"{real}.{rest}" if rest else real


def _static_argnums(call: ast.Call) -> Set[int]:
    out: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.add(e.value)
    return out


def _static_argnames(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        out.add(e.value)
    return out


class _Root:
    """One jit root: the function node, where it was registered, and
    which of its parameters are static (python-level, sync-free)."""

    def __init__(self, file: SourceFile, node: ast.AST, site: str,
                 static_nums: Set[int], static_names: Set[str]):
        self.file = file
        self.node = node  # FunctionDef | Lambda
        self.site = site
        params: List[str] = []
        args = node.args
        for a in list(args.posonlyargs) + list(args.args):
            params.append(a.arg)
        traced = [p for i, p in enumerate(params)
                  if i not in static_nums and p not in static_names]
        self.traced_params: Set[str] = set(traced)
        self.name = getattr(node, "name", "<lambda>")


def _jit_call_target(call: ast.Call) -> Optional[ast.Call]:
    """Return the call node when ``call`` IS a jit application —
    ``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``."""
    name = call_name(call)
    if name in ("jax.jit", "jit"):
        return call
    if name in ("functools.partial", "partial") and call.args:
        inner = call.args[0]
        if isinstance(inner, (ast.Name, ast.Attribute)) \
                and dotted(inner) in ("jax.jit", "jit"):
            return call
    return None


class _Ctx:
    """Per-run memo of each file's function index and import map — the
    Repo parses once; this keeps the passes from re-walking trees once
    per (root, body) pair."""

    def __init__(self, repo: Repo):
        self.repo = repo
        self._defs: Dict[str, Dict[str, List[ast.AST]]] = {}
        self._imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}

    def defs(self, file: SourceFile) -> Dict[str, List[ast.AST]]:
        if file.rel not in self._defs:
            index: Dict[str, List[ast.AST]] = {}
            if file.tree is not None:
                for n in ast.walk(file.tree):
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        index.setdefault(n.name, []).append(n)
            self._defs[file.rel] = index
        return self._defs[file.rel]

    def imports(self, file: SourceFile):
        if file.rel not in self._imports:
            self._imports[file.rel] = import_map(file)
        return self._imports[file.rel]


def _collect_roots(ctx: _Ctx, file: SourceFile) -> List[_Root]:
    tree = file.tree
    if tree is None:
        return []
    roots: List[_Root] = []
    defs = ctx.defs(file)

    def resolve(name: str) -> Optional[ast.AST]:
        cands = defs.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def add(fn_expr: ast.AST, site: str, jit_call: Optional[ast.Call],
            skip_if_decorated: bool = False):
        nums = _static_argnums(jit_call) if jit_call is not None else set()
        names = _static_argnames(jit_call) if jit_call is not None else set()
        if isinstance(fn_expr, ast.Lambda):
            roots.append(_Root(file, fn_expr, site, nums, names))
        elif isinstance(fn_expr, ast.Name):
            target = resolve(fn_expr.id)
            if target is None:
                return
            # a def already jitted by decorator registers via the
            # decorator path WITH its static argnums — re-adding it from
            # the wrap_jit site would lose them
            if skip_if_decorated and any(
                    (isinstance(d, (ast.Name, ast.Attribute))
                     and dotted(d) in ("jax.jit", "jit"))
                    or (isinstance(d, ast.Call)
                        and _jit_call_target(d) is not None)
                    for d in getattr(target, "decorator_list", [])):
                return
            roots.append(_Root(file, target, site, nums, names))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, (ast.Name, ast.Attribute)):
                    if dotted(dec) in ("jax.jit", "jit"):
                        roots.append(_Root(
                            file, node, f"@jit {file.rel}", set(), set()))
                elif isinstance(dec, ast.Call):
                    jc = _jit_call_target(dec)
                    if jc is not None:
                        roots.append(_Root(
                            file, node, f"@jit {file.rel}",
                            _static_argnums(jc), _static_argnames(jc)))
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if name in ("jax.jit", "jit") and node.args:
                add(node.args[0], f"jax.jit {file.rel}", node)
            elif name is not None and name.split(".")[-1] in (
                    "wrap_jit", "_wrap_jit") and len(node.args) >= 2:
                inner = node.args[1]
                if isinstance(inner, ast.Call):
                    continue  # jax.jit(...) inner call handled above
                # wrap_jit's own static_argnums kwarg mirrors the jit's
                add(inner, f"wrap_jit {file.rel}", node,
                    skip_if_decorated=True)
    return roots


def _reachable(ctx: _Ctx, root: _Root):
    """Yield ``(file, body_node, depth)`` for the root body and every
    function reachable from it by resolvable bare-name calls."""
    seen: Set[Tuple[str, int]] = set()
    queue: List[Tuple[SourceFile, ast.AST, int]] = [(root.file, root.node, 0)]
    while queue:
        file, node, depth = queue.pop()
        key = (file.rel, node.lineno)
        if key in seen:
            continue
        seen.add(key)
        yield file, node, depth
        if depth >= _MAX_DEPTH:
            continue
        imports = ctx.imports(file)
        defs = ctx.defs(file)
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            if not isinstance(call.func, ast.Name):
                continue
            fname = call.func.id
            cands = defs.get(fname, [])
            if len(cands) == 1:
                queue.append((file, cands[0], depth + 1))
                continue
            entry = imports.get(fname)
            if entry is not None and entry[1] is not None \
                    and entry[0].startswith("fedml_tpu"):
                target_file = ctx.repo.module(entry[0])
                if target_file is not None and target_file.tree is not None:
                    for n in target_file.tree.body:
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
                                and n.name == entry[1]:
                            queue.append((target_file, n, depth + 1))


def _check_body(ctx: _Ctx, root: _Root, file: SourceFile, body: ast.AST,
                is_root_body: bool, findings: List[Finding]) -> None:
    imports = ctx.imports(file)
    prog = root.name

    def flag(node: ast.AST, what: str) -> None:
        findings.append(Finding(
            PASS_ID, file.rel, node.lineno,
            f"jitted program '{prog}': {what}"))

    # nested defs and lambdas are traced as part of the program (loss
    # closures under jax.grad etc.) — walk everything under the body
    for node in ast.walk(body):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name is None:
            # attribute-of-call (`x.sum().item()`): the chain base is an
            # expression, but the trailing sync methods still apply
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "item" and not node.args:
                    flag(node, ".item() forces a device->host sync")
                elif node.func.attr == "block_until_ready":
                    flag(node, ".block_until_ready() forces a host sync")
            continue
        real = _resolve_base(file, name, imports)
        head = real.split(".")[0]
        last = real.split(".")[-1]
        if real.startswith("jax."):
            if real == "jax.device_get":
                flag(node, "jax.device_get forces a device->host sync")
            elif real == "jax.block_until_ready":
                flag(node, "jax.block_until_ready forces a host sync")
            continue
        if head in _HOST_MODULES:
            flag(node, f"{real}() is a host API call")
            continue
        if real == "random" or real.startswith("random."):
            flag(node, f"{real}() draws from module-level RNG "
                       "(use threaded jax.random keys)")
            continue
        if real.startswith("numpy.random."):
            flag(node, f"{name}() draws from module-level numpy RNG "
                       "(use threaded jax.random keys)")
            continue
        if real in ("numpy.asarray", "numpy.array"):
            flag(node, f"{name}() materializes a host array "
                       "(forces a sync on traced values)")
            continue
        if name in ("print", "input"):
            flag(node, f"{name}() is host I/O")
            continue
        if name == "open":
            flag(node, "open() is host I/O")
            continue
        parts = name.split(".")
        if len(parts) == 2 and parts[0] in _LOGGER_NAMES \
                and parts[1] in _LOGGER_METHODS:
            flag(node, f"{name}() logs from a jit-pure body")
            continue
        if parts[-1] == "item" and not node.args and not node.keywords:
            flag(node, f"{name}() forces a device->host sync")
            continue
        if parts[-1] == "block_until_ready":
            flag(node, f"{name}() forces a host sync")
            continue
        if name in ("float", "int", "bool") and is_root_body and node.args:
            touched = names_in(node.args[0]) & root.traced_params
            if touched:
                flag(node, f"{name}() on traced value "
                           f"'{sorted(touched)[0]}' forces a host sync")


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    ctx = _Ctx(repo)
    for file in repo.package_files():
        for root in _collect_roots(ctx, file):
            for body_file, body, depth in _reachable(ctx, root):
                _check_body(ctx, root, body_file, body, depth == 0,
                            findings)
    # duplicate roots (e.g. wrap_jit(name, jax.jit(fn))) and shared
    # helpers produce identical findings — dedup on full identity
    out, seen = [], set()
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.message)):
        k = (f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
