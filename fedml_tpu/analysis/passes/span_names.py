"""span-names — the telemetry taxonomy lint, as an analysis pass.

This is ``tools/check_span_names.py`` migrated onto the shared core:
the scanning/normalization/shape rules are byte-identical (the tool is
now a shim over this module — ``collect``/``check``/``normalize`` keep
their signatures and output so the existing tier-1 wiring and
``tests/test_telemetry.py`` run unmodified), and ``run(repo)`` adapts
the same checks to :class:`~fedml_tpu.analysis.core.Repo` findings,
reusing the already-loaded sources.
"""
from __future__ import annotations

import os
import re
from typing import List, Tuple

from fedml_tpu.analysis.core import Finding, Repo

PASS_ID = "span-names"

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
ROOTS = ("fedml_tpu",)

_SPAN_CALL = re.compile(
    r"\.(?:span|begin)\(\s*(?:\n\s*)?(f?)\"([^\"]+)\"")
_METRIC_CALL = re.compile(
    r"\.(counter|gauge|histogram)\(\s*(?:\n\s*)?(f?)\"([^\"]+)\"")
_SEGMENT = re.compile(r"^(?:[a-z0-9_]+|<[a-z_]+>)$")
_ROUND_SHAPE = re.compile(
    r"^round/<v>(?:/client/<v>)?/[a-z0-9_]+$")
# compression spans are exactly the two codec phases — anything else
# under compress/ is taxonomy drift
_COMPRESS_SHAPE = re.compile(r"^compress/(?:encode|decode)$")
# run-health namespaces: one segment after the prefix, per-entity
# dimensions (client id, phase) ride LABELS, never the name — and memory
# readings are instantaneous by definition, so mem/* must be gauges
_MEM_SHAPE = re.compile(r"^mem/[a-z0-9_]+$")
_HEALTH_SHAPE = re.compile(r"^health/[a-z0-9_]+$")
# resilience namespace: same one-segment rule (client ids, chaos actions
# and backends are labels); counters or gauges only — retry/reconnect/
# quorum signals are occurrence counts, not latency distributions
_RESILIENCE_SHAPE = re.compile(r"^resilience/[a-z0-9_]+$")
# crash-anywhere durability: the journal/restart signals are append and
# replay occurrence counts — COUNTERS only. A gauge here would let a
# restart silently zero the evidence the doctor's recovery section
# reads, and a histogram breaks the bounded live-frame contract.
_DURABILITY_SHAPE = re.compile(
    r"^resilience/(?:journal_[a-z0-9_]+|restarts|checkpoints_pruned)$")
# hierarchical-federation namespace: tier/<depth>/<signal> — exactly one
# interpolated tier depth then one signal segment (node/client ids are
# event fields, never name segments); counters or gauges only
_TIER_SHAPE = re.compile(r"^tier/<v>/[a-z0-9_]+$")
# live serving plane: serve/* spans are exactly the three swap phases
# (staging, the flip, the publisher's encode+send); serving/* metrics are
# one signal segment after the prefix — the endpoint id rides a label
_SERVE_SPAN_SHAPE = re.compile(r"^serve/(?:stage|swap|publish)$")
_SERVING_SHAPE = re.compile(r"^serving/[a-z0-9_]+$")
# request lifecycle: req/* spans are exactly the per-request stages the
# serving engine materializes at retirement (the whole request, its
# admission queue wait, prefill, decode, and a swap-stall sub-span
# pinned to the stalled stream) — span-only; the request's aggregate
# metrics live under serving/* (ttft_ms, tpot_ms, tokens_per_s)
_REQ_SPAN_SHAPE = re.compile(r"^req/(?:request|queue|prefill|decode|stall)$")
# live telemetry plane: live/* is the stream/collector meta-namespace
# (frames, seq gaps, alerts, scrapes) — one signal segment; node/job/rule
# dimensions ride labels. Metric-only: the plane never opens spans.
_LIVE_SHAPE = re.compile(r"^live/[a-z0-9_]+$")
# secure aggregation: secagg/* is metric-only (the masked encode/decode
# phases ride the existing compress/* spans); one signal segment, and
# counters only — every secagg signal is a protocol occurrence count
_SECAGG_SHAPE = re.compile(r"^secagg/[a-z0-9_]+$")
# job plane: sched/* is the supervision/preemption namespace — metric
# only, one signal segment (run/job/node ids ride event fields in
# sched_event records, never name segments); counters or gauges only —
# restart/preempt/reschedule signals are occurrence counts, queue depths
# are levels, neither is a latency distribution (MTTR is a bench metric,
# not a histogram)
_SCHED_SHAPE = re.compile(r"^sched/[a-z0-9_]+$")
# update integrity: integrity/* is the containment namespace (screen
# drops, quarantine, rollbacks, non-finite wire refusals) — metric-only
# (the screen/robust-agg programs live in the catalog as
# integrity/<name> PROGRAM names, not spans), one signal segment
# (clients/rounds/reasons ride integrity_event fields); counters or
# gauges only — screen/rollback signals are occurrence counts, the
# quarantine population is a level, neither is a distribution
_INTEGRITY_SHAPE = re.compile(r"^integrity/[a-z0-9_]+$")
# performance attribution: profile/* is the program-catalog namespace —
# metric-only (catalog programs are NOT spans; their names live in the
# `program` label), one signal segment, counter/gauge only (flops/bytes/
# HBM readings are levels, capture/recompile signals are counts — a
# histogram here would violate the bounded-frame live-plane contract)
_PROFILE_SHAPE = re.compile(r"^profile/[a-z0-9_]+$")
# multichip sharding: shard/* is the per-shard layout namespace (shard
# counts, per-shard HBM, depth-reduction occurrences on the virtual
# mesh) — metric-only (program names ride the `program` label exactly
# as profile/*), one signal segment, counter/gauge only — shard counts
# and per-shard byte plans are levels, guard trips are occurrence
# counts, neither is a distribution
_SHARD_SHAPE = re.compile(r"^shard/[a-z0-9_]+$")
# quantized residency: quant/* is the 4-bit/int8 base-weight namespace
# (packed-base bytes, packed-leaf counts) — metric-only (the pack/
# dequant-matmul programs live in the catalog as quant/<name> PROGRAM
# names, not spans), one signal segment (formats/blocks ride labels);
# counters or gauges only — packed footprints are levels, pack events
# are occurrence counts, neither is a distribution
_QUANT_SHAPE = re.compile(r"^quant/[a-z0-9_]+$")
# federated analytics: fa/* is the sketch-round namespace (rounds
# closed, quorum closes, deadline fires, stale/screened submissions,
# aborts, heavy-hitter recall, the accounted DP epsilon) — metric-only
# (an analytics round's spans keep their round/* names; the fused merge
# keeps compress/*), one signal segment (task/tier ride labels);
# counters or gauges only — round/drop signals are occurrence counts,
# recall/epsilon readings are levels, neither is a distribution
_FA_SHAPE = re.compile(r"^fa/[a-z0-9_]+$")
# causal tracing: tracepath/* is the span-stream/critical-path meta-
# namespace (frames, merged records, seq gaps, the latest round's
# critical phase/share) — metric-only (the traced spans themselves keep
# their own round/*, comm/* names), one signal segment (node/job ride
# labels); counters or gauges only — frame/record signals are occurrence
# counts, critical-phase readings are levels, and a histogram would
# break the bounded live-frame contract
_TRACEPATH_SHAPE = re.compile(r"^tracepath/[a-z0-9_]+$")


def normalize(literal: str, is_fstring: bool) -> str:
    if is_fstring:
        literal = re.sub(r"\{[^}]*\}", "<v>", literal)
    # literal numeric ids (docstring examples, fixed round 0 spans) are the
    # runtime shape of an interpolated id — same placeholder
    return re.sub(r"(?<=/)\d+(?=/|$)", "<v>", literal)


def _scan(path: str, src: str, out: list) -> None:
    for m in _SPAN_CALL.finditer(src):
        lineno = src[: m.start()].count("\n") + 1
        out.append((path, lineno, "span",
                    normalize(m.group(2), bool(m.group(1)))))
    for m in _METRIC_CALL.finditer(src):
        lineno = src[: m.start()].count("\n") + 1
        out.append((path, lineno, m.group(1),
                    normalize(m.group(3), bool(m.group(2)))))


def iter_py():
    for root in ROOTS:
        for base, dirs, files in os.walk(os.path.join(REPO, root)):
            dirs[:] = [d for d in dirs if d not in ("__pycache__", ".git")]
            for fn in files:
                if fn.endswith(".py"):
                    yield os.path.join(base, fn)


def collect():
    """[(path, lineno, kind, name)] for every instrumented literal."""
    out = []
    for path in sorted(iter_py()):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        _scan(path, src, out)
    return out


def _check_structured(entries) -> List[Tuple[str, int, str]]:
    """[(relpath, lineno, message)] — the rule engine behind check()."""
    problems: List[Tuple[str, int, str]] = []
    metric_kinds = {}
    for path, lineno, kind, name in entries:
        rel = os.path.relpath(path, REPO) if os.path.isabs(path) else path
        where = f"{rel}:{lineno}"

        def bad(msg: str, rel=rel, lineno=lineno) -> None:
            problems.append((rel, lineno, msg))

        segments = name.split("/")
        if not all(_SEGMENT.match(s) for s in segments):
            bad(f"{kind} name {name!r} violates the taxonomy "
                "(lowercase [a-z0-9_] segments joined by '/')")
            continue
        if kind == "span" and name.startswith("round/"):
            if not _ROUND_SHAPE.match(name):
                bad(f"span {name!r} must follow "
                    "round/<n>[/client/<id>]/<phase>")
        if kind == "span" and name.startswith("compress/"):
            if not _COMPRESS_SHAPE.match(name):
                bad(f"span {name!r} must be compress/encode "
                    "or compress/decode")
        if kind == "span" and name.startswith(
                ("mem/", "health/", "resilience/", "tier/", "live/",
                 "secagg/", "profile/", "sched/", "integrity/",
                 "tracepath/", "shard/", "quant/", "fa/")):
            bad(f"{name!r} — mem/, health/, resilience/, tier/, "
                "live/, secagg/, profile/, sched/, integrity/, "
                "tracepath/, shard/, quant/ and fa/ are metric "
                "namespaces, not span names")
        if kind == "span" and name.startswith("serve/"):
            if not _SERVE_SPAN_SHAPE.match(name):
                bad(f"span {name!r} must be serve/stage, "
                    "serve/swap or serve/publish")
        if kind != "span" and name.startswith("serve/"):
            bad(f"{kind} {name!r} — serve/ is the live-plane "
                "span namespace; its metrics live under serving/")
        if kind == "span" and name.startswith("req/"):
            if not _REQ_SPAN_SHAPE.match(name):
                bad(f"span {name!r} must be req/request, req/queue, "
                    "req/prefill, req/decode or req/stall")
        if kind != "span" and name.startswith("req/"):
            bad(f"{kind} {name!r} — req/ is the request-lifecycle "
                "span namespace; its aggregate metrics live under "
                "serving/")
        if kind != "span" and name.startswith("serving/"):
            if not _SERVING_SHAPE.match(name):
                bad(f"{kind} {name!r} must be serving/<signal> "
                    "(one segment; the endpoint id rides a label)")
        if kind != "span" and name.startswith("mem/"):
            if kind != "gauge":
                bad(f"{kind} {name!r} — mem/* readings are "
                    "instantaneous and must be gauges")
            elif not _MEM_SHAPE.match(name):
                bad(f"gauge {name!r} must be mem/<reading> "
                    "(one segment; device/phase go in labels)")
        if kind != "span" and name.startswith("health/"):
            if not _HEALTH_SHAPE.match(name):
                bad(f"{kind} {name!r} must be health/<signal> "
                    "(one segment; client ids go in labels)")
        if kind != "span" and name.startswith("resilience/"):
            if not _RESILIENCE_SHAPE.match(name):
                bad(f"{kind} {name!r} must be resilience/<signal> "
                    "(one segment; clients/actions/backends go in labels)")
            elif kind == "histogram":
                bad(f"{kind} {name!r} — resilience/* signals are "
                    "occurrence counts (counter) or levels (gauge), not "
                    "histograms")
            elif _DURABILITY_SHAPE.match(name) and kind != "counter":
                bad(f"{kind} {name!r} — durability journal/restart "
                    "signals are append/replay occurrence counts; "
                    "counters only")
        if kind != "span" and name.startswith("tier/"):
            if not _TIER_SHAPE.match(name):
                bad(f"{kind} {name!r} must be tier/<depth>/"
                    "<signal> (one depth segment, one signal segment; "
                    "node/client ids ride event fields)")
            elif kind == "histogram":
                bad(f"{kind} {name!r} — tier/* signals are "
                    "occurrence counts (counter) or levels (gauge), not "
                    "histograms")
        if kind != "span" and name.startswith("live/"):
            if not _LIVE_SHAPE.match(name):
                bad(f"{kind} {name!r} must be live/<signal> "
                    "(one segment; node/job/rule dimensions ride labels)")
        if kind != "span" and name.startswith("profile/"):
            if not _PROFILE_SHAPE.match(name):
                bad(f"{kind} {name!r} must be profile/<signal> "
                    "(one segment; program names and capture triggers "
                    "ride labels)")
            elif kind == "histogram":
                bad(f"{kind} {name!r} — profile/* signals are "
                    "levels (gauge) or occurrence counts (counter), not "
                    "histograms")
        if kind != "span" and name.startswith("shard/"):
            if not _SHARD_SHAPE.match(name):
                bad(f"{kind} {name!r} must be shard/<signal> "
                    "(one segment; program names and mesh axes ride "
                    "labels)")
            elif kind == "histogram":
                bad(f"{kind} {name!r} — shard/* signals are "
                    "levels (gauge) or occurrence counts (counter), not "
                    "histograms")
        if kind != "span" and name.startswith("integrity/"):
            if not _INTEGRITY_SHAPE.match(name):
                bad(f"{kind} {name!r} must be integrity/<signal> "
                    "(one segment; clients/rounds/reasons ride "
                    "integrity_event fields)")
            elif kind == "histogram":
                bad(f"{kind} {name!r} — integrity/* signals are "
                    "occurrence counts (counter) or levels (gauge), not "
                    "histograms")
        if kind != "span" and name.startswith("sched/"):
            if not _SCHED_SHAPE.match(name):
                bad(f"{kind} {name!r} must be sched/<signal> "
                    "(one segment; run/job/node ids ride sched_event "
                    "fields)")
            elif kind == "histogram":
                bad(f"{kind} {name!r} — sched/* signals are "
                    "occurrence counts (counter) or levels (gauge), not "
                    "histograms")
        if kind != "span" and name.startswith("quant/"):
            if not _QUANT_SHAPE.match(name):
                bad(f"{kind} {name!r} must be quant/<signal> "
                    "(one segment; formats and block sizes ride labels)")
            elif kind == "histogram":
                bad(f"{kind} {name!r} — quant/* signals are "
                    "levels (gauge) or occurrence counts (counter), not "
                    "histograms")
        if kind != "span" and name.startswith("fa/"):
            if not _FA_SHAPE.match(name):
                bad(f"{kind} {name!r} must be fa/<signal> "
                    "(one segment; task/tier dimensions ride labels)")
            elif kind == "histogram":
                bad(f"{kind} {name!r} — fa/* signals are occurrence "
                    "counts (counter) or levels (gauge), not "
                    "histograms")
        if kind != "span" and name.startswith("tracepath/"):
            if not _TRACEPATH_SHAPE.match(name):
                bad(f"{kind} {name!r} must be tracepath/<signal> "
                    "(one segment; node/job dimensions ride labels)")
            elif kind == "histogram":
                bad(f"{kind} {name!r} — tracepath/* signals are "
                    "occurrence counts (counter) or levels (gauge), not "
                    "histograms")
        if kind != "span" and name.startswith("secagg/"):
            if not _SECAGG_SHAPE.match(name):
                bad(f"{kind} {name!r} must be secagg/<signal> "
                    "(one segment; rounds/clients/tiers ride event "
                    "fields)")
            elif kind != "counter":
                bad(f"{kind} {name!r} — secagg/* signals are "
                    "protocol occurrence counts; counters only")
        if kind != "span":
            prev = metric_kinds.get(name)
            if prev is not None and prev[0] != kind:
                bad(f"metric {name!r} registered as {kind} but "
                    f"already a {prev[0]} at {prev[1]}")
            else:
                metric_kinds.setdefault(name, (kind, where))
    return problems


def check(entries):
    """Historical API: problem strings, ``path:line: message``."""
    return [f"{rel}:{lineno}: {msg}"
            for rel, lineno, msg in _check_structured(entries)]


_DUP_REF = re.compile(r"(registered as \w+ but already a \w+ at .+):\d+$")


def run(repo: Repo) -> List[Finding]:
    # feed repo-relative paths (file.rel) so findings carry the same
    # paths the runner's allow/baseline/--changed plumbing keys on,
    # whatever --root the analysis runs against
    entries: list = []
    for file in repo.package_files():
        _scan(file.rel, file.src, entries)
    # the duplicate-kind message embeds the first registration's
    # `path:line` (kept byte-identical in the shim's check()); baseline
    # keys are line-number-free by contract, so the Finding variant
    # drops the line
    return [Finding(PASS_ID, rel, lineno, _DUP_REF.sub(r"\1", msg))
            for rel, lineno, msg in _check_structured(entries)]


def main() -> int:
    entries = collect()
    problems = check(entries)
    for p in problems:
        print(p)  # noqa: T201 (CLI output)
    if problems:
        print(f"\n{len(problems)} problem(s)")  # noqa: T201 (CLI output)
        return 1
    print(f"span-name lint clean ({len(entries)} instrumented names)")  # noqa: T201 (CLI output)
    return 0
