"""lint — the in-tree ruff stand-in, as an analysis pass.

``tools/lint.py`` migrated onto the shared core (the tool is now a shim
over this module; ``check_file``/``main`` keep their signatures, output
and exit codes).  Checks are unchanged:

  F401  unused module-level import (skipped in __init__.py re-exports)
  E722  bare except
  B006  mutable default argument
  W291  trailing whitespace
  E501  line longer than 100 chars
  T201  print() in library code (CLI/tools/tests exempt)

``# noqa`` on the offending line suppresses any check (kept for
compatibility; new waivers should prefer ``# graft: allow(lint): why``).
``run(repo)`` reuses the repo's already-parsed ASTs instead of re-reading
every file.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import List, Optional, Tuple

from fedml_tpu.analysis.core import Finding, Repo

PASS_ID = "lint"

MAX_LINE = 100
LIB_DIRS = ("fedml_tpu",)
PRINT_EXEMPT = ("cli.py", "env_collect.py")


def iter_py(root):
    for base, dirs, files in os.walk(root):
        dirs[:] = [d for d in dirs if d not in ("__pycache__", ".git")]
        for fn in files:
            if fn.endswith(".py"):
                yield os.path.join(base, fn)


def imported_names(node):
    if isinstance(node, ast.Import):
        for a in node.names:
            yield (a.asname or a.name.split(".")[0]), node.lineno
    elif isinstance(node, ast.ImportFrom):
        for a in node.names:
            if a.name != "*":
                yield (a.asname or a.name), node.lineno


def _check_source(path: str, src: str, tree: Optional[ast.Module],
                  syntax_error: Optional[SyntaxError]
                  ) -> List[Tuple[int, str]]:
    problems: List[Tuple[int, str]] = []
    lines = src.splitlines()
    noqa = {i + 1 for i, l in enumerate(lines) if "# noqa" in l}

    for i, line in enumerate(lines, 1):
        if i in noqa:
            continue
        if line.rstrip("\n") != line.rstrip():
            problems.append((i, "W291 trailing whitespace"))
        if len(line) > MAX_LINE:
            problems.append((i, f"E501 line too long ({len(line)})"))

    if tree is None:
        if syntax_error is not None:
            problems.append((syntax_error.lineno or 0,
                             f"E999 syntax error: {syntax_error.msg}"))
        return problems

    # F401: module-level imports never referenced
    if os.path.basename(path) != "__init__.py":
        imports = {}
        for node in tree.body:
            if (isinstance(node, ast.ImportFrom)
                    and node.module == "__future__"):
                continue
            for name, lineno in imported_names(node):
                imports[name] = lineno
        used = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                n = node
                while isinstance(n, ast.Attribute):
                    n = n.value
                if isinstance(n, ast.Name):
                    used.add(n.id)
        # names in __all__ / docstring-style re-export count as used
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                used.add(node.value)
        for name, lineno in imports.items():
            if name not in used and lineno not in noqa:
                problems.append((lineno, f"F401 unused import '{name}'"))

    in_lib = any(path.startswith(d + os.sep) or f"/{d}/" in path
                 for d in LIB_DIRS)
    for node in ast.walk(tree):
        lineno = getattr(node, "lineno", 0)
        if lineno in noqa:
            continue
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append((lineno, "E722 bare except"))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in node.args.defaults + node.args.kw_defaults:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    problems.append(
                        (default.lineno, "B006 mutable default argument"))
        if (in_lib and isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and os.path.basename(path) not in PRINT_EXEMPT):
            problems.append((lineno, "T201 print() in library code"))
    return problems


def check_file(path):
    """Historical API: lint one file from disk."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree: Optional[ast.Module] = ast.parse(src, filename=path)
        err: Optional[SyntaxError] = None
    except SyntaxError as e:
        tree, err = None, e
    return _check_source(path, src, tree, err)


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for file in repo.files:
        for lineno, msg in _check_source(
                file.rel, file.src, file.tree, file.syntax_error):
            findings.append(Finding(PASS_ID, file.rel, lineno, msg))
    return findings


def main():
    roots = sys.argv[1:] or ["fedml_tpu", "tools", "examples", "bench.py",
                             "__graft_entry__.py"]
    total = 0
    for root in roots:
        paths = [root] if root.endswith(".py") else list(iter_py(root))
        for path in sorted(paths):
            for lineno, msg in check_file(path):
                print(f"{path}:{lineno}: {msg}")  # noqa: T201 (CLI output)
                total += 1
    if total:
        print(f"\n{total} problem(s)")  # noqa: T201 (CLI output)
        return 1
    print("lint clean")  # noqa: T201 (CLI output)
    return 0
