"""message-contract — every sent message type has a registered receiver.

The PR 7 namespace-isolation bug class: a manager sends
``Message(MyMessage.MSG_TYPE_X, ...)`` but no peer ever calls
``register_message_receive_handler(MSG_TYPE_X, ...)`` (or vice versa),
and the message silently rots in an inbox.  We resolve the message-type
expression at every ``Message(...)`` construction and every handler
registration down to its string constant (class attributes and
module-level constants, across ``from x import y``), then flag:

* a type value that is sent somewhere but handled nowhere;
* a type value with a handler that nothing ever sends.

Expressions that do not resolve to a constant (computed types) are
ignored — dynamic protocols own their contracts.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from fedml_tpu.analysis.core import (
    Finding,
    Repo,
    SourceFile,
    call_name,
    import_map,
)

PASS_ID = "message-contract"


def _string_consts(repo: Repo):
    """class_consts[class_name][attr] = value; module_consts[rel][name]
    = value (module-level string assignments)."""
    class_consts: Dict[str, Dict[str, str]] = {}
    module_consts: Dict[str, Dict[str, str]] = {}
    for file in repo.package_files():
        tree = file.tree
        if tree is None:
            continue
        mod: Dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                mod[node.targets[0].id] = node.value.value
        module_consts[file.rel] = mod
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = class_consts.setdefault(node.name, {})
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and isinstance(stmt.value, ast.Constant) \
                        and isinstance(stmt.value.value, str):
                    attrs[stmt.targets[0].id] = stmt.value.value
    return class_consts, module_consts


class _Resolver:
    def __init__(self, repo: Repo):
        self.repo = repo
        self.class_consts, self.module_consts = _string_consts(repo)
        self._aliases: Dict[str, Dict[str, str]] = {}
        self._imports: Dict[str, Dict] = {}

    def _import_map(self, file: SourceFile) -> Dict:
        if file.rel not in self._imports:
            self._imports[file.rel] = import_map(file)
        return self._imports[file.rel]

    def _alias_map(self, file: SourceFile) -> Dict[str, str]:
        """``M = InfMessage`` style local aliases, plus import renames."""
        cached = self._aliases.get(file.rel)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        tree = file.tree
        if tree is not None:
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Name):
                    out[node.targets[0].id] = node.value.id
        for name, (_, orig) in self._import_map(file).items():
            if orig is not None and orig != name:
                out[name] = orig
        self._aliases[file.rel] = out
        return out

    def resolve(self, file: SourceFile, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            cls = expr.value.id
            aliases = self._alias_map(file)
            for _ in range(3):  # follow M = InfMessage chains
                if cls in self.class_consts:
                    break
                nxt = aliases.get(cls)
                if nxt is None or nxt == cls:
                    break
                cls = nxt
            return self.class_consts.get(cls, {}).get(expr.attr)
        if isinstance(expr, ast.Name):
            local = self.module_consts.get(file.rel, {})
            if expr.id in local:
                return local[expr.id]
            entry = self._import_map(file).get(expr.id)
            if entry is not None and entry[1] is not None:
                target = self.repo.module(entry[0])
                if target is not None:
                    return self.module_consts.get(
                        target.rel, {}).get(entry[1])
        return None


def run(repo: Repo) -> List[Finding]:
    resolver = _Resolver(repo)
    # value -> first (path, line) seen, per direction
    sent: Dict[str, Tuple[str, int]] = {}
    handled: Dict[str, Tuple[str, int]] = {}
    for file in repo.package_files():
        tree = file.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = call_name(node)
            if name is None:
                continue
            last = name.split(".")[-1]
            if last == "Message":
                value = resolver.resolve(file, node.args[0])
                if value is not None:
                    sent.setdefault(value, (file.rel, node.lineno))
            elif last == "register_message_receive_handler" \
                    and len(node.args) >= 2:
                value = resolver.resolve(file, node.args[0])
                if value is not None:
                    handled.setdefault(value, (file.rel, node.lineno))
    findings: List[Finding] = []
    for value in sorted(set(sent) - set(handled)):
        path, line = sent[value]
        findings.append(Finding(
            PASS_ID, path, line,
            f"message type '{value}' is sent here but no peer registers "
            "a receive handler for it"))
    for value in sorted(set(handled) - set(sent)):
        path, line = handled[value]
        findings.append(Finding(
            PASS_ID, path, line,
            f"receive handler registered for '{value}' but nothing in "
            "the repo sends that message type"))
    return findings
