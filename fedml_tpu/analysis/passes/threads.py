"""thread-safety — state shared across thread entrypoints is lock-guarded.

Per class we build the set of *thread entrypoints*:

* methods handed to ``threading.Thread(target=...)`` / ``Timer``;
* comm handlers registered via ``register_message_receive_handler``;
* HTTP ``do_*`` methods;
* ``run`` on ``threading.Thread`` subclasses;

plus the pseudo-entrypoint ``<caller>`` for everything reachable from
the owning thread.  Intra-class reachability follows ``self.method()``
calls.  A ``self.*`` attribute written (outside ``__init__``) from two
or more distinct entrypoints, with at least one of those accesses not
under a ``with <lock>:`` block, is a finding — that is exactly the
timer-vs-handler races PRs 4-8 kept fixing by hand.

A helper whose every intra-class call site sits inside a lock block is
treated as lock-held (the ``with self._lock: self._flush()`` pattern).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from fedml_tpu.analysis.core import (
    Finding,
    Repo,
    SourceFile,
    call_name,
    dotted,
    in_lock_block,
)

PASS_ID = "thread-safety"

_DO_METHOD = re.compile(r"^do_[A-Z]+$")
_MAIN = "<caller>"
# attributes that are themselves synchronization/thread handles: writing
# the handle from two entrypoints is the lifecycle pattern (start/stop),
# not a data race the lock discipline covers
_HANDLE_ATTR = re.compile(r"(lock|thread|timer|_cv|cond|event|stop|"
                          r"shutdown|closed|running|finished|done)", re.I)


def _methods(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _thread_entrypoints(cls: ast.ClassDef,
                        methods: Dict[str, ast.AST]) -> Dict[str, str]:
    """entrypoint method name -> how it becomes a thread entrypoint."""
    out: Dict[str, str] = {}
    for name in methods:
        if _DO_METHOD.match(name):
            out[name] = "HTTP handler"
    bases = " ".join(filter(None, (dotted(b) for b in cls.bases)))
    if "Thread" in bases and "run" in methods:
        out["run"] = "Thread.run"
    for m in methods.values():
        for node in ast.walk(m):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            last = name.split(".")[-1]
            target: Optional[ast.AST] = None
            if last in ("Thread", "Timer"):
                for kw in node.keywords:
                    if kw.arg in ("target", "function"):
                        target = kw.value
                if target is None and last == "Timer" and len(node.args) >= 2:
                    target = node.args[1]
                how = f"threading.{last} target"
            elif last == "register_message_receive_handler" \
                    and len(node.args) >= 2:
                target = node.args[1]
                how = "comm handler"
            else:
                continue
            attr = _self_attr(target) if target is not None else None
            if attr is not None and attr in methods:
                out.setdefault(attr, how)
    return out


def _calls_of_self(m: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(m):
        if isinstance(node, ast.Call):
            attr = _self_attr(node.func)
            if attr is not None:
                out.add(attr)
    return out


def _reachable_from(entry: str, call_edges: Dict[str, Set[str]]) -> Set[str]:
    seen: Set[str] = set()
    stack = [entry]
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(call_edges.get(cur, ()))
    return seen


def _lock_held_methods(file: SourceFile, cls: ast.ClassDef,
                       methods: Dict[str, ast.AST]) -> Set[str]:
    """Methods whose every intra-class call site is under a lock."""
    sites: Dict[str, List[bool]] = {}
    for m in methods.values():
        for node in ast.walk(m):
            if isinstance(node, ast.Call):
                attr = _self_attr(node.func)
                if attr in methods:
                    sites.setdefault(attr, []).append(
                        in_lock_block(file, node))
    return {name for name, guards in sites.items()
            if guards and all(guards)}


def _check_class(file: SourceFile, cls: ast.ClassDef,
                 findings: List[Finding]) -> None:
    # BaseHTTPRequestHandler subclasses are instantiated per request —
    # their self.* is never shared between threads (shared state lives
    # on self.server or in closures, which other classes own)
    bases = " ".join(filter(None, (dotted(b) for b in cls.bases)))
    if "RequestHandler" in bases:
        return
    methods = _methods(cls)
    entries = _thread_entrypoints(cls, methods)
    if not entries:
        return
    call_edges = {name: _calls_of_self(m) & set(methods)
                  for name, m in methods.items()}
    lock_held = _lock_held_methods(file, cls, methods)

    # comm handlers all run on the one receive-loop thread — they are a
    # single logical entrypoint, concurrent with timers/HTTP/<caller>
    # but serialized with each other
    label = {entry: ("<comm>" if how == "comm handler" else entry)
             for entry, how in entries.items()}
    how_of = {label[e]: how for e, how in entries.items()}

    # attribute each method to the entrypoints that reach it
    attribution: Dict[str, Set[str]] = {name: set() for name in methods}
    for entry in entries:
        for m in _reachable_from(entry, call_edges):
            attribution[m].add(label[entry])
    thread_reached = {m for m, owners in attribution.items() if owners}
    for name in methods:
        if name.startswith("__"):
            continue
        how = entries.get(name)
        if how is None:
            # public methods are always callable from the owning thread;
            # private helpers only when not exclusively thread-internal
            if not name.startswith("_") or name not in thread_reached:
                attribution[name].add(_MAIN)
        elif not name.startswith("_") and how.startswith("threading."):
            # a PUBLIC Thread/Timer target is dual-role: thread body AND
            # plain API surface (the flush()-as-target pattern).  Comm
            # handlers, do_* and Thread.run are framework-invoked only —
            # public by convention, never called by the owning thread.
            attribution[name].add(_MAIN)
    # <caller>-attributed methods propagate through their call chains
    main_reach: Set[str] = set()
    for name, owners in list(attribution.items()):
        if _MAIN in owners:
            main_reach |= _reachable_from(name, call_edges)
    for m in main_reach:
        if m in attribution:
            attribution[m].add(_MAIN)

    # accesses[attr] = list of (entrypoint, is_write, guarded, node)
    accesses: Dict[str, List[Tuple[str, bool, bool, ast.AST]]] = {}
    for name, m in methods.items():
        if name in ("__init__", "__del__", "__enter__", "__exit__"):
            continue
        owners = attribution.get(name) or set()
        if not owners:
            continue
        body_guarded = name in lock_held
        for node in ast.walk(m):
            attr = _self_attr(node)
            if attr is None or attr in methods:
                continue
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            guarded = body_guarded or in_lock_block(file, node)
            for owner in owners:
                accesses.setdefault(attr, []).append(
                    (owner, is_write, guarded, node))

    for attr in sorted(accesses):
        if _HANDLE_ATTR.search(attr):
            continue
        recs = accesses[attr]
        writers = {owner for owner, is_write, _, _ in recs if is_write}
        if len(writers) < 2:
            continue
        if not (writers - {_MAIN}):
            continue
        # unguarded READS are legal (the idiom is: mutate under the
        # lock, read a just-written snapshot on the owning thread) —
        # the race class PRs 4-8 kept fixing is the unguarded WRITE
        unguarded = [(owner, node)
                     for owner, is_write, guarded, node in recs
                     if is_write and not guarded]
        if not unguarded:
            continue
        unguarded.sort(key=lambda r: r[1].lineno)
        owner, node = unguarded[0]
        names = ", ".join(sorted(
            e if e == _MAIN else f"{e} ({how_of.get(e, '?')})"
            for e in writers))
        findings.append(Finding(
            PASS_ID, file.rel, node.lineno,
            f"{cls.name}.self.{attr} is written from multiple thread "
            f"entrypoints [{names}] with an unguarded write in "
            f"'{owner}' — guard the writes with the instance lock"))


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for file in repo.package_files():
        tree = file.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                _check_class(file, node, findings)
    return findings
