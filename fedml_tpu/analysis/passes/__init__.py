"""Analysis passes — one module per invariant.

Every pass exposes ``PASS_ID`` and ``run(repo) -> list[Finding]``.  The
registry lives in :mod:`fedml_tpu.analysis.runner` so that adding a pass
is: write the module, add it to ``ALL_PASSES``, document it in
``docs/static_analysis.md``.
"""
