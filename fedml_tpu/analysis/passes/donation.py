"""donation — a buffer donated to a jitted program must not be re-read.

We collect every binding assigned from a jit application carrying
``donate_argnums`` (module-level ``_prog = wrap_jit(.., jax.jit(f,
donate_argnums=(1,)))`` or instance attributes ``self._step = jax.jit(f,
donate_argnums=(0, 1))``), remembering the donated positions.  At every
call of such a binding we take the argument expression at each donated
position and, within the same function scope, flag any *read* of that
expression after the call — unless the same statement rebinds it (the
canonical ``params = step(params, ...)`` / ``self.a, self.b =
self._step(self.a, self.b)`` donation pattern), or a later statement
rebinds it before the first read.  Calls inside loops additionally treat
any read of an un-rebound donated expression in the loop body as a
finding: the next iteration would hand XLA a deleted buffer.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from fedml_tpu.analysis.core import (
    Finding,
    Repo,
    SourceFile,
    call_name,
    dotted,
    enclosing_function,
    stmt_of,
)

PASS_ID = "donation"


def _donate_argnums(call: ast.Call) -> Set[int]:
    out: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.add(e.value)
    return out


def _find_jit_with_donation(expr: ast.AST) -> Optional[Set[int]]:
    """If ``expr`` is (or wraps) a ``jax.jit(..., donate_argnums=...)``
    call, return the donated positions."""
    if not isinstance(expr, ast.Call):
        return None
    name = call_name(expr)
    if name in ("jax.jit", "jit"):
        nums = _donate_argnums(expr)
        return nums or None
    # wrap_jit("name", jax.jit(fn, donate_argnums=...), ...)
    if name is not None and name.split(".")[-1] in ("wrap_jit", "_wrap_jit"):
        for arg in expr.args:
            nums = _find_jit_with_donation(arg)
            if nums:
                return nums
    return None


def _collect_donating_bindings(file: SourceFile) -> Dict[str, Set[int]]:
    """binding source text (``_prog`` / ``self._step``) -> donated
    argnums, from assignments in this file."""
    out: Dict[str, Set[int]] = {}
    tree = file.tree
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            nums = _find_jit_with_donation(node.value)
            if nums:
                target = dotted(node.targets[0])
                if target is not None:
                    out[target] = nums
    return out


def _store_targets(stmt: ast.AST) -> Set[str]:
    """Textual forms (``x``, ``self.params``) stored by ``stmt``."""
    out: Set[str] = set()
    targets: Sequence[ast.AST] = ()
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign, ast.For)):
        targets = (stmt.target,)
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, (ast.Name, ast.Attribute)):
                d = dotted(node)
                if d is not None:
                    out.add(d)
    return out


def _reads_of(node: ast.AST, expr_text: str) -> List[ast.AST]:
    """Load-context occurrences of ``expr_text`` under ``node``."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, (ast.Name, ast.Attribute)) \
                and isinstance(getattr(n, "ctx", None), ast.Load) \
                and dotted(n) == expr_text:
            # skip the bases of longer attribute chains: ``x.shape`` reads
            # metadata of a donated ``x`` — still a read, keep it
            out.append(n)
    return out


def _check_call(file: SourceFile, call: ast.Call, donated: Set[int],
                binding: str, findings: List[Finding]) -> None:
    fn = enclosing_function(file, call)
    if fn is None:
        return
    call_stmt = stmt_of(file, call)
    if not isinstance(call_stmt, ast.stmt):
        return

    for pos in sorted(donated):
        if pos >= len(call.args):
            continue
        arg = call.args[pos]
        expr_text = dotted(arg)
        if expr_text is None:
            continue  # expression arg (fresh temporary) — nothing to re-read
        # rebound by the very statement that makes the call?
        if expr_text in _store_targets(call_stmt):
            continue
        # loop-carried donation without rebinding: every iteration after
        # the first passes a deleted buffer
        loop = None
        for anc in file.ancestors(call):
            if isinstance(anc, (ast.For, ast.While)):
                loop = anc
                break
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        scope = loop if loop is not None else fn
        offending: Optional[ast.AST] = None
        if loop is not None:
            # loop-carried: a rebinding anywhere in the loop body (other
            # than the call statement itself) restores the name each
            # iteration; without one, any read in the loop is a hazard
            rebound_in_loop = any(
                isinstance(stmt, ast.stmt) and stmt is not call_stmt
                and expr_text in _store_targets(stmt)
                for stmt in ast.walk(loop))
            if rebound_in_loop:
                continue
            # no rebinding at all: iteration 2 re-passes the deleted
            # buffer at this very call site
            offending = arg
        for n in _reads_of(scope, expr_text):
            if n is arg or (n.lineno == arg.lineno
                            and n.col_offset == arg.col_offset):
                continue
            if loop is None:
                if n.lineno <= call.lineno:
                    continue  # straight-line scope: only later reads count
                # a rebinding between the call and the read clears it
                rebound = False
                for stmt in ast.walk(scope):
                    if isinstance(stmt, ast.stmt) and stmt is not call_stmt \
                            and call.lineno < getattr(stmt, "lineno", 0) \
                            <= n.lineno \
                            and expr_text in _store_targets(stmt):
                        rebound = True
                        break
                if rebound:
                    continue
            offending = n
            break
        if offending is not None:
            where = "in the enclosing loop" if loop is not None else \
                "after the donating call"
            findings.append(Finding(
                PASS_ID, file.rel, offending.lineno,
                f"read of '{expr_text}' {where} — it was donated to "
                f"'{binding}' (argnum {pos}) and its buffer is deleted"))


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for file in repo.package_files():
        bindings = _collect_donating_bindings(file)
        if not bindings:
            continue
        tree = file.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            target = dotted(node.func)
            if target is None or target not in bindings:
                continue
            _check_call(file, node, bindings[target], target, findings)
    return findings
