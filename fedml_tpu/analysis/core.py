"""Shared analysis core: repo model, parsed files, scopes, call graphs.

Every pass operates on a :class:`Repo` — the set of first-party python
files parsed exactly once (source text, line table, AST, parent links,
``# graft: allow`` waivers).  The helpers here are deliberately
heuristic: they resolve what an AST can resolve (same-module calls,
``from fedml_tpu.x import y`` imports, ``self.method()`` within a class)
and stay silent where python's dynamism wins.  Passes are tuned so that
what they *do* report is worth a human's time; `analysis_baseline.txt`
absorbs the verified-benign remainder.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# one line-waiver syntax for every pass: the justification after the
# colon is mandatory (enforced by the runner, not the regex)
ALLOW_RE = re.compile(
    r"#\s*graft:\s*allow\(\s*([a-z0-9_\-, ]+?)\s*\)(?::\s*(\S.*))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer finding.  ``key`` deliberately excludes the line
    number so baseline entries survive unrelated edits above them."""

    pass_id: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.pass_id}|{self.path}|{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


class SourceFile:
    """One parsed file: source, lines, AST (lazy), parent links (lazy),
    and the ``# graft: allow(...)`` waivers found on its lines."""

    def __init__(self, abspath: str, rel: str, src: str):
        self.path = abspath
        self.rel = rel.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self._tree: Optional[ast.Module] = None
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self.syntax_error: Optional[SyntaxError] = None
        self._parsed = False
        # line -> (pass ids, justification-or-None)
        self.allows: Dict[int, Tuple[Set[str], Optional[str]]] = {}
        for i, line in enumerate(self.lines, 1):
            m = ALLOW_RE.search(line)
            if m:
                ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
                self.allows[i] = (ids, m.group(2))

    @property
    def tree(self) -> Optional[ast.Module]:
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.src, filename=self.path)
            except SyntaxError as e:  # reported by the lint pass as E999
                self.syntax_error = e
        return self._tree

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(node):
                        self._parents[child] = node
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        p = self.parents.get(node)
        while p is not None:
            yield p
            p = self.parents.get(p)

    def allowed(self, pass_id: str, line: int) -> bool:
        """True when ``line`` carries ``# graft: allow(<pass_id>)``, or a
        contiguous comment block directly above it does (the waiver plus
        its justification may wrap over several comment lines)."""
        entry = self.allows.get(line)
        if entry is not None and pass_id in entry[0]:
            return True
        ln = line - 1
        while 1 <= ln <= len(self.lines) \
                and self.lines[ln - 1].lstrip().startswith("#"):
            entry = self.allows.get(ln)
            if entry is not None and pass_id in entry[0]:
                return True
            # stacked single-pass waivers compose: keep scanning the
            # comment block past allows for other passes
            ln -= 1
        return False


class Repo:
    """All first-party python files, parsed once and shared by every
    pass.  ``roots`` mirrors the historical lint roots; domain passes
    narrow to :meth:`package_files`."""

    DEFAULT_ROOTS: Sequence[str] = (
        "fedml_tpu", "tools", "examples", "bench.py", "__graft_entry__.py")

    def __init__(self, root: str, roots: Sequence[str] = DEFAULT_ROOTS):
        self.root = os.path.abspath(root)
        self.files: List[SourceFile] = []
        self.by_rel: Dict[str, SourceFile] = {}
        for entry in roots:
            target = os.path.join(self.root, entry)
            if entry.endswith(".py"):
                if os.path.isfile(target):
                    self._add(target)
                continue
            for base, dirs, names in os.walk(target):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for fn in sorted(names):
                    if fn.endswith(".py"):
                        self._add(os.path.join(base, fn))
        self.files.sort(key=lambda f: f.rel)

    def _add(self, abspath: str) -> None:
        rel = os.path.relpath(abspath, self.root)
        if rel in self.by_rel:
            return
        with open(abspath, encoding="utf-8") as f:
            src = f.read()
        sf = SourceFile(abspath, rel, src)
        self.files.append(sf)
        self.by_rel[sf.rel] = sf

    def package_files(self) -> List[SourceFile]:
        return [f for f in self.files if f.rel.startswith("fedml_tpu/")]

    def module(self, dotted_name: str) -> Optional[SourceFile]:
        """Resolve ``fedml_tpu.compression.codecs`` to its SourceFile."""
        rel = dotted_name.replace(".", "/")
        return (self.by_rel.get(rel + ".py")
                or self.by_rel.get(rel + "/__init__.py"))


# ---- AST helpers shared by the passes -------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain (``jax.random.normal``) or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def enclosing_function(file: SourceFile, node: ast.AST):
    for anc in file.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def import_map(file: SourceFile) -> Dict[str, Tuple[str, Optional[str]]]:
    """name -> (module, original_name_or_None).  ``from a.b import c as d``
    maps ``d -> ("a.b", "c")``; ``import a.b as ab`` maps
    ``ab -> ("a.b", None)``."""
    out: Dict[str, Tuple[str, Optional[str]]] = {}
    tree = file.tree
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (a.name, None)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name != "*":
                    out[a.asname or a.name] = (node.module, a.name)
    return out


def names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def in_lock_block(file: SourceFile, node: ast.AST) -> bool:
    """True when ``node`` sits under a ``with <something lock-ish>:``.

    Lock-ish = any context expression whose source mentions lock/mutex/
    cond — matches the repo convention (``self._lock``, ``_catalog_lock``,
    ``self._cv``)."""
    for anc in file.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                src = ast.unparse(item.context_expr).lower()
                if "lock" in src or "mutex" in src or "_cv" in src \
                        or "cond" in src:
                    return True
    return False


def stmt_of(file: SourceFile, node: ast.AST) -> ast.AST:
    """The nearest enclosing statement (``node`` itself when it is one)."""
    if isinstance(node, ast.stmt):
        return node
    for anc in file.ancestors(node):
        if isinstance(anc, ast.stmt):
            return anc
    return node
