"""graftcheck runner — pass registry, suppression, baseline, CLI.

Finding flow: every pass reports raw findings; the runner then drops

1. line waivers — ``# graft: allow(<pass-id>): <why>`` on the finding
   line (or a standalone comment directly above).  An allow *without*
   the justification is itself a finding (pass id ``suppression``);
2. baseline entries — ``analysis_baseline.txt`` lines of the form
   ``pass-id|path|message :: justification`` matching the finding's
   key (line numbers excluded, so unrelated edits don't invalidate it).

Exit codes: 0 clean, 1 unsuppressed findings, 2 infrastructure error.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Set

from fedml_tpu.analysis import core
from fedml_tpu.analysis.core import Finding, Repo
from fedml_tpu.analysis.passes import (
    donation,
    host_sync,
    jit_purity,
    lint,
    messages,
    span_names,
    threads,
)

ALL_PASSES = {
    jit_purity.PASS_ID: jit_purity,
    donation.PASS_ID: donation,
    host_sync.PASS_ID: host_sync,
    threads.PASS_ID: threads,
    messages.PASS_ID: messages,
    span_names.PASS_ID: span_names,
    lint.PASS_ID: lint,
}

BASELINE_NAME = "analysis_baseline.txt"
SUPPRESSION_PASS = "suppression"


class BaselineError(ValueError):
    pass


def load_baseline(path: str) -> Dict[str, str]:
    """key -> justification.  Every entry must carry one."""
    out: Dict[str, str] = {}
    if not os.path.isfile(path):
        return out
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            entry, sep, why = line.partition(" :: ")
            if not sep or not why.strip():
                raise BaselineError(
                    f"{path}:{i}: baseline entry needs a justification "
                    "('pass-id|path|message :: why')")
            if entry.count("|") < 2:
                raise BaselineError(
                    f"{path}:{i}: malformed baseline key "
                    "(expected 'pass-id|path|message')")
            out[entry.strip()] = why.strip()
    return out


def _allow_findings(repo: Repo) -> List[Finding]:
    """Allow-comments missing their mandatory justification."""
    out: List[Finding] = []
    for file in repo.files:
        for line, (ids, why) in sorted(file.allows.items()):
            if why is None or not why.strip():
                out.append(Finding(
                    SUPPRESSION_PASS, file.rel, line,
                    f"graft: allow({', '.join(sorted(ids))}) requires a "
                    "justification — '# graft: allow(<pass-id>): <why>'"))
    return out


class AnalysisResult:
    def __init__(self) -> None:
        self.findings: List[Finding] = []       # unsuppressed
        self.suppressed_inline: List[Finding] = []
        self.suppressed_baseline: List[Finding] = []
        self.stale_baseline: List[str] = []
        self.counts: Dict[str, int] = {}
        self.files = 0
        self.elapsed_s = 0.0

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> str:
        return json.dumps({
            "schema": "graftcheck/v1",
            "ok": self.ok,
            "files": self.files,
            "elapsed_s": round(self.elapsed_s, 3),
            "counts": {k: v for k, v in sorted(self.counts.items())},
            "suppressed": {
                "inline": len(self.suppressed_inline),
                "baseline": len(self.suppressed_baseline),
            },
            "stale_baseline": len(self.stale_baseline),
            "findings": [
                {"pass": f.pass_id, "path": f.path, "line": f.line,
                 "message": f.message}
                for f in self.findings],
        }, sort_keys=True)


def run_analysis(root: str,
                 passes: Optional[Sequence[str]] = None,
                 baseline_path: Optional[str] = None,
                 changed_only: Optional[Set[str]] = None,
                 repo: Optional[Repo] = None) -> AnalysisResult:
    t0 = time.monotonic()
    result = AnalysisResult()
    repo = repo if repo is not None else Repo(root)
    result.files = len(repo.files)
    ids = list(passes) if passes else list(ALL_PASSES)
    for pid in ids:
        if pid not in ALL_PASSES:
            raise ValueError(f"unknown pass {pid!r} "
                             f"(have: {', '.join(sorted(ALL_PASSES))})")

    if baseline_path is None:
        baseline_path = os.path.join(repo.root, BASELINE_NAME)
    baseline = load_baseline(baseline_path)
    matched: Set[str] = set()

    raw: List[Finding] = []
    for pid in ids:
        found = ALL_PASSES[pid].run(repo)
        result.counts[pid] = 0
        raw.extend(found)
    raw.extend(_allow_findings(repo))
    result.counts.setdefault(SUPPRESSION_PASS, 0)

    for f in sorted(raw, key=lambda f: (f.path, f.line, f.pass_id,
                                        f.message)):
        file = repo.by_rel.get(f.path)
        if file is not None and f.pass_id != SUPPRESSION_PASS \
                and file.allowed(f.pass_id, f.line):
            result.suppressed_inline.append(f)
            continue
        if f.key in baseline:
            matched.add(f.key)
            result.suppressed_baseline.append(f)
            continue
        if changed_only is not None and f.path not in changed_only:
            continue
        result.findings.append(f)
        result.counts[f.pass_id] = result.counts.get(f.pass_id, 0) + 1

    # a --passes subset run can only judge entries of the passes that
    # actually executed — anything else would tell the developer to
    # delete entries a full run still needs
    ran = set(ids) | {SUPPRESSION_PASS}
    result.stale_baseline = sorted(
        key for key in set(baseline) - matched
        if key.split("|", 1)[0] in ran)
    result.elapsed_s = time.monotonic() - t0
    return result


def _changed_files(root: str, base: str) -> Set[str]:
    out: Set[str] = set()
    for args in (["git", "diff", "--name-only", base, "--"],
                 ["git", "diff", "--name-only", "--cached", "--"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(args, cwd=root, capture_output=True,
                              text=True, check=False)
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(args)} failed: {proc.stderr.strip()}")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return out


def _default_root() -> str:
    # core.py lives at <root>/fedml_tpu/analysis/core.py
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(core.__file__))))


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="semantic static analysis for fedml_tpu's invariants")
    ap.add_argument("--root", default=_default_root(),
                    help="repo root (default: auto-detected)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--changed", metavar="BASE", default=None,
                    help="only report findings in files changed vs the "
                         "given git ref (analysis still runs repo-wide)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one machine-readable JSON line on stdout")
    ap.add_argument("--list-passes", action="store_true")
    ap.add_argument("--write-baseline", action="store_true",
                    help="print baseline lines for the current findings "
                         "(fill in the ':: justification' before use)")
    args = ap.parse_args(argv)

    if args.list_passes:
        for pid, module in ALL_PASSES.items():
            doc = (module.__doc__ or "").strip().splitlines()[0]
            print(f"{pid:17s} {doc}")  # noqa: T201 (CLI output)
        return 0

    passes = ([p.strip() for p in args.passes.split(",") if p.strip()]
              if args.passes else None)
    changed: Optional[Set[str]] = None
    try:
        if args.changed is not None:
            changed = _changed_files(args.root, args.changed)
        result = run_analysis(args.root, passes=passes,
                              baseline_path=args.baseline,
                              changed_only=changed)
    except (ValueError, RuntimeError) as e:
        print(f"graftcheck: error: {e}", file=sys.stderr)  # noqa: T201 (CLI output)
        return 2

    if args.write_baseline:
        for f in result.findings:
            print(f"{f.key} :: TODO justify")  # noqa: T201 (CLI output)
        return 0 if result.ok else 1

    if args.as_json:
        print(result.to_json())  # noqa: T201 (CLI output)
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())  # noqa: T201 (CLI output)
    for key in result.stale_baseline:
        print(f"graftcheck: note: stale baseline entry (fixed? remove "  # noqa: T201 (CLI output)
              f"it): {key}")
    n_sup = len(result.suppressed_inline) + len(result.suppressed_baseline)
    scope = (f" in {len(changed)} changed file(s)"
             if changed is not None else "")
    if result.findings:
        print(f"\ngraftcheck: {len(result.findings)} finding(s){scope} "  # noqa: T201 (CLI output)
              f"({n_sup} suppressed) across {result.files} files "
              f"in {result.elapsed_s:.1f}s")
        return 1
    print(f"graftcheck clean{scope}: {result.files} files, "  # noqa: T201 (CLI output)
          f"{len(ALL_PASSES) if passes is None else len(passes)} passes, "
          f"{n_sup} suppressed finding(s), {result.elapsed_s:.1f}s")
    return 0
