"""Device discovery — parity with ``python/fedml/device/device.py:42``.

On TPU the interesting object is not a single device but the mesh; this
module exposes both: ``get_device`` (reference surface) and ``get_mesh``.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def get_device(args: Any = None):
    devices = jax.devices()
    return devices[0]


def get_mesh(
    args: Any = None,
    axis_names: Sequence[str] = ("clients",),
    shape: Optional[Sequence[int]] = None,
) -> Mesh:
    devices = np.asarray(jax.devices())
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    return Mesh(devices.reshape(shape), axis_names=tuple(axis_names))


def device_count() -> int:
    return jax.device_count()
