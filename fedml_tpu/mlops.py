"""``fedml_tpu.mlops`` — public observability API.

Parity target: ``python/fedml/mlops/__init__.py:10-196`` (``mlops.log``,
``log_metric``, ``log_artifact``, ``log_model``, ``log_llm_record``,
``event`` spans). Everything lands in the local JSONL sink
(``core/mlops/metrics.py``) — the hosted-MQTT backend's role here —
with optional wandb mirroring.
"""
from __future__ import annotations

import contextlib

from fedml_tpu.core.mlops.event import MLOpsProfilerEvent
from fedml_tpu.core.mlops.metrics import (  # noqa: F401
    init,
    log,
    log_artifact,
    log_llm_record,
    log_metric,
    log_model,
    log_round_info,
)

_event_singleton = None


def _events() -> MLOpsProfilerEvent:
    global _event_singleton
    if _event_singleton is None:
        _event_singleton = MLOpsProfilerEvent(None)
    return _event_singleton


@contextlib.contextmanager
def event(name: str, event_value=None):
    """Span context manager (reference: ``mlops.event(..., started/ended)``)."""
    _events().log_event_started(name, event_value)
    try:
        yield
    finally:
        _events().log_event_ended(name, event_value)


__all__ = [
    "event",
    "init",
    "log",
    "log_artifact",
    "log_llm_record",
    "log_metric",
    "log_model",
    "log_round_info",
]
