"""FedMLRunner — facade choosing the engine runner.

Parity: ``python/fedml/runner.py:19-185``.
"""
from __future__ import annotations

from typing import Any

from fedml_tpu import constants


class FedMLRunner:
    def __init__(
        self,
        args: Any,
        device: Any,
        dataset: Any,
        model: Any,
        client_trainer=None,
        server_aggregator=None,
    ):
        self.args = args
        self.runner = self._build(args, device, dataset, model, client_trainer, server_aggregator)

    def _build(self, args, device, dataset, model, client_trainer, server_aggregator):
        tt = str(getattr(args, "training_type", constants.FEDML_TRAINING_PLATFORM_SIMULATION))
        if tt == constants.FEDML_TRAINING_PLATFORM_SIMULATION:
            from fedml_tpu.simulation.simulator import create_simulator

            return create_simulator(args, device, dataset, model, client_trainer, server_aggregator)
        if tt in (
            constants.FEDML_TRAINING_PLATFORM_CROSS_SILO,
            constants.FEDML_TRAINING_PLATFORM_CROSS_CLOUD,
        ):
            role = str(getattr(args, "role", constants.ROLE_CLIENT))
            is_server = (role == constants.ROLE_SERVER
                         or int(getattr(args, "rank", 0)) == 0)
            if tt == constants.FEDML_TRAINING_PLATFORM_CROSS_CLOUD:
                from fedml_tpu.cross_cloud import CloudClient, CloudServer

                cls = CloudServer if is_server else CloudClient
            else:
                from fedml_tpu.cross_silo.client.client import Client
                from fedml_tpu.cross_silo.server.server import Server

                cls = Server if is_server else Client
            if is_server:
                return cls(args, device, dataset, model, server_aggregator)
            return cls(args, device, dataset, model, client_trainer)
        if tt == constants.FEDML_TRAINING_PLATFORM_CROSS_DEVICE:
            from fedml_tpu.cross_device.server import ServerCrossDevice

            return ServerCrossDevice(args, device, dataset, model, server_aggregator)
        raise ValueError(f"unknown training_type {tt!r}")

    def run(self):
        return self.runner.run()
