"""Inference gateway — one front door for all deployed endpoints.

Parity target: ``model_scheduler/device_model_inference.py:52-132`` (the
FastAPI gateway: ``/inference/{end_point_id}`` + OpenAI-style subpaths,
per-endpoint auth token, redis lookups for the target device, request
metrics). Re-design: a stdlib threading HTTP server that resolves
replicas through the EndpointCache, round-robins across healthy ones,
proxies with streaming passthrough, and on connection failure marks the
replica OFFLINE (health-driven re-route) before trying the next — a dead
worker 503s only its own endpoint.
"""
from __future__ import annotations

import hmac
import itertools
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from fedml_tpu.deploy.cache import EndpointCache, EndpointStatus
from fedml_tpu.serving.monitor import EndpointMonitor

_STREAMING_TYPES = ("application/x-ndjson", "text/event-stream")


class InferenceGateway:
    def __init__(self, cache: EndpointCache, host: str = "127.0.0.1",
                 port: int = 0, request_timeout: float = 120.0):
        self.cache = cache
        self.request_timeout = request_timeout
        self._rr = itertools.count()
        self._monitors: Dict[str, EndpointMonitor] = {}
        self._mon_lock = threading.Lock()
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                path = self.path.rstrip("/")
                if path in ("", "/ready"):
                    self._json(200, {"ready": True})
                elif path == "/endpoints":
                    self._json(200, gw.describe_endpoints())
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                parts = self.path.strip("/").split("/")
                if len(parts) < 2 or parts[0] != "inference":
                    self._json(404, {"error": "not found"})
                    return
                endpoint_id, subpath = parts[1], "/".join(parts[2:])
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                gw._proxy(self, endpoint_id, subpath, body)

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "InferenceGateway":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def run(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- introspection ----------------------------------------------------
    def describe_endpoints(self):
        out = []
        live = {ep["endpoint_id"] for ep in self.cache.list_endpoints()}
        with self._mon_lock:  # evict monitors of undeployed endpoints
            for eid in list(self._monitors):
                if eid not in live:
                    del self._monitors[eid]
        for ep in self.cache.list_endpoints():
            eid = ep["endpoint_id"]
            row = {k: ep.get(k) for k in
                   ("endpoint_id", "endpoint_name", "model_name",
                    "model_version", "status")}
            row["replicas"] = [
                {"worker_id": r.get("worker_id"), "status": r.get("status")}
                for r in ep.get("replicas", {}).values()
            ]
            row["metrics"] = self._monitor(eid).snapshot()
            out.append(row)
        return out

    def _monitor(self, endpoint_id: str) -> EndpointMonitor:
        with self._mon_lock:
            mon = self._monitors.get(endpoint_id)
            if mon is None:
                mon = self._monitors[endpoint_id] = EndpointMonitor()
            return mon

    # -- proxy ------------------------------------------------------------
    @staticmethod
    def _target_path(subpath: str) -> str:
        # OpenAI-style subpaths map onto the replica's /v1 surface
        # (reference routes /inference/{id}/chat/completions the same way)
        if subpath in ("completions", "chat/completions"):
            return "/v1/" + subpath
        return "/" + subpath if subpath else "/predict"

    def _authorized(self, handler, ep: Dict) -> bool:
        token = ep.get("token")
        if not token:
            return True
        auth = handler.headers.get("Authorization", "")
        return hmac.compare_digest(auth, f"Bearer {token}")

    def _proxy(self, handler, endpoint_id: str, subpath: str,
               body: bytes) -> None:
        t0 = time.time()
        ep = self.cache.get(endpoint_id)
        if ep is None:
            # no monitor for unknown ids — scanners must not grow state
            self._reply_json(handler, 404,
                             {"error": f"no such endpoint {endpoint_id}"})
            return
        mon = self._monitor(endpoint_id)
        if not self._authorized(handler, ep):
            self._reply_json(handler, 401, {"error": "invalid token"})
            mon.record_request(time.time() - t0, False)
            return

        replicas = self.cache.healthy_replicas(endpoint_id)
        if replicas:
            start = next(self._rr) % len(replicas)
            replicas = replicas[start:] + replicas[:start]
        ok = False
        for rep in replicas:
            sent, ok = self._try_replica(handler, rep, subpath, body)
            if sent:
                break
            # connection-level failure: mark OFFLINE so every later request
            # (and other gateway processes) skips it until the health loop
            # sees it recover
            self.cache.set_replica(endpoint_id, rep["worker_id"],
                                   url=rep.get("url"),
                                   status=EndpointStatus.OFFLINE)
        else:
            self._reply_json(handler, 503, {
                "error": f"no healthy replica for endpoint {endpoint_id}"})
        if not self.cache.healthy_replicas(endpoint_id):
            self.cache.set_status(endpoint_id, EndpointStatus.OFFLINE)
        mon.record_request(time.time() - t0, ok)

    def _try_replica(self, handler, rep: Dict, subpath: str,
                     body: bytes) -> Tuple[bool, bool]:
        """Returns (response_sent, response_ok)."""
        url = rep["url"] + self._target_path(subpath)
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            resp = urllib.request.urlopen(req, timeout=self.request_timeout)
        except urllib.error.HTTPError as e:
            # upstream answered (replica alive): forward its error verbatim
            payload = e.read()
            handler.send_response(e.code)
            handler.send_header(
                "Content-Type", e.headers.get("Content-Type", "application/json"))
            handler.send_header("Content-Length", str(len(payload)))
            handler.end_headers()
            handler.wfile.write(payload)
            return True, False
        except (urllib.error.URLError, OSError):
            return False, False  # dead replica → caller re-routes
        with resp:
            ctype = resp.headers.get("Content-Type", "application/json")
            if any(ctype.startswith(t) for t in _STREAMING_TYPES):
                handler.send_response(resp.status)
                handler.send_header("Content-Type", ctype)
                handler.send_header("Transfer-Encoding", "chunked")
                handler.end_headers()
                try:
                    while True:
                        chunk = resp.read(8192)
                        if not chunk:
                            break
                        handler.wfile.write(
                            f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                    handler.wfile.write(b"0\r\n\r\n")
                except BrokenPipeError:
                    return True, False
                return True, True
            payload = resp.read()
            handler.send_response(resp.status)
            handler.send_header("Content-Type", ctype)
            handler.send_header("Content-Length", str(len(payload)))
            handler.end_headers()
            try:
                handler.wfile.write(payload)
            except BrokenPipeError:
                return True, False
            return True, True

    @staticmethod
    def _reply_json(handler, code: int, obj) -> None:
        body = json.dumps(obj).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
