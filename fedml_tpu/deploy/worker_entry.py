"""Replica entry point — runs one endpoint's predictor in its own process.

Parity target: the code the reference runs *inside* the inference
container (``serving/fedml_inference_runner.py`` wrapped by the docker
image built in ``device_model_deployment.py``). Here the "image" is a
model-card package directory: ``model_config.yaml`` names either a
builtin predictor or a user ``FedMLPredictor`` subclass shipped in the
card.
"""
from __future__ import annotations

import argparse
import os
import sys

import yaml

from fedml_tpu.deploy.model_cards import MODEL_CONFIG_FILE
from fedml_tpu.serving.inference_runner import FedMLInferenceRunner


def build_predictor(package_dir: str):
    with open(os.path.join(package_dir, MODEL_CONFIG_FILE)) as f:
        cfg = yaml.safe_load(f) or {}
    params = cfg.get("params") or {}
    builtin = cfg.get("builtin")
    if builtin == "llama":
        import jax
        import jax.numpy as jnp

        from fedml_tpu.models.llm.llama import LlamaConfig, LlamaForCausalLM
        from fedml_tpu.serving import ContinuousBatchingEngine, LlamaPredictor

        class _A:
            pass

        a = _A()
        a.model_size = params.get("model_size", "tiny")
        a.lora_rank = params.get("lora_rank") or None
        model = LlamaForCausalLM(LlamaConfig.from_args(a))
        weights = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
        engine = ContinuousBatchingEngine(
            model, weights,
            batch_slots=int(params.get("batch_slots", 4)),
            max_len=int(params.get("max_len", 512)),
            quantize=params.get("quantize"),
            quantize_donate=True,  # freshly-initialized weights, no other user
        )
        return LlamaPredictor(engine)
    if builtin is not None:
        raise ValueError(f"unknown builtin predictor: {builtin}")
    sys.path.insert(0, package_dir)
    module = __import__(cfg["entry_module"])
    cls = getattr(module, cfg["entry_class"])
    return cls(**params)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--package", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    args = ap.parse_args(argv)
    predictor = build_predictor(os.path.abspath(args.package))
    # LLM predictors also get the OpenAI-compatible surface, which the
    # gateway's /inference/{id}/chat/completions route forwards to
    openai = None
    engine = getattr(predictor, "engine", None)
    if engine is not None:
        from fedml_tpu.serving.openai_protocol import OpenAIServing

        openai = OpenAIServing(engine)
    runner = FedMLInferenceRunner(predictor, host=args.host, port=args.port,
                                  openai=openai)
    runner.run()


if __name__ == "__main__":
    main()
