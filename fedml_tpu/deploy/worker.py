"""Deploy worker agent — boots endpoint replicas on its node.

Parity target: ``model_scheduler/device_client_runner.py`` (worker deploy
agent: receives deployment over MQTT, runs the model container, reports
result) + the executor ``device_model_deployment.py:528`` (docker/Triton
there). Re-design: the replica is a **subprocess** running
``fedml_tpu.deploy.worker_entry`` — its own Python/JAX runtime owns the
accelerator, the parent supervises it — and the model package arrives
through the object store (the S3 seam), control through the broker.

Wire protocol (JSON over broker topics):

  worker → ``deploy/{cluster}/master``:
      worker_online {worker_id, capacity}
      heartbeat     {worker_id}
      deploy_result {worker_id, endpoint_id, ok, url|error}
      undeploy_result {worker_id, endpoint_id, ok}
      replica_down  {worker_id, endpoint_id, rc}
  master → ``deploy/{cluster}/worker/{worker_id}``:
      deploy   {endpoint_id, model_name, model_version, package_key}
      undeploy {endpoint_id}
"""
from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Dict

from fedml_tpu.core.distributed.communication.broker_agent import BrokerJsonAgent
from fedml_tpu.core.distributed.communication.object_store import ObjectStore
from fedml_tpu.deploy.model_cards import FedMLModelCards

logger = logging.getLogger(__name__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Replica:
    def __init__(self, endpoint_id: str, proc: subprocess.Popen, url: str):
        self.endpoint_id = endpoint_id
        self.proc = proc
        self.url = url


class DeployWorkerAgent(BrokerJsonAgent):
    def __init__(self, worker_id: str, broker_host: str, broker_port: int,
                 store: ObjectStore, workdir: str = ".fedml_deploy",
                 cluster: str = "default", capacity: int = 4,
                 heartbeat_s: float = 2.0):
        super().__init__(broker_host, broker_port)
        self.worker_id = worker_id
        self.cluster = cluster
        self.capacity = capacity
        self.store = store
        self.workdir = os.path.abspath(os.path.join(workdir, worker_id))
        os.makedirs(self.workdir, exist_ok=True)
        self.replicas: Dict[str, _Replica] = {}
        self._cap_lock = threading.Lock()
        self._inflight = 0  # boots in progress count toward capacity
        self._heartbeat_s = heartbeat_s
        self.subscribe_json(
            f"deploy/{cluster}/worker/{worker_id}", self._on_message)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "DeployWorkerAgent":
        self._publish({"type": "worker_online", "worker_id": self.worker_id,
                       "capacity": self.capacity})
        self.spawn_loop(self._heartbeat_loop)
        self.spawn_loop(self._supervise_loop)
        return self

    def shutdown(self) -> None:
        self._stopping.set()
        for rep in list(self.replicas.values()):
            self._kill_replica(rep)
        self.replicas.clear()
        self.stop_agent()

    def serve_forever(self) -> None:
        """Blocking daemon loop (CLI `deploy worker` entry)."""
        self.start()
        try:
            while not self._stopping.is_set():
                time.sleep(0.5)
        except KeyboardInterrupt:
            self.shutdown()

    # -- control-plane handlers ------------------------------------------
    def _on_message(self, msg: Dict) -> None:
        mtype = msg.get("type")
        if mtype == "deploy":
            threading.Thread(
                target=self._handle_deploy, args=(msg,), daemon=True).start()
        elif mtype == "undeploy":
            self._handle_undeploy(msg)

    def _handle_deploy(self, msg: Dict) -> None:
        endpoint_id = str(msg["endpoint_id"])
        with self._cap_lock:
            # in-flight boots count too: boots take up to boot_timeout, and
            # each replica is a JAX/XLA process — oversubscription is what
            # --capacity exists to prevent
            if len(self.replicas) + self._inflight >= self.capacity:
                error = f"worker at capacity {self.capacity}"
            elif endpoint_id in self.replicas:
                error = f"endpoint {endpoint_id} already deployed here"
            else:
                error = None
                self._inflight += 1
        if error is not None:
            self._publish({"type": "deploy_result", "worker_id": self.worker_id,
                           "endpoint_id": endpoint_id, "ok": False,
                           "error": error})
            return
        try:
            url = self._boot_replica(endpoint_id, msg)
            self._publish({"type": "deploy_result", "worker_id": self.worker_id,
                           "endpoint_id": endpoint_id, "ok": True, "url": url})
        except Exception as e:
            logger.exception("deploy of %s failed", endpoint_id)
            self._publish({"type": "deploy_result", "worker_id": self.worker_id,
                           "endpoint_id": endpoint_id, "ok": False,
                           "error": str(e)})
        finally:
            with self._cap_lock:
                self._inflight -= 1

    def _boot_replica(self, endpoint_id: str, msg: Dict) -> str:
        pkg_key = msg["package_key"]
        pkg_dir = os.path.join(self.workdir, "endpoints", endpoint_id)
        zip_path = pkg_dir + ".zip"
        os.makedirs(os.path.dirname(zip_path), exist_ok=True)
        with open(zip_path, "wb") as f:
            f.write(self.store.get_object(pkg_key))
        FedMLModelCards.unpack(zip_path, pkg_dir)
        os.unlink(zip_path)

        port = _free_port()
        url = f"http://127.0.0.1:{port}"
        env = dict(os.environ)
        env["FEDML_ENDPOINT_ID"] = endpoint_id
        # the replica's cwd is the package dir; make sure it can still
        # import fedml_tpu (tests/dev run from a source checkout)
        import fedml_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(fedml_tpu.__file__)))
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH")) if p)
        log_path = os.path.join(self.workdir, f"{endpoint_id}.log")
        with open(log_path, "ab") as log_f:
            proc = subprocess.Popen(
                [sys.executable, "-m", "fedml_tpu.deploy.worker_entry",
                 "--package", pkg_dir, "--host", "127.0.0.1",
                 "--port", str(port)],
                cwd=pkg_dir, env=env, stdout=log_f,
                stderr=subprocess.STDOUT, start_new_session=True,
            )
        deadline = time.time() + float(msg.get("boot_timeout", 120))
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replica exited rc={proc.returncode} during boot "
                    f"(log: {log_path})")
            try:
                with urllib.request.urlopen(url + "/ready", timeout=2) as r:
                    if json.loads(r.read()).get("ready"):
                        self.replicas[endpoint_id] = _Replica(
                            endpoint_id, proc, url)
                        return url
            except (OSError, ValueError):
                pass
            time.sleep(0.2)
        # group-kill + reap: the replica may have spawned helpers, and an
        # unreaped child would sit as a zombie in this agent's table
        self._kill_replica(_Replica(endpoint_id, proc, url))
        raise TimeoutError(f"replica for {endpoint_id} never became ready")

    def _handle_undeploy(self, msg: Dict) -> None:
        endpoint_id = str(msg["endpoint_id"])
        rep = self.replicas.pop(endpoint_id, None)
        if rep is not None:
            self._kill_replica(rep)
        self._publish({"type": "undeploy_result", "worker_id": self.worker_id,
                       "endpoint_id": endpoint_id, "ok": rep is not None})

    def _kill_replica(self, rep: _Replica, grace_s: float = 3.0) -> None:
        if rep.proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(rep.proc.pid), signal.SIGTERM)
            deadline = time.time() + grace_s
            while time.time() < deadline and rep.proc.poll() is None:
                time.sleep(0.05)
            if rep.proc.poll() is None:
                os.killpg(os.getpgid(rep.proc.pid), signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            rep.proc.wait(timeout=5)  # reap; no zombie in our table
        except (subprocess.TimeoutExpired, OSError):
            pass

    # -- background loops -------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stopping.is_set():
            self._publish({"type": "heartbeat", "worker_id": self.worker_id,
                           "endpoints": sorted(self.replicas)})
            time.sleep(self._heartbeat_s)

    def _supervise_loop(self) -> None:
        """Report replica crashes upstream (JobMonitor twin,
        ``comm_utils/job_monitor.py:37`` in the reference)."""
        while not self._stopping.is_set():
            for eid, rep in list(self.replicas.items()):
                rc = rep.proc.poll()
                if rc is not None:
                    # pop, not del: a concurrent undeploy may have removed
                    # the key already, and a KeyError here would silently
                    # kill supervision for every future replica
                    if self.replicas.pop(eid, None) is not None:
                        self._publish({"type": "replica_down",
                                       "worker_id": self.worker_id,
                                       "endpoint_id": eid, "rc": rc})
            time.sleep(0.5)

    def _publish(self, msg: Dict) -> None:
        # daemon side: raising in a heartbeat/handler thread would kill
        # the loop; master deploy timeouts cover a lost result
        self.publish_json(f"deploy/{self.cluster}/master", msg, best_effort=True)
