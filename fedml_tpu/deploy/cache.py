"""Endpoint cache — cross-process endpoint state table.

Parity target: ``model_scheduler/device_model_cache.py`` (redis hash of
deployment results/statuses per endpoint, idle-device pick, endpoint
tokens). Re-design: a JSON file with atomic replace + mtime-based reload,
readable by master, gateway, and CLI in separate processes — redis
without the dependency, at the scale a single deploy master handles.
"""
from __future__ import annotations

import contextlib
import fcntl
import json
import os
import secrets
import threading
import time
from typing import Any, Dict, List, Optional


class EndpointStatus:
    DEPLOYING = "DEPLOYING"
    DEPLOYED = "DEPLOYED"
    FAILED = "FAILED"
    OFFLINE = "OFFLINE"
    DELETED = "DELETED"


class EndpointCache:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._lock_path = self.path + ".lock"
        self._mtime = 0.0
        self._table: Dict[str, Dict[str, Any]] = {}
        self._reload_if_stale()

    @contextlib.contextmanager
    def _fs_lock(self):
        """Inter-process write lock: master, gateway, and CLI all mutate the
        table from separate processes; without flock a read-modify-write
        would silently erase another process's update."""
        fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # -- write ------------------------------------------------------------
    def upsert_endpoint(self, endpoint_id: str, *, endpoint_name: str,
                        model_name: str, model_version: int,
                        status: str, token: Optional[str] = None) -> Dict:
        with self._fs_lock(), self._lock:
            self._reload_locked()
            ep = self._table.setdefault(endpoint_id, {
                "endpoint_id": endpoint_id,
                "created_at": time.time(),
                "replicas": {},
            })
            ep.update({
                "endpoint_name": endpoint_name,
                "model_name": model_name,
                "model_version": int(model_version),
                "status": status,
            })
            if token is not None:
                ep["token"] = token
            self._persist_locked()
            return dict(ep)

    def set_status(self, endpoint_id: str, status: str) -> None:
        with self._fs_lock(), self._lock:
            self._reload_locked()
            if endpoint_id in self._table:
                self._table[endpoint_id]["status"] = status
                self._persist_locked()

    def set_replica(self, endpoint_id: str, worker_id: str, *,
                    url: Optional[str], status: str) -> None:
        with self._fs_lock(), self._lock:
            self._reload_locked()
            ep = self._table.get(endpoint_id)
            if ep is None:
                return
            ep.setdefault("replicas", {})[worker_id] = {
                "worker_id": worker_id,
                "url": url,
                "status": status,
                "updated_at": time.time(),
            }
            self._persist_locked()

    def delete_endpoint(self, endpoint_id: str) -> bool:
        with self._fs_lock(), self._lock:
            self._reload_locked()
            existed = self._table.pop(endpoint_id, None) is not None
            if existed:
                self._persist_locked()
            return existed

    # -- read -------------------------------------------------------------
    def get(self, endpoint_id: str) -> Optional[Dict[str, Any]]:
        self._reload_if_stale()
        ep = self._table.get(endpoint_id)
        return json.loads(json.dumps(ep)) if ep else None

    def list_endpoints(self) -> List[Dict[str, Any]]:
        self._reload_if_stale()
        return [json.loads(json.dumps(e)) for e in self._table.values()]

    def healthy_replicas(self, endpoint_id: str) -> List[Dict[str, Any]]:
        ep = self.get(endpoint_id)
        if not ep:
            return []
        return [r for r in ep.get("replicas", {}).values()
                if r.get("status") == EndpointStatus.DEPLOYED and r.get("url")]

    @staticmethod
    def new_token() -> str:
        return secrets.token_urlsafe(16)

    # -- persistence ------------------------------------------------------
    def _persist_locked(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._table, f)
        os.replace(tmp, self.path)
        try:
            self._mtime = os.stat(self.path).st_mtime
        except OSError:
            pass

    def _reload_if_stale(self) -> None:
        with self._lock:
            self._reload_locked()

    def _reload_locked(self) -> None:
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return
        if mtime == self._mtime:
            return
        try:
            with open(self.path) as f:
                self._table = json.load(f)
            self._mtime = mtime
        except (OSError, ValueError):
            pass  # mid-replace read; next call picks it up
