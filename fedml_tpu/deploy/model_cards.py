"""Model cards — the local model registry behind create/list/delete/deploy.

Parity target: ``model_scheduler/device_model_cards.py:24`` (CRUD over
``~/.fedml/fedml-model-client/fedml/models/<name>``, zip packaging, and
``serve_model_on_premise`` :37 kicking off deployment). Re-design: a
versioned directory registry + zip packaging into the object store; no
hosted ModelOps backend — deployment goes straight to the deploy master.

A model card is a directory containing ``model_config.yaml``:

    entry_module: my_predictor     # python file in the card (no .py)
    entry_class: MyPredictor       # FedMLPredictor subclass
    params: {...}                  # kwargs passed to the constructor

plus whatever code/weights the predictor needs. Builtin cards (no user
code) may instead specify ``builtin: llama`` with preset params.
"""
from __future__ import annotations

import json
import os
import shutil
import time
import zipfile
from typing import Any, Dict, List, Optional

import yaml

MODEL_CONFIG_FILE = "model_config.yaml"


class FedMLModelCards:
    def __init__(self, root: Optional[str] = None):
        self.root = os.path.abspath(
            root or os.path.join(os.path.expanduser("~"), ".fedml_tpu", "models")
        )
        os.makedirs(self.root, exist_ok=True)

    # -- CRUD -------------------------------------------------------------
    def create_model(self, name: str, workspace: str) -> Dict[str, Any]:
        """Register ``workspace`` as a new version of model ``name``.

        Reference: ``device_model_cards.py`` ``create_model``/
        ``add_model_files`` — recreating an existing card bumps its version.
        """
        self._check_name(name)
        cfg_path = os.path.join(workspace, MODEL_CONFIG_FILE)
        if not os.path.isfile(cfg_path):
            raise FileNotFoundError(
                f"model workspace must contain {MODEL_CONFIG_FILE}: {workspace}")
        with open(cfg_path) as f:
            cfg = yaml.safe_load(f) or {}
        if "builtin" not in cfg and (
                "entry_module" not in cfg or "entry_class" not in cfg):
            raise ValueError(
                f"{MODEL_CONFIG_FILE} needs either 'builtin' or "
                f"'entry_module' + 'entry_class'")
        version = self._next_version(name)
        dst = self._version_dir(name, version)
        shutil.copytree(workspace, dst)
        card = {
            "model_name": name,
            "model_version": version,
            "created_at": time.time(),
            "config": cfg,
        }
        with open(os.path.join(dst, "card.json"), "w") as f:
            json.dump(card, f)
        return card

    def list_models(self) -> List[Dict[str, Any]]:
        out = []
        for name in sorted(os.listdir(self.root)):
            versions = self.list_versions(name)
            if versions:
                out.append({
                    "model_name": name,
                    "versions": versions,
                    "latest": versions[-1],
                })
        return out

    def list_versions(self, name: str) -> List[int]:
        d = os.path.join(self.root, name)
        if not os.path.isdir(d):
            return []
        return sorted(
            int(v[1:]) for v in os.listdir(d)
            if v.startswith("v") and v[1:].isdigit()
        )

    def get_card(self, name: str, version: Optional[int] = None) -> Dict[str, Any]:
        version = version or self._latest_version(name)
        path = os.path.join(self._version_dir(name, version), "card.json")
        with open(path) as f:
            return json.load(f)

    def delete_model(self, name: str, version: Optional[int] = None) -> bool:
        self._check_name(name)
        if version is None:
            d = os.path.join(self.root, name)
        else:
            d = self._version_dir(name, version)
        if not os.path.isdir(d):
            return False
        shutil.rmtree(d)
        return True

    # -- packaging --------------------------------------------------------
    def package(self, name: str, version: Optional[int] = None,
                out_dir: Optional[str] = None) -> str:
        """Zip a card version for shipping to a deploy worker (the
        reference's build step before the S3 upload)."""
        version = version or self._latest_version(name)
        src = self._version_dir(name, version)
        out_dir = out_dir or self.root
        zip_path = os.path.join(out_dir, f"{name}-v{version}.zip")
        with zipfile.ZipFile(zip_path, "w", zipfile.ZIP_DEFLATED) as z:
            for base, _, files in os.walk(src):
                for fn in files:
                    full = os.path.join(base, fn)
                    z.write(full, os.path.relpath(full, src))
        return zip_path

    @staticmethod
    def unpack(zip_path: str, dst: str) -> str:
        os.makedirs(dst, exist_ok=True)
        with zipfile.ZipFile(zip_path) as z:
            for info in z.infolist():
                # zip-slip guard: entries must stay under dst
                target = os.path.realpath(os.path.join(dst, info.filename))
                if not target.startswith(os.path.realpath(dst) + os.sep):
                    raise ValueError(f"zip entry escapes target: {info.filename}")
            z.extractall(dst)
        return dst

    # -- internals --------------------------------------------------------
    def _check_name(self, name: str) -> None:
        if not name or "/" in name or name.startswith("."):
            raise ValueError(f"bad model name: {name!r}")

    def _version_dir(self, name: str, version: int) -> str:
        return os.path.join(self.root, name, f"v{version}")

    def _latest_version(self, name: str) -> int:
        versions = self.list_versions(name)
        if not versions:
            raise FileNotFoundError(f"no such model card: {name}")
        return versions[-1]

    def _next_version(self, name: str) -> int:
        versions = self.list_versions(name)
        return (versions[-1] + 1) if versions else 1
