"""Deploy master — orchestrates endpoint deployments across workers.

Parity target: ``model_scheduler/device_server_runner.py`` (deploy master
agent: dispatches deployments to worker agents, aggregates results,
maintains endpoint state) + the scheduling half of
``device_model_cards.py:37`` ``serve_model_on_premise``. Re-design: the
master holds a worker registry fed by broker heartbeats, ships model
packages via the object store, and writes endpoint state into the
JSON-file EndpointCache that the gateway and CLI read.
"""
from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
import uuid
from typing import Dict, List, Optional

from fedml_tpu.core.distributed.communication.broker_agent import (
    BrokerJsonAgent,
    PeerRegistry,
)
from fedml_tpu.core.distributed.communication.object_store import ObjectStore
from fedml_tpu.deploy.cache import EndpointCache, EndpointStatus
from fedml_tpu.deploy.model_cards import FedMLModelCards

logger = logging.getLogger(__name__)


class DeployMaster(BrokerJsonAgent):
    def __init__(self, broker_host: str, broker_port: int, store: ObjectStore,
                 cache: EndpointCache, cards: Optional[FedMLModelCards] = None,
                 cluster: str = "default", worker_timeout_s: float = 6.0,
                 health_interval_s: float = 1.0):
        super().__init__(broker_host, broker_port)
        self.cluster = cluster
        self.store = store
        self.cache = cache
        self.cards = cards or FedMLModelCards()
        self.registry = PeerRegistry(worker_timeout_s)
        self._results: Dict[str, Dict[str, Dict]] = {}  # eid → worker → result
        self._events: Dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self.subscribe_json(f"deploy/{cluster}/master", self._on_message)
        self._health_interval_s = health_interval_s
        self._health_started = False

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "DeployMaster":
        if not self._health_started:
            self._health_started = True
            self.spawn_loop(self._health_loop)
        return self

    def shutdown(self) -> None:
        self.stop_agent()

    # -- worker registry --------------------------------------------------
    def live_workers(self) -> List[str]:
        return self.registry.live()

    def wait_for_workers(self, n: int, timeout: float = 30.0) -> List[str]:
        return self.registry.wait_for(n, timeout, what="deploy workers")

    # -- deployment API ---------------------------------------------------
    def deploy(self, model_name: str, *, endpoint_name: Optional[str] = None,
               version: Optional[int] = None, n_replicas: int = 1,
               workers: Optional[List[str]] = None, timeout: float = 180.0,
               with_token: bool = False) -> Dict:
        """Deploy a model card to ``n_replicas`` workers.

        Raises if NO replica came up; otherwise returns the endpoint
        record (possibly degraded — replicas that failed or never
        reported inside ``timeout`` are marked FAILED in the cache)."""
        card = self.cards.get_card(model_name, version)
        version = card["model_version"]
        endpoint_id = uuid.uuid4().hex[:12]
        endpoint_name = endpoint_name or f"{model_name}-{endpoint_id[:4]}"

        targets = workers or self._pick_workers(n_replicas)
        token = EndpointCache.new_token() if with_token else None
        self.cache.upsert_endpoint(
            endpoint_id, endpoint_name=endpoint_name, model_name=model_name,
            model_version=version, status=EndpointStatus.DEPLOYING,
            token=token)

        zip_path = self.cards.package(model_name, version)
        key = self.store.new_key(f"deploy/{endpoint_id}")
        with open(zip_path, "rb") as f:
            # returned key is authoritative (CAS backends return a CID)
            key = self.store.put_object(key, f.read())

        event = threading.Event()
        with self._lock:
            self._results[endpoint_id] = {}
            self._events[endpoint_id] = event
        for wid in targets:
            self.cache.set_replica(endpoint_id, wid, url=None,
                                   status=EndpointStatus.DEPLOYING)
            self._send(wid, {"type": "deploy", "endpoint_id": endpoint_id,
                             "model_name": model_name,
                             "model_version": version, "package_key": key})

        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                results = dict(self._results.get(endpoint_id, {}))
            if len(results) == len(targets):
                break
            event.wait(timeout=0.2)
            event.clear()
        with self._lock:
            results = self._results.pop(endpoint_id, {})
            self._events.pop(endpoint_id, None)
        self.store.delete_object(key)

        # a target that never reported is a failed replica, not a phantom
        # left DEPLOYING forever (the health loop only polls replicas that
        # have a url)
        for wid in targets:
            if wid not in results:
                self.cache.set_replica(endpoint_id, wid, url=None,
                                       status=EndpointStatus.FAILED)
        ok = [w for w, r in results.items() if r.get("ok")]
        status = EndpointStatus.DEPLOYED if ok else EndpointStatus.FAILED
        self.cache.set_status(endpoint_id, status)
        record = self.cache.get(endpoint_id)
        if not ok:
            errors = {w: r.get("error") for w, r in results.items()}
            raise RuntimeError(
                f"deployment of {model_name} failed on all workers: {errors}"
                if results else
                f"deployment of {model_name} timed out after {timeout}s "
                f"(targets {targets})")
        if len(ok) < len(targets):
            logger.warning(
                "endpoint %s deployed degraded: %d/%d replicas ok",
                endpoint_id, len(ok), len(targets))
        return record

    def undeploy(self, endpoint_id: str) -> bool:
        ep = self.cache.get(endpoint_id)
        if ep is None:
            return False
        for wid in ep.get("replicas", {}):
            self._send(wid, {"type": "undeploy", "endpoint_id": endpoint_id})
        self.cache.delete_endpoint(endpoint_id)
        return True

    def list_endpoints(self) -> List[Dict]:
        return self.cache.list_endpoints()

    # -- internals --------------------------------------------------------
    def _pick_workers(self, n: int) -> List[str]:
        live = self.live_workers()
        if len(live) < n:
            raise RuntimeError(
                f"need {n} workers, only {len(live)} online: {live}")
        # least-loaded first (reference: scheduler_matcher / idle-device
        # pick in device_model_cache.get_idle_device), respecting each
        # worker's advertised capacity
        load: Dict[str, int] = {w: 0 for w in live}
        for ep in self.cache.list_endpoints():
            for wid, rep in ep.get("replicas", {}).items():
                # FAILED/OFFLINE replicas run no process — they must not
                # eat capacity forever
                if wid in load and rep.get("status") in (
                        EndpointStatus.DEPLOYED, EndpointStatus.DEPLOYING):
                    load[wid] += 1
        caps = {w: int(self.registry.get(w).get("capacity", 4))
                for w in live}
        free = [w for w in live if load[w] < caps[w]]
        if len(free) < n:
            raise RuntimeError(
                f"need {n} workers with free capacity, only {len(free)} "
                f"available (load {load}, caps {caps})")
        return sorted(free, key=lambda w: (load[w], w))[:n]

    def _send(self, worker_id: str, msg: Dict) -> None:
        self.publish_json(f"deploy/{self.cluster}/worker/{worker_id}", msg)

    def _on_message(self, msg: Dict) -> None:
        mtype = msg.get("type")
        wid = str(msg.get("worker_id", ""))
        if mtype in ("worker_online", "heartbeat"):
            if "capacity" in msg:
                self.registry.touch(wid, capacity=int(msg["capacity"]))
            else:
                self.registry.touch(wid)
        elif mtype == "deploy_result":
            eid = str(msg["endpoint_id"])
            self.cache.set_replica(
                eid, wid, url=msg.get("url"),
                status=(EndpointStatus.DEPLOYED if msg.get("ok")
                        else EndpointStatus.FAILED))
            with self._lock:
                if eid in self._results:
                    self._results[eid][wid] = msg
                event = self._events.get(eid)
            if event is not None:
                event.set()
        elif mtype == "replica_down":
            eid = str(msg["endpoint_id"])
            self.cache.set_replica(eid, wid, url=None,
                                   status=EndpointStatus.OFFLINE)
            if not self.cache.healthy_replicas(eid):
                self.cache.set_status(eid, EndpointStatus.OFFLINE)
        elif mtype == "undeploy_result":
            pass  # cache entry already dropped in undeploy()

    def _health_loop(self) -> None:
        """Poll replica /ready and flip statuses — the reference's
        ``device_model_monitor.py`` / JobMonitor endpoint liveness."""
        while not self._stopping.is_set():
            for ep in self.cache.list_endpoints():
                eid = ep["endpoint_id"]
                healthy = 0
                for wid, rep in ep.get("replicas", {}).items():
                    url = rep.get("url")
                    if not url or rep.get("status") not in (
                            EndpointStatus.DEPLOYED, EndpointStatus.OFFLINE):
                        continue
                    ok = False
                    try:
                        with urllib.request.urlopen(url + "/ready",
                                                    timeout=2) as r:
                            ok = bool(json.loads(r.read()).get("ready"))
                    except (OSError, ValueError):
                        ok = False
                    if ok:
                        healthy += 1
                    new = (EndpointStatus.DEPLOYED if ok
                           else EndpointStatus.OFFLINE)
                    if new != rep.get("status"):
                        self.cache.set_replica(eid, wid, url=url, status=new)
                if ep.get("status") in (EndpointStatus.DEPLOYED,
                                        EndpointStatus.OFFLINE):
                    new_ep = (EndpointStatus.DEPLOYED if healthy
                              else EndpointStatus.OFFLINE)
                    if new_ep != ep.get("status"):
                        self.cache.set_status(eid, new_ep)
            time.sleep(self._health_interval_s)
