"""Model-deploy control plane — the "Deploy" quarter of the product.

Parity target: the reference's ``computing/scheduler/model_scheduler/``
(its single largest subsystem, ~10.1k LoC): model cards CRUD +
``serve_model_on_premise`` (``device_model_cards.py:24,:37``), deploy
master/worker agents (``device_server_runner.py``,
``device_client_runner.py``), the deployment executor
(``device_model_deployment.py:528`` — docker/Triton there), the FastAPI
inference gateway with per-endpoint routing/auth/metrics
(``device_model_inference.py:52-132``), and the redis endpoint cache
(``device_model_cache.py``).

TPU-native re-design:

- the container boundary (docker/Triton) becomes a **subprocess with its
  own JAX/XLA runtime** (one process per endpoint replica ⇒ one TPU
  client per replica; XLA owns the chip, so co-locating replicas in one
  process would serialize them anyway);
- the MQTT control plane is the in-tree broker transport; model packages
  ride the object store (the S3 seam);
- redis becomes a JSON-file endpoint cache readable across processes
  (gateway, master, CLI);
- the gateway is a stdlib threading HTTP server (no ASGI stack in this
  environment) that proxies ``/inference/{endpoint_id}`` to healthy
  replicas with per-endpoint metrics and failure-driven re-routing.
"""
from fedml_tpu.deploy.cache import EndpointCache, EndpointStatus
from fedml_tpu.deploy.gateway import InferenceGateway
from fedml_tpu.deploy.master import DeployMaster
from fedml_tpu.deploy.model_cards import FedMLModelCards
from fedml_tpu.deploy.worker import DeployWorkerAgent

__all__ = [
    "DeployMaster",
    "DeployWorkerAgent",
    "EndpointCache",
    "EndpointStatus",
    "FedMLModelCards",
    "InferenceGateway",
]
