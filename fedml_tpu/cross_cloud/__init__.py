"""Cross-cloud ("Cheetah") engine — multi-cloud federated training.

Parity target: ``python/fedml/cross_cloud/`` (client/server managers,
``__init__.py:392`` ``_init_cross_cloud``) — the reference's Cheetah runs
the cross-silo horizontal protocol where each "silo" is a cloud GPU
cluster. TPU-native re-design:

- a silo = a cloud TPU slice. Each silo process initializes the
  multi-host runtime for ITS slice (``parallel/multihost.py``, env
  FEDML_COORDINATOR_ADDRESS/...), so local training shards over the
  whole slice via the existing NamedSharding paths;
- federation across clouds rides whichever transport each silo can
  reach (broker over TCP/DCN, gRPC), with **per-silo overrides** from
  ``data_silo_config`` yamls (``arguments.update_client_specific_args``)
  — each cloud brings its own broker address, batch size, data paths;
- the round FSM is exactly the cross-silo one: the protocol does not
  change because the silos live in different clouds, only the transport
  configuration and the compute inside each silo do.
"""
from __future__ import annotations

from typing import Any

from fedml_tpu.cross_silo.client.client import Client
from fedml_tpu.cross_silo.server.server import Server


class CloudServer(Server):
    """Cross-cloud aggregation server (cross-silo FSM; cloud silos)."""


class CloudClient(Client):
    """One cloud silo: multi-host slice compute + federation transport.

    ``fedml_tpu.init`` has already applied this silo's override yaml and
    initialized the slice runtime by the time this constructor runs; the
    Client base then builds the trainer adapter (sharded over every
    device the runtime exposes) and the wire manager from the
    (overridden) transport args.
    """

    def __init__(self, args: Any, device: Any, dataset: Any, model: Any,
                 client_trainer=None):
        super().__init__(args, device, dataset, model, client_trainer)


__all__ = ["CloudClient", "CloudServer"]
