"""FedLLM — federated LoRA fine-tuning round loop (the flagship config).

Parity target: ``python/spotlight_prj/fedllm/run_fedllm.py`` (the reference's
FedLLM app: cross-silo FedAvg over peft adapters). This is the simulation
analogue: N clients share one compiled engine (sequential local training, the
``sp`` backend shape — ``simulation/sp/fedavg/fedavg_api.py:66``), exchanging
LoRA dicts; aggregation is a weighted tree-average. The cross-silo engine
runs the same trainer/aggregator pair over a real transport.

BASELINE.md config #4: Llama-2-7B LoRA, 8 clients, FSDP+TP mesh.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List

import numpy as np

from fedml_tpu.core.mlops.event import MLOpsProfilerEvent
from fedml_tpu.data.dataset import FederatedDataset
from fedml_tpu.models.llm.llama import LlamaConfig
from fedml_tpu.simulation.sampling import sample_clients
from fedml_tpu.train.llm.federated import LLMAggregator, LLMClientTrainer

logger = logging.getLogger(__name__)


class FedLLMAPI:
    """Round loop: sample clients → local LoRA steps → weighted average."""

    def __init__(self, args: Any, device: Any, dataset: FederatedDataset,
                 cfg: LlamaConfig = None, mesh=None):
        self.args = args
        self.dataset = dataset
        self.cfg = cfg or LlamaConfig.from_args(args, vocab_size=dataset.class_num)
        # one engine serves every simulated client (params are swapped in);
        # this is exactly the reference's sp-backend memory model
        self.client = LLMClientTrainer(self.cfg, args, mesh=mesh)
        self.aggregator = LLMAggregator(
            self.cfg, args, mesh=mesh, engine=self.client.engine
        )
        self.global_exchange = self.aggregator.get_init_params()
        self.event = MLOpsProfilerEvent(args)
        self.test_history: List[dict] = []
        # on_device_round: true fuses the ENTIRE round (client-switch,
        # local steps, LoRA FedAvg) into one donated-buffer XLA program —
        # see LLMTrainer.compile_federated_round. The trust-stack hooks
        # intercept per-client payloads on the host, which that program
        # bypasses, so the two are mutually exclusive by construction.
        self.on_device = bool(getattr(args, "on_device_round", False))
        self._fed_round = None
        self._fed_round_key = None
        if self.on_device:
            self._check_no_host_hooks()

    def _check_no_host_hooks(self) -> None:
        from fedml_tpu.core.dp.fedml_differential_privacy import (
            FedMLDifferentialPrivacy,
        )
        from fedml_tpu.core.fhe.fhe_agg import FedMLFHE
        from fedml_tpu.core.security.attacker import FedMLAttacker
        from fedml_tpu.core.security.defender import FedMLDefender

        active = [
            name
            for name, on in (
                ("attack", FedMLAttacker.get_instance().is_attack_enabled()),
                ("defense", FedMLDefender.get_instance().is_defense_enabled()),
                ("dp", FedMLDifferentialPrivacy.get_instance().is_dp_enabled()),
                ("fhe", FedMLFHE.get_instance().is_fhe_enabled()),
            )
            if on
        ]
        if active:
            raise ValueError(
                f"on_device_round: true is incompatible with host-side "
                f"trust-stack hooks (active: {', '.join(active)}) — the "
                f"fused round never surfaces per-client payloads to the "
                f"host; disable the hooks or drop on_device_round")

    def _train_one_round_on_device(self, round_idx: int) -> Dict:
        """The fused-round fast path: one XLA program per round."""
        engine = self.client.engine
        client_ids = sample_clients(self.args, round_idx)
        batch = engine.batch_size
        steps = int(getattr(self.args, "local_steps_per_round", 0) or 0)
        if steps <= 0:
            # default: one optimizer step per local epoch, each on a fresh
            # random batch (the fixed-shape SPMD analogue of an epoch sweep)
            steps = int(getattr(self.args, "epochs", 1))
        key = (len(client_ids), steps)
        if self._fed_round_key != key:
            self._fed_round = engine.compile_federated_round(*key)
            self._fed_round_key = key

        xs = np.zeros((len(client_ids), steps, batch, engine.seq_len), np.int32)
        ys = np.zeros_like(xs)
        ms = np.ones((len(client_ids), steps, batch), np.float32)
        weights = np.zeros((len(client_ids),), np.float32)
        rng = np.random.default_rng(
            int(getattr(self.args, "random_seed", 0)) * 9973 + round_idx)
        for i, cid in enumerate(client_ids):
            x, y = self.dataset.train_data_local_dict[cid]
            x, y = np.asarray(x), np.asarray(y)
            idx = rng.integers(0, x.shape[0], size=(steps, batch))
            xs[i], ys[i] = x[idx], y[idx]
            weights[i] = float(self.dataset.train_data_local_num_dict[cid])

        self.event.log_event_started("round", round_idx)
        t0 = time.time()
        engine.params, engine.opt_state, self.global_exchange, loss = (
            self._fed_round(engine.params, engine.opt_state,
                            self.global_exchange, xs, ys, ms, weights))
        loss = float(loss)  # jit returns futures: block BEFORE stopping t
        dt = time.time() - t0
        self.event.log_event_ended("round", round_idx)
        report = {"round": round_idx, "round_sec": dt, "train_loss": loss}
        self._maybe_test_and_checkpoint(round_idx, report)
        return report

    def train_one_round(self, round_idx: int) -> Dict:
        if self.on_device:
            return self._train_one_round_on_device(round_idx)
        client_ids = sample_clients(self.args, round_idx)
        payloads = []
        self.event.log_event_started("round", round_idx)
        t0 = time.time()
        for cid in client_ids:
            self.client.set_id(cid)
            self.client.set_round(round_idx)
            data = self.dataset.train_data_local_dict[cid]
            # run_local_training = attack/DP/FHE hook chain around train()
            updated, _metrics = self.client.run_local_training(
                self.global_exchange, data, None, self.args
            )
            n = self.dataset.train_data_local_num_dict[cid]
            payloads.append((float(n), updated))
        # full ServerAggregator hook chain: defense/DP before-hooks,
        # defense-wrapped FedMLAggOperator, central-DP/contribution after
        model_list, _ = self.aggregator.on_before_aggregation(payloads)
        self.global_exchange = self.aggregator.aggregate(model_list)
        self.global_exchange = self.aggregator.on_after_aggregation(
            self.global_exchange
        )
        dt = time.time() - t0
        self.event.log_event_ended("round", round_idx)

        report = {"round": round_idx, "round_sec": dt}
        self._maybe_test_and_checkpoint(round_idx, report)
        return report

    def _maybe_test_and_checkpoint(self, round_idx: int, report: Dict) -> None:
        freq = int(getattr(self.args, "frequency_of_the_test", 1))
        if round_idx % max(freq, 1) == 0 or round_idx == int(
            getattr(self.args, "comm_round", 1)
        ) - 1:
            metrics = self.aggregator.test(
                self.global_exchange, self.dataset.test_data_global, None, self.args
            )
            report.update(metrics)
            self.test_history.append(report)
            logger.info("fedllm round %d: %s", round_idx, metrics)
        ckpt_dir = getattr(self.args, "checkpoint_dir", None)
        every = int(getattr(self.args, "save_every_rounds", 0) or 0)
        if ckpt_dir and every and round_idx % every == 0:
            self.aggregator.save_round(str(ckpt_dir), round_idx)

    def train(self) -> Dict:
        t0 = time.time()
        rounds = int(getattr(self.args, "comm_round", 1))
        for r in range(rounds):
            self.train_one_round(r)
        wall = time.time() - t0
        final = self.test_history[-1] if self.test_history else {}
        return {
            "wall_clock_sec": wall,
            "rounds": rounds,
            "rounds_per_sec": rounds / max(wall, 1e-9),
            **final,
        }
