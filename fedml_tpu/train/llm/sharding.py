"""Mesh + partition rules for the LLM path — DeepSpeed ZeRO-3 replaced by
``jax.sharding``.

Parity target: the reference's LLM distribution is DeepSpeed ZeRO-3 via HF
Trainer (``train/llm/distributed.py:8-64`` barrier/gather_parameter over
``deepspeed.comm``). TPU-native re-design (SURVEY §2.10): a named device
mesh with axes

    dp    — pure data parallelism (params replicated)
    fsdp  — ZeRO-3-style parameter/optimizer sharding (params split, batch split)
    ep    — expert parallelism (MoE expert dim split; XLA inserts the
            dispatch/combine all-to-alls)
    tp    — megatron-style tensor parallelism (heads/mlp/vocab split)
    sp    — sequence/context parallelism (ring attention, fedml_tpu/parallel)

Model code never mentions these axes: layers annotate *logical* axes
("embed", "heads", "mlp", "vocab") via ``nn.with_logical_partitioning``;
the rules below map logical→mesh, and XLA inserts the all-gathers /
reduce-scatters that DeepSpeed does by hand.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from flax import linen as nn
from flax.core import meta as flax_meta
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

# logical axis → mesh axis (None = replicated). "embed" rides fsdp so every
# weight matrix has exactly one fsdp-sharded dimension → ZeRO-3 memory
# scaling; "heads"/"mlp"/"vocab" ride tp.
LOGICAL_RULES: Sequence[Tuple[str, Any]] = (
    ("batch", ("dp", "fsdp")),
    ("seq", "sp"),
    ("embed", "fsdp"),
    ("heads", "tp"),
    ("mlp", "tp"),
    ("vocab", "tp"),
    ("expert", "ep"),
)


def make_mesh(
    dp: int = 1,
    fsdp: int = -1,
    tp: int = 1,
    sp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, fsdp, ep, tp, sp) mesh; ``fsdp=-1`` absorbs the
    remainder.

    Axis order puts tp/sp innermost so they land on the fastest ICI hops;
    ep sits between fsdp and tp so expert all-to-alls stay within a slice.
    The ep axis always exists (size 1 when unused) so downstream sharding
    code never branches on mesh rank.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if fsdp == -1:
        fsdp = n // max(dp * tp * sp * ep, 1)
    assert dp * fsdp * ep * tp * sp == n, (
        f"mesh {dp}x{fsdp}x{ep}x{tp}x{sp} != {n} devices"
    )
    arr = np.asarray(devices).reshape(dp, fsdp, ep, tp, sp)
    return Mesh(arr, axis_names=("dp", "fsdp", "ep", "tp", "sp"))


def mesh_from_args(args: Any, devices=None) -> Mesh:
    return make_mesh(
        dp=int(getattr(args, "mesh_dp", 1)),
        fsdp=int(getattr(args, "mesh_fsdp", -1)),
        tp=int(getattr(args, "mesh_tp", 1)),
        sp=int(getattr(args, "mesh_sp", 1)),
        ep=int(getattr(args, "mesh_ep", 1)),
        devices=devices,
    )


def logical_shardings(abstract_tree: Any, mesh: Mesh) -> Any:
    """NamedShardings for a tree of ``nn.Partitioned``-annotated leaves."""
    specs = nn.get_partition_spec(abstract_tree)
    return nn.logical_to_mesh_sharding(specs, mesh, LOGICAL_RULES)


def unbox(tree: Any) -> Any:
    """Strip flax Partitioned metadata boxes → plain pytree of arrays."""
    return flax_meta.unbox(tree)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(("dp", "fsdp")))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def init_sharded_params(model, sample_tokens, mesh: Mesh, seed: int = 0,
                        zeros: bool = False):
    """Initialise parameters *already sharded* — no host-side full copy.

    Returns (params, shardings) with metadata boxes stripped.

    ``zeros=True`` skips the random-init program and materializes every
    leaf as a sharded zeros buffer (a memset, seconds instead of minutes
    at 7B scale on a CPU mesh) — for dryruns that validate the sharded
    train program's compile+execute, not training statistics.
    """
    key = jax.random.key(seed)
    abstract = jax.eval_shape(model.init, key, sample_tokens)
    shardings = logical_shardings(abstract, mesh)
    if zeros:
        ab, sh = unbox(abstract), unbox(shardings)
        import jax.numpy as jnp

        zeros_fn = jax.jit(
            lambda: jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), ab),
            out_shardings=sh)
        return zeros_fn(), sh
    init_fn = jax.jit(model.init, out_shardings=shardings)
    params = init_fn(key, sample_tokens)
    return unbox(params), unbox(shardings)
