"""Federated LLM fine-tuning — the FedLLM spotlight, TPU-native.

Parity target: ``python/spotlight_prj/fedllm/run_fedllm.py`` — ``LLMTrainer``
(:246) / ``LLMAggregator`` (:460) binding ``fedml.train.llm`` into the
``ClientTrainer``/``ServerAggregator`` frame, with per-round checkpoints
(:171) and DeepSpeed process-group sync (:435).

TPU re-design: each client runs the compiled sharded train step from
``trainer.py`` over its own token shard; when LoRA is on, ONLY the adapter
dict crosses the federation transport (the reference ships peft state
dicts the same way), so a 7B base model federates with ~0.1% of the
traffic of full FedAvg. The exchanged payload is the flat
``{path: array}`` dict from :func:`extract_lora`, which the generic
``FedMLAggOperator`` treats as just another pytree.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

import numpy as np

from fedml_tpu.core.alg_frame.client_trainer import ClientTrainer
from fedml_tpu.core.alg_frame.server_aggregator import ServerAggregator
from fedml_tpu.models.llm.llama import LlamaConfig
from fedml_tpu.train.llm.trainer import LLMTrainer

logger = logging.getLogger(__name__)

Pytree = Any


class LLMClientTrainer(ClientTrainer):
    """ClientTrainer over the sharded LLM train step.

    ``train(params, train_data, device, args)`` consumes the *exchangeable*
    params (LoRA dict or full tree), runs ``args.epochs`` of local steps,
    and returns the updated exchangeable params.
    """

    def __init__(self, cfg: LlamaConfig, args: Any, mesh=None):
        super().__init__(model=None, args=args)
        self.engine = LLMTrainer(cfg, args, mesh=mesh)
        self.engine.init(seed=int(getattr(args, "random_seed", 0)))
        self.lora_only = self.engine.lora_only
        self._round_seed = 0

    # engine-contract hooks: shapes are already static (fixed [B, T] token
    # batches), so pad_to_batches is a no-op; the round index seeds shuffling
    def set_pad_to_batches(self, n) -> None:
        pass

    def set_round(self, round_idx: int) -> None:
        self._round_seed = int(round_idx)

    def get_exchange_params(self) -> Pytree:
        # fresh buffers (the train step donates params); host numpy when
        # the silo mesh spans processes — see LLMTrainer.exchange_state
        return self.engine.exchange_state()

    def set_exchange_params(self, exchanged: Pytree) -> None:
        self.engine.load_exchange_state(exchanged)

    def train(self, params: Pytree, train_data, device, args) -> Tuple[Pytree, Dict]:
        """ClientTrainer contract: (new_exchange_params, metrics)."""
        self.set_exchange_params(params)
        x, y = train_data
        x = np.asarray(x)
        y = np.asarray(y)
        batch = self.engine.batch_size
        epochs = int(getattr(args, "epochs", 1))
        seed = (int(getattr(args, "random_seed", 0)) * 9973 + self.id * 1009
                + self._round_seed)
        rng = np.random.default_rng(seed)
        n = x.shape[0]
        losses = []
        for _ in range(epochs):
            order = rng.permutation(n)
            for i in range(0, n, batch):
                idx = order[i : i + batch]
                m = np.ones((batch,), np.float32)
                if len(idx) < batch:
                    # pad the trailing partial batch; mask=0 rows are no-ops
                    # (same pad+mask discipline as data/dataset.batch_epochs)
                    m[len(idx):] = 0.0
                    idx = np.concatenate(
                        [idx, np.full(batch - len(idx), idx[0] if len(idx) else 0)]
                    ).astype(idx.dtype)
                losses.append(self.engine.step(x[idx], y[idx], m))
        self.local_sample_number = n
        metrics = {"train_loss": float(np.mean(losses)) if losses else 0.0,
                   "train_samples": float(n)}
        return self.get_exchange_params(), metrics

    def test(self, params: Pytree, test_data, device, args) -> Dict:
        self.set_exchange_params(params)
        x, y = test_data
        n = min(len(x), self.engine.batch_size * 8)
        return self.engine.evaluate(np.asarray(x[:n]), np.asarray(y[:n]))


class LLMAggregator(ServerAggregator):
    """ServerAggregator for LLM federation — aggregates the exchange dict.

    The payloads are flat ``{path: array}`` dicts (or full pytrees); both
    are pytrees, so the defense/DP hook chain and ``FedMLAggOperator``
    apply unchanged. Reference: ``run_fedllm.py:460`` LLMAggregator.
    """

    def __init__(self, cfg: LlamaConfig, args: Any, mesh=None,
                 engine: Optional[LLMTrainer] = None):
        super().__init__(model=None, args=args)
        self.engine = engine or LLMTrainer(cfg, args, mesh=mesh)
        if self.engine.params is None:
            self.engine.init(seed=int(getattr(args, "random_seed", 0)))
        self.lora_only = self.engine.lora_only

    def get_init_params(self) -> Pytree:
        return self.engine.exchange_state()

    def set_global_params(self, exchanged: Pytree) -> None:
        self.engine.load_exchange_state(exchanged)

    def test(self, params: Pytree, test_data, device, args) -> Dict:
        self.set_global_params(params)
        x, y = test_data
        n = min(len(x), self.engine.batch_size * 8)
        metrics = self.engine.evaluate(np.asarray(x[:n]), np.asarray(y[:n]))
        return {"test_loss": metrics["eval_loss"], "test_acc": metrics["eval_acc"]}

    def save_round(self, ckpt_dir: str, round_idx: int) -> str:
        return self.engine.save_checkpoint(ckpt_dir, round_idx)
