"""Typed configuration for the LLM fine-tuning kit.

Parity target: ``train/llm/configurations.py`` in the reference
(``ExperimentArguments`` :31, ``ModelArguments`` :140, ``DatasetArguments``
:326, ``get_peft_config`` :291) — HF ``dataclass`` argument groups, re-cut
for the JAX path: model selection is a :class:`LlamaConfig` preset, the
DeepSpeed block is replaced by mesh axis sizes, and truncation/packing are
explicit because XLA needs static shapes.
"""
from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class ModelArguments:
    model_name: str = "tiny"          # LlamaConfig preset name
    lora_rank: int = 8
    lora_alpha: float = 16.0
    use_flash_attention: bool = True
    gradient_checkpointing: bool = True
    dtype: str = "bfloat16"


@dataclasses.dataclass
class DatasetArguments:
    dataset: str = "synthetic_lm"
    max_seq_length: int = 512          # reference: truncation_max_length (:530)
    vocab_size: int = 256
    train_size: int = 2048
    test_size: int = 256


@dataclasses.dataclass
class ExperimentArguments:
    learning_rate: float = 1e-4
    weight_decay: float = 0.0
    warmup_steps: int = 0
    max_steps: int = 100
    per_device_batch_size: int = 8
    gradient_accumulation_steps: int = 1
    seed: int = 0
    output_dir: str = "./outputs"
    save_every_rounds: int = 1
    # mesh (replaces the reference's deepspeed json)
    mesh_dp: int = 1
    mesh_fsdp: int = -1
    mesh_tp: int = 1
    mesh_sp: int = 1

    @property
    def global_batch_size(self) -> int:
        return self.per_device_batch_size * self.gradient_accumulation_steps


def from_args(args: Any):
    """Build the three argument groups from a flat fedml-style args bag."""

    def pick(cls):
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in vars(args).items() if k in fields and v is not None}
        return cls(**kw)

    return pick(ModelArguments), pick(DatasetArguments), pick(ExperimentArguments)
