"""LLM fine-tuning kit (UnitedLLM-equivalent): sharded trainer, LoRA,
federated binding. Parity: reference ``python/fedml/train/llm/``."""
from fedml_tpu.train.llm.configurations import (  # noqa: F401
    DatasetArguments,
    ExperimentArguments,
    ModelArguments,
)
from fedml_tpu.train.llm.sharding import (  # noqa: F401
    LOGICAL_RULES,
    make_mesh,
    mesh_from_args,
)
from fedml_tpu.train.llm.trainer import (  # noqa: F401
    LLMTrainer,
    extract_lora,
    merge_lora,
)
